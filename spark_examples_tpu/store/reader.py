"""StoreSource: the catalog as a GenotypeSource, with verified reads.

The read path is tiered:

1. **disk** — each chunk file is ``np.memmap``-ed read-only (zero-copy:
   the packed bytes page in on demand and a packed-transport consumer
   ships slices of the mapping straight to ``device_put``);
2. **decode cache** — dense int8 decodes of hot chunks, bounded host
   RAM with hit/miss accounting (store/cache.py);
3. the consumer: ``blocks`` / ``packed_blocks`` re-grid chunks into any
   requested block width (never spanning a contig), ``range_source``
   answers contig/variant/position range queries off the catalog, and
   cursors resume deterministically — the drop-in contract every job
   surface (runner, streaming, serve staging) already assumes.

**Integrity**: a chunk's filename is its sha256. On first touch per
reader the bytes are re-hashed against the address (``store.read``
fault site fires first, so the chaos harness can corrupt or fail the
read deterministically). A mismatch or truncation first attempts an
in-place **heal** (store/heal.py): a verified copy from a peer replica
directory, else a re-compaction of the chunk's origin span when the
manifest records one — degradation instead of fail-fast. Only when no
route repairs it is the chunk quarantined — recorded in
``<store>/quarantine.json`` (atomic, idempotent — store/quarantine.py),
counted, and raised as :class:`StoreCorruptError` naming the resume
cursor. Corruption is damage, not weather: the retry layer
(ingest/resilient.py) retries transient ``IOError`` s around this path
but never a quarantined chunk.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import replace as _dc_replace

import numpy as np

from spark_examples_tpu.core import faults, hashing, telemetry
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.source import BlockMeta
from spark_examples_tpu.store import quarantine as qledger
from spark_examples_tpu.store.cache import DecodeCache
from spark_examples_tpu.store.heal import HealError, heal_chunk
from spark_examples_tpu.store.manifest import (
    ChunkRecord,
    StoreCorruptError,
    StoreManifest,
)

DEFAULT_CACHE_BYTES = 256 << 20  # 256 MB of decoded chunks


def open_store(path: str, cache_bytes: int = DEFAULT_CACHE_BYTES,
               verify: bool = True,
               readahead_chunks: int = 0,
               replicas=(), auto_heal: bool = True) -> "StoreSource":
    """Open a compacted store (manifest load + lazy chunk mapping).

    ``readahead_chunks > 0`` arms the background readahead pool
    (store/readahead.py): the streaming loops warm that many chunks
    ahead of the cursor into the decode cache, so the store-cold tier
    (mmap + first-touch verify + decode) overlaps consumption instead
    of serializing in front of it.

    ``replicas`` names peer store directories holding content-addressed
    copies of the chunks; together with ``auto_heal`` (default on) a
    chunk that fails its digest verify is repaired in place — from a
    replica, else by re-compacting its origin span when the manifest
    records one — instead of failing the read (store/heal.py).
    """
    return StoreSource(path, StoreManifest.load(path),
                       cache_bytes=cache_bytes, verify=verify,
                       readahead_chunks=readahead_chunks,
                       replicas=replicas, auto_heal=auto_heal)


class StoreSource:
    """A compacted store as a streaming genotype source (see module
    docstring). Construct via :func:`open_store`."""

    def __init__(self, root: str, manifest: StoreManifest,
                 cache_bytes: int = DEFAULT_CACHE_BYTES,
                 verify: bool = True, readahead_chunks: int = 0,
                 replicas=(), auto_heal: bool = True):
        self.root = root
        self.manifest = manifest
        self.verify = bool(verify)
        self.replicas = tuple(replicas)
        self.auto_heal = bool(auto_heal)
        self.cache = DecodeCache(cache_bytes)
        self._verified: set[int] = set()
        self._positions: np.ndarray | None = None
        self._ra = None
        if readahead_chunks:
            if readahead_chunks < 0:
                raise ValueError(
                    f"readahead_chunks must be >= 0, got {readahead_chunks}"
                )
            from spark_examples_tpu.store.readahead import ReadaheadPool

            self._ra = ReadaheadPool(readahead_chunks)

    def close(self) -> None:
        """Stop the readahead pool (idempotent; streams already yielded
        stay valid — the pool only warms the cache)."""
        if self._ra is not None:
            self._ra.close()
            self._ra = None

    # -- GenotypeSource metadata -------------------------------------------

    @property
    def n_samples(self) -> int:
        return self.manifest.n_samples

    @property
    def n_variants(self) -> int:
        return self.manifest.n_variants

    @property
    def sample_ids(self) -> list[str]:
        if self.manifest.sample_ids is not None:
            return self.manifest.sample_ids
        return [f"S{i:06d}" for i in range(self.n_samples)]

    @property
    def exact_n_variants(self) -> bool:
        """Same claim shape as Packed2BitSource: a single-contig store
        streams exactly ceil(V/bv) blocks on both transports; a multi-
        contig store's blocks flush at chromosome runs, so it declines."""
        return len(self.manifest.contig_runs) <= 1

    @property
    def positions(self) -> np.ndarray | None:
        """Per-variant positions (mmap), digest-verified on first load."""
        if not self.manifest.has_positions:
            return None
        if self._positions is None:
            pos_path = os.path.join(self.root, "positions.npy")
            want = self.manifest.positions_digest
            if self.verify and want is not None:
                got = hashing.sha256_file(pos_path)
                if got != want:
                    raise StoreCorruptError(
                        f"store positions file {pos_path!r} does not "
                        f"match its manifest digest (truncated or "
                        "corrupt) — re-compact the store", 0,
                    )
            self._positions = np.load(pos_path, mmap_mode="r")
        return self._positions

    # -- chunk access (the tiered read path) -------------------------------

    def _chunk_path(self, rec: ChunkRecord) -> str:
        return os.path.join(self.root, rec.filename())

    def _damaged(self, idx: int, rec: ChunkRecord, reason: str,
                 healed: bool) -> np.ndarray:
        """A chunk failed its size/existence/digest check: try an
        in-place heal first (replica copy, else origin re-compaction —
        store/heal.py), and only quarantine + fail when no route
        repairs it. ``healed`` guards the retry: a chunk that fails its
        check AGAIN right after a successful heal is damage the heal
        cannot fix (e.g. a fault spec re-corrupting every read), and
        must fail rather than loop."""
        telemetry.count("store.verify_failures")
        if self.auto_heal and not healed and (
            self.replicas or self.manifest.origin is not None
        ):
            try:
                how = heal_chunk(self.root, self.manifest, rec,
                                 replicas=self.replicas)
            except HealError as e:
                reason = f"{reason}; heal failed ({e})"
            else:
                warnings.warn(
                    f"store: chunk {idx} ({rec.digest[:16]}...) was "
                    f"corrupt ({reason}) and healed in place from "
                    f"{how} — the stream continues",
                    RuntimeWarning, stacklevel=4,
                )
                self._verified.discard(idx)
                return self._chunk_bytes(idx, _healed=True)
        self._quarantine(idx, rec, reason)

    def _quarantine(self, idx: int, rec: ChunkRecord, reason: str):
        """Record a corrupt chunk and fail fast with the cursor named.

        The file is left in place (the operator may be able to recover
        it — e.g. re-copy from a replica; content addressing means a
        recovered chunk needs no manifest surgery), but its address is
        appended to quarantine.json (atomically and idempotently —
        store/quarantine.py) so post-mortem tooling sees every incident
        even after the process dies."""
        telemetry.count("store.quarantined")
        qledger.record(self.root, {
            "chunk": idx, "digest": rec.digest,
            "file": rec.filename(), "start": rec.start,
            "stop": rec.stop, "reason": reason,
        })
        raise StoreCorruptError(
            f"store chunk {idx} ({rec.filename()}, variants "
            f"[{rec.start}, {rec.stop})) is corrupt: {reason} — the "
            "chunk is quarantined (see quarantine.json), not retried "
            "and not skipped; recover the file (its name is its "
            "expected sha256 — restore it from a replica, or delete it "
            "and re-run the compaction over the original source) and "
            f"resume from start_variant={rec.start} (or the last "
            "--checkpoint-dir checkpoint)",
            rec.start,
        )

    def _chunk_bytes(self, idx: int, _healed: bool = False) -> np.ndarray:
        """The chunk's packed bytes, mapped and (first touch) verified.
        Damage on any check routes through :meth:`_damaged` — one heal
        attempt, then quarantine + fail."""
        rec = self.manifest.chunks[idx]
        path = self._chunk_path(rec)
        # Chaos site BEFORE the mapping: an armed truncate corrupts the
        # file relative to its content address (exactly what a torn
        # replica copy looks like); an io_error exercises the retry
        # boundary wrapping this source.
        faults.fire("store.read", path=path)
        w_bytes = bitpack.packed_width(rec.width)
        try:
            m = np.memmap(path, dtype=np.uint8, mode="r",
                          shape=(self.n_samples, w_bytes))
        except ValueError as e:
            # Wrong file size for the catalog shape = truncation.
            return self._damaged(
                idx, rec, f"wrong size for ({self.n_samples}, "
                f"{w_bytes}) bytes ({e})", _healed)
        except FileNotFoundError:
            # A cataloged chunk that does not exist is damage (a lost
            # replica copy, a deleted quarantined file), not weather —
            # letting it escape as raw OSError would burn the retry
            # layer's whole reopen budget re-missing the same file and
            # end with no recovery guidance. Other OSErrors (EIO, a
            # flapping mount) stay retryable.
            return self._damaged(idx, rec, "chunk file missing", _healed)
        if self.verify and idx not in self._verified:
            got = hashing.sha256_bytes(m)
            telemetry.count("store.chunks_verified")
            if got != rec.digest:
                # Release the mapping before a heal rewrites the file.
                del m
                return self._damaged(
                    idx, rec, f"sha256 {got[:16]}... does not match the "
                    "content address (bit rot or a torn write)", _healed)
            self._verified.add(idx)
        return m

    def _decode_chunk(self, idx: int) -> np.ndarray:
        """Unconditional map+verify+decode of one chunk into the cache —
        the cold tier's actual work, shared by the consumer path and the
        readahead workers (who run it off the critical path)."""
        rec = self.manifest.chunks[idx]
        with telemetry.span("store.chunk_read", cat="store", chunk=idx):
            raw = self._chunk_bytes(idx)
            dense = bitpack.unpack_dosages_np(raw)[:, :rec.width]
        self.cache.put(idx, dense)
        return dense

    def _warm_dense(self, idx: int) -> np.ndarray:
        """Readahead worker body: decode unless already resident (peek —
        a background warmer must not touch the consumer-facing hit/miss
        accounting)."""
        cached = self.cache.peek(idx)
        if cached is not None:
            return cached
        return self._decode_chunk(idx)

    def _schedule_ahead(self, last_idx: int, packed: bool = False) -> None:
        """Warm the ``depth`` chunks after ``last_idx`` in the background.

        Dense transport warms full decodes into the cache; the packed
        transport's cold cost is the first-touch digest verify, so it
        warms ``_chunk_bytes`` (map + verify) instead. Errors raised by
        a warm are delivered to the consumer when its cursor reaches the
        failed chunk (ReadaheadPool.consume), in order."""
        if self._ra is None:
            return
        n_chunks = len(self.manifest.chunks)
        for j in range(last_idx + 1,
                       min(last_idx + 1 + self._ra.depth, n_chunks)):
            if packed:
                if j in self._verified:
                    continue
                self._ra.schedule(("bytes", j),
                                  lambda j=j: self._chunk_bytes(j))
            else:
                if self.cache.peek(j) is not None:
                    continue
                self._ra.schedule(("dense", j),
                                  lambda j=j: self._warm_dense(j))

    def _chunk_dense(self, idx: int) -> np.ndarray:
        """Dense int8 decode of one chunk, through the decode cache and
        (when armed) the readahead rendezvous."""
        cached = self.cache.get(idx)
        if cached is not None:
            return cached
        if self._ra is not None:
            got = self._ra.consume(("dense", idx))  # re-raises a failed warm
            if got is not None:
                return got
        return self._decode_chunk(idx)

    def read_range(self, lo: int, hi: int) -> np.ndarray:
        """Dense (N, hi-lo) int8 slice of the global variant order —
        the random-access primitive range queries and tests build on."""
        if not 0 <= lo <= hi <= self.n_variants:
            raise ValueError(
                f"variant range [{lo}, {hi}) out of bounds for a "
                f"{self.n_variants}-variant store"
            )
        parts = [
            self._chunk_dense(i)[:, max(lo - rec.start, 0):hi - rec.start]
            for i, rec in self.manifest.chunks_for_range(lo, hi)
        ]
        if not parts:
            return np.empty((self.n_samples, 0), np.int8)
        out = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        return np.ascontiguousarray(out)

    # -- streaming transports ----------------------------------------------

    def _grid(self, block_variants: int):
        """(idx, lo, hi, contig) for every block of the store's grid:
        per-contig-segment, restarting at each run boundary (the same
        geometry VCF/PLINK streams produce, so contigs stay exact)."""
        bounds = self.manifest.segment_bounds()
        runs = self.manifest.contig_runs
        idx = 0
        for s in range(len(bounds) - 1):
            contig = runs[s][0]
            for lo in range(bounds[s], bounds[s + 1], block_variants):
                hi = min(lo + block_variants, bounds[s + 1])
                yield idx, lo, hi, contig
                idx += 1

    def _meta(self, idx, lo, hi, contig) -> BlockMeta:
        pos = self.positions
        return BlockMeta(idx, lo, hi, contig,
                         pos[lo:hi] if pos is not None else None)

    def blocks(self, block_variants: int, start_variant: int = 0):
        """Dense blocks at any width; resume skips blocks starting
        before the cursor (ceil-align for mid-block cursors, exact for
        self-produced stops — the contract every geometry here keeps)."""
        for idx, lo, hi, contig in self._grid(block_variants):
            if lo < start_variant:
                continue
            covering = self.manifest.chunks_for_range(lo, hi)
            if covering:
                self._schedule_ahead(covering[-1][0])
            yield self.read_range(lo, hi), self._meta(idx, lo, hi, contig)

    def packed_blocks(self, block_variants: int, start_variant: int = 0):
        """2-bit packed blocks for the packed transport. Zero-copy when
        a block falls inside one chunk on the byte grid (the common
        case: bv dividing chunk_variants); re-packed from the dense
        decode otherwise — same bytes semantics either way (tail pad
        codes are MISSING, free to every gram piece)."""
        if block_variants % bitpack.VARIANTS_PER_BYTE:
            raise ValueError(
                f"packed_blocks needs block_variants divisible by "
                f"{bitpack.VARIANTS_PER_BYTE}, got {block_variants}"
            )
        vpb = bitpack.VARIANTS_PER_BYTE
        for idx, lo, hi, contig in self._grid(block_variants):
            if lo < start_variant:
                continue
            covering = self.manifest.chunks_for_range(lo, hi)
            if covering:
                self._schedule_ahead(covering[-1][0], packed=True)
            if len(covering) == 1 and (lo - covering[0][1].start) % vpb == 0:
                i, rec = covering[0]
                if self._ra is not None:
                    warmed = self._ra.consume(("bytes", i))  # re-raises
                    raw = (warmed if warmed is not None
                           else self._chunk_bytes(i))
                else:
                    raw = self._chunk_bytes(i)
                b0 = (lo - rec.start) // vpb
                b1 = bitpack.packed_width(hi - rec.start)
                pblock = np.ascontiguousarray(raw[:, b0:b1])
            else:
                pblock = bitpack.pack_dosages(self.read_range(lo, hi))
            yield pblock, self._meta(idx, lo, hi, contig)

    # -- range queries (the catalog's partitioner surface) -----------------

    def variant_range(self, lo: int, hi: int) -> "StoreRangeSource":
        """A GenotypeSource over global variants [lo, hi) — arbitrary
        bounds, chunk- and block-grid independent."""
        return StoreRangeSource(self, lo, hi)

    def contig_source(self, contig: str) -> "StoreRangeSource":
        lo, hi = self.manifest.contig_span(contig)
        return StoreRangeSource(self, lo, hi)

    def position_span(self, contig: str, start: int, end: int) -> tuple[int, int]:
        """Global variant range covering positions [start, end) on
        ``contig`` — the reference's ``searchVariants`` range semantics,
        answered from the catalog + position index without touching a
        single chunk. Empty span when nothing matches."""
        lo, hi = self.manifest.contig_span(contig)
        if hi <= lo:
            return 0, 0
        pos = self.positions
        if pos is None:
            raise ValueError(
                "this store was compacted from a source without "
                "positions — position-range queries need them; "
                "variant_range/contig_source still work"
            )
        seg = pos[lo:hi]
        a = lo + int(np.searchsorted(seg, start, side="left"))
        b = lo + int(np.searchsorted(seg, end, side="left"))
        return a, b

    def restrict(self, references) -> object:
        """The ``--references CONTIG:START:END`` filter over the store:
        one range source per reference, chained in order — the catalog
        analog of the reference fork's genomic-range partitioners."""
        from spark_examples_tpu.ingest.source import ChainSource, EmptyShare

        parts = []
        for ref in references:
            lo, hi = self.position_span(ref.contig, ref.start, ref.end)
            if hi > lo:
                parts.append(StoreRangeSource(self, lo, hi))
        if not parts:
            return EmptyShare(self)
        if len(parts) == 1:
            return parts[0]
        return ChainSource(parts)


class StoreRangeSource:
    """A contiguous global-variant window [lo, hi) of a store, with
    LOCAL indexing — the unit a range query returns. Unlike
    ``WindowSource`` it accepts arbitrary (unaligned) bounds: the store
    decodes at chunk granularity anyway, so re-gridding from ``lo`` is
    free. Blocks still never span a contig run."""

    def __init__(self, store: StoreSource, lo: int, hi: int):
        if not 0 <= lo <= hi <= store.n_variants:
            raise ValueError(
                f"range [{lo}, {hi}) out of bounds for a "
                f"{store.n_variants}-variant store"
            )
        self.store = store
        self.lo = lo
        self.hi = hi

    @property
    def n_samples(self) -> int:
        return self.store.n_samples

    @property
    def n_variants(self) -> int:
        return self.hi - self.lo

    @property
    def sample_ids(self) -> list[str]:
        return self.store.sample_ids

    @property
    def exact_n_variants(self) -> bool:
        bounds = self.store.manifest.segment_bounds()
        inner = [b for b in bounds if self.lo < b < self.hi]
        return not inner

    def blocks(self, block_variants: int, start_variant: int = 0):
        bounds = self.store.manifest.segment_bounds()
        runs = self.store.manifest.contig_runs
        idx = 0
        for s in range(len(bounds) - 1):
            seg_lo = max(bounds[s], self.lo)
            seg_hi = min(bounds[s + 1], self.hi)
            if seg_hi <= seg_lo:
                continue
            for lo in range(seg_lo, seg_hi, block_variants):
                hi = min(lo + block_variants, seg_hi)
                local_lo = lo - self.lo
                if local_lo < start_variant:
                    idx += 1
                    continue
                covering = self.store.manifest.chunks_for_range(lo, hi)
                if covering:
                    self.store._schedule_ahead(covering[-1][0])
                meta = self.store._meta(idx, lo, hi, runs[s][0])
                yield self.store.read_range(lo, hi), _dc_replace(
                    meta, start=local_lo, stop=hi - self.lo,
                )
                idx += 1

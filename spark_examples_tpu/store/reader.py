"""StoreSource: the catalog as a GenotypeSource, with verified reads.

The read path is tiered:

1. **disk** — each chunk file is ``np.memmap``-ed read-only (the packed
   bytes of a raw-codec chunk page in on demand and ship zero-copy; a
   compressed chunk's stored bytes are inflated through the native
   decode below);
2. **decode cache** — decoded chunks in bounded host RAM with hit/miss
   accounting (store/cache.py), charged at their DECODED size: dense
   int8 decodes under ``("dense", idx)`` keys, and — for compressed
   chunks on the packed transport — inflated 2-bit payloads under
   ``("packed", idx)``;
3. the consumer: ``blocks`` / ``packed_blocks`` re-grid chunks into any
   requested block width (never spanning a contig), ``range_source``
   answers contig/variant/position range queries off the catalog, and
   cursors resume deterministically — the drop-in contract every job
   surface (runner, streaming, serve staging) already assumes.

Decoding is one native call where it matters (store/codec.py
``decode_into``): inflate + 2-bit unpack straight into the destination
buffer — a fresh cache entry, a ``read_range`` output, or (via
``decode_range_into`` / ``block_spans``, the prefetch staging ring's
direct drive) a reusable staging slab — with a bit-identical Python
fallback that degrades loudly (``store.codec.fallback``).

**Integrity**: a chunk's filename is the sha256 of its STORED bytes.
On first touch per reader the file is re-hashed against the address
(``store.read`` fault site fires first, so the chaos harness can
corrupt or fail the read deterministically) — corrupt compressed bytes
are caught exactly where corrupt raw bytes are. A mismatch, a wrong
size, or undecodable stored bytes first attempt an in-place **heal**
(store/heal.py): a verified copy from a peer replica directory, else a
re-compaction (and re-compression) of the chunk's origin span when the
manifest records one — degradation instead of fail-fast. Only when no
route repairs it is the chunk quarantined — recorded in
``<store>/quarantine.json`` (atomic, idempotent — store/quarantine.py),
counted, and raised as :class:`StoreCorruptError` naming the resume
cursor. Corruption is damage, not weather: the retry layer
(ingest/resilient.py) retries transient ``IOError`` s around this path
but never a quarantined chunk.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import replace as _dc_replace

import numpy as np

from spark_examples_tpu.core import faults, hashing, telemetry
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.source import BlockMeta
from spark_examples_tpu.store import codec as codecmod
from spark_examples_tpu.store import quarantine as qledger
from spark_examples_tpu.store.cache import DecodeCache
from spark_examples_tpu.store.heal import HealError, heal_chunk, recover_dict
from spark_examples_tpu.store.manifest import (
    ChunkRecord,
    StoreCorruptError,
    StoreManifest,
)

DEFAULT_CACHE_BYTES = 256 << 20  # 256 MB of decoded chunks
DEFAULT_READAHEAD_MAX = 16


def open_store(path: str, cache_bytes: int = DEFAULT_CACHE_BYTES,
               verify: bool = True,
               readahead_chunks: int = 0,
               readahead_chunks_max: int = DEFAULT_READAHEAD_MAX,
               replicas=(), auto_heal: bool = True) -> "StoreSource":
    """Open a compacted store (manifest load + lazy chunk mapping).

    ``readahead_chunks > 0`` arms the background readahead pool
    (store/readahead.py): the streaming loops warm chunks ahead of the
    cursor into the decode cache, so the store-cold tier (mmap +
    first-touch verify + decode) overlaps consumption instead of
    serializing in front of it. ``readahead_chunks`` is the depth
    FLOOR; ``readahead_chunks_max`` (when > floor) lets the pool adapt
    the depth to the measured consumer cadence vs decode latency —
    deep when the consumer outruns the decode, shallow when it does
    not (exported as the ``store.readahead.depth`` gauge).

    ``replicas`` names peer store directories holding content-addressed
    copies of the chunks; together with ``auto_heal`` (default on) a
    chunk that fails its digest verify is repaired in place — from a
    replica, else by re-compacting its origin span when the manifest
    records one — instead of failing the read (store/heal.py).
    """
    return StoreSource(path, StoreManifest.load(path),
                       cache_bytes=cache_bytes, verify=verify,
                       readahead_chunks=readahead_chunks,
                       readahead_chunks_max=readahead_chunks_max,
                       replicas=replicas, auto_heal=auto_heal)


class StoreSource:
    """A compacted store as a streaming genotype source (see module
    docstring). Construct via :func:`open_store`."""

    def __init__(self, root: str, manifest: StoreManifest,
                 cache_bytes: int = DEFAULT_CACHE_BYTES,
                 verify: bool = True, readahead_chunks: int = 0,
                 readahead_chunks_max: int = DEFAULT_READAHEAD_MAX,
                 replicas=(), auto_heal: bool = True):
        self.root = root
        self.manifest = manifest
        self.verify = bool(verify)
        self.replicas = tuple(replicas)
        self.auto_heal = bool(auto_heal)
        self.cache = DecodeCache(cache_bytes)
        self._verified: set[int] = set()
        self._positions: np.ndarray | None = None
        self._dicts: dict[str, bytes] = {}
        self._ra = None
        if readahead_chunks:
            if readahead_chunks < 0:
                raise ValueError(
                    f"readahead_chunks must be >= 0, got {readahead_chunks}"
                )
            from spark_examples_tpu.store.readahead import ReadaheadPool

            self._ra = ReadaheadPool(readahead_chunks,
                                     max_depth=readahead_chunks_max)

    def close(self) -> None:
        """Stop the readahead pool (idempotent; streams already yielded
        stay valid — the pool only warms the cache)."""
        if self._ra is not None:
            self._ra.close()
            self._ra = None

    # -- GenotypeSource metadata -------------------------------------------

    @property
    def n_samples(self) -> int:
        return self.manifest.n_samples

    @property
    def n_variants(self) -> int:
        return self.manifest.n_variants

    @property
    def sample_ids(self) -> list[str]:
        if self.manifest.sample_ids is not None:
            return self.manifest.sample_ids
        return [f"S{i:06d}" for i in range(self.n_samples)]

    @property
    def exact_n_variants(self) -> bool:
        """Same claim shape as Packed2BitSource: a single-contig store
        streams exactly ceil(V/bv) blocks on both transports; a multi-
        contig store's blocks flush at chromosome runs, so it declines."""
        return len(self.manifest.contig_runs) <= 1

    @property
    def positions(self) -> np.ndarray | None:
        """Per-variant positions (mmap), digest-verified on first load."""
        if not self.manifest.has_positions:
            return None
        if self._positions is None:
            pos_path = os.path.join(self.root, "positions.npy")
            want = self.manifest.positions_digest
            if self.verify and want is not None:
                got = hashing.sha256_file(pos_path)
                if got != want:
                    raise StoreCorruptError(
                        f"store positions file {pos_path!r} does not "
                        f"match its manifest digest (truncated or "
                        "corrupt) — re-compact the store", 0,
                    )
            self._positions = np.load(pos_path, mmap_mode="r")
        return self._positions

    # -- chunk access (the tiered read path) -------------------------------

    def _chunk_path(self, rec: ChunkRecord) -> str:
        return os.path.join(self.root, rec.filename())

    def _handle_damage(self, idx: int, rec: ChunkRecord, reason: str,
                       healed: bool) -> None:
        """A chunk failed a size/existence/digest/decode check: try an
        in-place heal first (replica copy, else origin re-compaction —
        store/heal.py), and only quarantine + fail when no route
        repairs it. Returns (with the file repaired and the chunk's
        first-touch verification reset) so the caller can retry its
        read ONCE; ``healed`` guards that retry — a chunk that fails
        again right after a successful heal is damage the heal cannot
        fix (e.g. a fault spec re-corrupting every read), and must
        fail rather than loop."""
        telemetry.count("store.verify_failures")
        if self.auto_heal and not healed and (
            self.replicas or self.manifest.origin is not None
        ):
            try:
                how = heal_chunk(self.root, self.manifest, rec,
                                 replicas=self.replicas)
            except HealError as e:
                reason = f"{reason}; heal failed ({e})"
            else:
                warnings.warn(
                    f"store: chunk {idx} ({rec.digest[:16]}...) was "
                    f"corrupt ({reason}) and healed in place from "
                    f"{how} — the stream continues",
                    RuntimeWarning, stacklevel=5,
                )
                self._verified.discard(idx)
                return
        self._quarantine(idx, rec, reason)

    def _quarantine(self, idx: int, rec: ChunkRecord, reason: str):
        """Record a corrupt chunk and fail fast with the cursor named.

        The file is left in place (the operator may be able to recover
        it — e.g. re-copy from a replica; content addressing means a
        recovered chunk needs no manifest surgery), but its address is
        appended to quarantine.json (atomically and idempotently —
        store/quarantine.py) so post-mortem tooling sees every incident
        even after the process dies."""
        telemetry.count("store.quarantined")
        qledger.record(self.root, {
            "chunk": idx, "digest": rec.digest,
            "file": rec.filename(), "start": rec.start,
            "stop": rec.stop, "reason": reason,
        })
        raise StoreCorruptError(
            f"store chunk {idx} ({rec.filename()}, variants "
            f"[{rec.start}, {rec.stop})) is corrupt: {reason} — the "
            "chunk is quarantined (see quarantine.json), not retried "
            "and not skipped; recover the file (its name is its "
            "expected sha256 — restore it from a replica, or delete it "
            "and re-run the compaction over the original source) and "
            f"resume from start_variant={rec.start} (or the last "
            "--checkpoint-dir checkpoint)",
            rec.start,
        )

    def _stored_bytes(self, idx: int, _healed: bool = False) -> np.ndarray:
        """The chunk file's STORED bytes (1-D uint8 mmap), size-checked
        against the catalog and (first touch) sha256-verified against
        the content address. Damage routes through
        :meth:`_handle_damage` — one heal attempt, then quarantine +
        fail."""
        rec = self.manifest.chunks[idx]
        path = self._chunk_path(rec)
        # Chaos site BEFORE the mapping: an armed truncate corrupts the
        # file relative to its content address (exactly what a torn
        # replica copy looks like); an io_error exercises the retry
        # boundary wrapping this source.
        faults.fire("store.read", path=path)
        want = rec.disk_size(self.n_samples)
        try:
            size = os.path.getsize(path)
        except FileNotFoundError:
            # A cataloged chunk that does not exist is damage (a lost
            # replica copy, a deleted quarantined file), not weather —
            # letting it escape as raw OSError would burn the retry
            # layer's whole reopen budget re-missing the same file and
            # end with no recovery guidance. Other OSErrors (EIO, a
            # flapping mount) stay retryable.
            self._handle_damage(idx, rec, "chunk file missing", _healed)
            return self._stored_bytes(idx, _healed=True)
        if size != want:
            # Wrong on-disk size for the catalog row = truncation (the
            # check the raw mmap shape used to provide, kept explicit
            # now that compressed sizes are per-chunk).
            self._handle_damage(
                idx, rec, f"file is {size} bytes, catalog says {want}",
                _healed)
            return self._stored_bytes(idx, _healed=True)
        m = np.memmap(path, dtype=np.uint8, mode="r")
        if self.verify and idx not in self._verified:
            got = hashing.sha256_bytes(m)
            telemetry.count("store.chunks_verified")
            if got != rec.digest:
                # Release the mapping before a heal rewrites the file.
                del m
                self._handle_damage(
                    idx, rec, f"sha256 {got[:16]}... does not match the "
                    "content address (bit rot or a torn write)", _healed)
                return self._stored_bytes(idx, _healed=True)
            self._verified.add(idx)
        return m

    def _dict_bytes(self, rec: ChunkRecord) -> bytes | None:
        """The chunk's shared preset dictionary (dicts/<digest>.zdict),
        digest-verified on first load per reader and cached. A missing
        or corrupt dictionary file is store damage: recovered through
        the same replica/origin routes as a chunk (store/heal.py
        recover_dict), else failed fast with the chunk's cursor."""
        dd = rec.dict_digest
        if dd is None:
            return None
        cached = self._dicts.get(dd)
        if cached is not None:
            return cached
        path = codecmod.dict_path(self.root, dd)
        data = None
        try:
            with open(path, "rb") as f:
                data = f.read()
            if hashing.sha256_bytes(data) != dd:
                data = None
        except OSError:
            data = None
        if data is None:
            if not self.auto_heal:
                # Same contract as chunks: with healing disabled,
                # damage fails fast instead of quietly rewriting store
                # files the caller said not to touch.
                raise StoreCorruptError(
                    f"store dictionary {path!r} is missing or corrupt "
                    "(healing disabled) — restore the file from a "
                    "replica or run `store heal`, then resume from "
                    f"start_variant={rec.start}",
                    rec.start,
                )
            try:
                data = recover_dict(self.root, self.manifest, dd,
                                    replicas=self.replicas)
            except HealError as e:
                raise StoreCorruptError(
                    f"store dictionary {path!r} is missing or corrupt "
                    f"and could not be recovered ({e}) — every chunk "
                    "compressed against it is unreadable; restore the "
                    "file from a replica or re-run `store heal`, then "
                    f"resume from start_variant={rec.start}",
                    rec.start,
                ) from e
        self._dicts[dd] = data
        return data

    def _decode_span_into(self, idx: int, v0: int, v1: int,
                          out: np.ndarray, col_off: int,
                          _healed: bool = False) -> None:
        """Decode variants [v0, v1) of chunk ``idx`` into ``out`` at
        ``col_off`` — the native (or fallback) decode with the same
        one-heal-then-quarantine damage contract as the byte reads."""
        rec = self.manifest.chunks[idx]
        m = self._stored_bytes(idx, _healed)
        try:
            codecmod.decode_into(
                m, rec.codec, self._dict_bytes(rec), self.n_samples,
                bitpack.packed_width(rec.width), v0, v1, out, col_off,
            )
        except codecmod.StoreDecodeError as e:
            # Undecodable stored bytes behave exactly like a digest
            # mismatch (they can only diverge when verification is
            # off or the damage landed mid-read).
            del m
            self._handle_damage(idx, rec, str(e), _healed)
            self._decode_span_into(idx, v0, v1, out, col_off,
                                   _healed=True)

    def _decode_chunk(self, idx: int) -> np.ndarray:
        """Unconditional map+verify+decode of one chunk into the cache —
        the cold tier's actual work, shared by the consumer path and the
        readahead workers (who run it off the critical path)."""
        rec = self.manifest.chunks[idx]
        with telemetry.span("store.chunk_read", cat="store", chunk=idx):
            dense = np.empty((self.n_samples, rec.width), np.int8)
            self._decode_span_into(idx, 0, rec.width, dense, 0)
        self.cache.put(("dense", idx), dense)
        return dense

    def _decompress_payload(self, idx: int,
                            _healed: bool = False) -> np.ndarray:
        """Inflate a compressed chunk's 2-bit payload into host RAM
        (and the decode cache — charged at its DECODED size): the
        packed transport's unit for non-raw chunks."""
        rec = self.manifest.chunks[idx]
        m = self._stored_bytes(idx, _healed)
        try:
            payload = codecmod.decompress(
                rec.codec, m, rec.payload_size(self.n_samples),
                self._dict_bytes(rec))
        except codecmod.StoreDecodeError as e:
            del m
            self._handle_damage(idx, rec, str(e), _healed)
            return self._decompress_payload(idx, _healed=True)
        arr = np.frombuffer(payload, np.uint8).reshape(
            self.n_samples, bitpack.packed_width(rec.width))
        self.cache.put(("packed", idx), arr)
        return arr

    def _payload(self, idx: int) -> np.ndarray:
        """The chunk's packed 2-bit payload, (n, w_bytes) uint8: the
        verified mmap itself for raw chunks (zero-copy), the cached
        inflate for compressed ones."""
        rec = self.manifest.chunks[idx]
        if rec.codec == codecmod.RAW:
            return self._stored_bytes(idx).reshape(
                self.n_samples, bitpack.packed_width(rec.width))
        cached = self.cache.get(("packed", idx))
        if cached is not None:
            return cached
        return self._decompress_payload(idx)

    def _warm_payload(self, idx: int) -> np.ndarray:
        """Readahead worker body, packed transport: verify (raw) or
        inflate-and-cache (compressed) unless already resident (peek —
        a background warmer must not touch the consumer-facing hit/miss
        accounting)."""
        rec = self.manifest.chunks[idx]
        if rec.codec == codecmod.RAW:
            return self._stored_bytes(idx).reshape(
                self.n_samples, bitpack.packed_width(rec.width))
        cached = self.cache.peek(("packed", idx))
        if cached is not None:
            return cached
        return self._decompress_payload(idx)

    def _warm_dense(self, idx: int) -> np.ndarray:
        """Readahead worker body, dense transport: decode unless
        already resident (peek, for the same accounting reason)."""
        cached = self.cache.peek(("dense", idx))
        if cached is not None:
            return cached
        return self._decode_chunk(idx)

    def _schedule_ahead(self, last_idx: int, packed: bool = False) -> None:
        """Warm the chunks after ``last_idx`` in the background, to the
        pool's (possibly cadence-adapted) current depth.

        Called once per consumed block, which is also the pool's
        consumer-cadence sample (``note_retire``). Dense transport
        warms full decodes into the cache; the packed transport's cold
        cost is the first-touch digest verify plus (for compressed
        chunks) the inflate, so it warms the payload instead. Errors
        raised by a warm are delivered to the consumer when its cursor
        reaches the failed chunk (ReadaheadPool.consume), in order."""
        if self._ra is None:
            return
        self._ra.note_retire(last_idx)
        n_chunks = len(self.manifest.chunks)
        for j in range(last_idx + 1,
                       min(last_idx + 1 + self._ra.depth, n_chunks)):
            rec = self.manifest.chunks[j]
            if packed:
                if rec.codec == codecmod.RAW:
                    if j in self._verified:
                        continue
                elif self.cache.peek(("packed", j)) is not None:
                    continue
                self._ra.schedule(("packed", j),
                                  lambda j=j: self._warm_payload(j))
            else:
                if self.cache.peek(("dense", j)) is not None:
                    continue
                self._ra.schedule(("dense", j),
                                  lambda j=j: self._warm_dense(j))

    def _chunk_dense(self, idx: int) -> np.ndarray:
        """Dense int8 decode of one chunk, through the decode cache and
        (when armed) the readahead rendezvous."""
        cached = self.cache.get(("dense", idx))
        if cached is not None:
            return cached
        if self._ra is not None:
            got = self._ra.consume(("dense", idx))  # re-raises a failed warm
            if got is not None:
                return got
        return self._decode_chunk(idx)

    def read_range(self, lo: int, hi: int) -> np.ndarray:
        """Dense (N, hi-lo) int8 slice of the global variant order —
        the random-access primitive range queries and tests build on."""
        if not 0 <= lo <= hi <= self.n_variants:
            raise ValueError(
                f"variant range [{lo}, {hi}) out of bounds for a "
                f"{self.n_variants}-variant store"
            )
        parts = [
            self._chunk_dense(i)[:, max(lo - rec.start, 0):hi - rec.start]
            for i, rec in self.manifest.chunks_for_range(lo, hi)
        ]
        if not parts:
            return np.empty((self.n_samples, 0), np.int8)
        out = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        return np.ascontiguousarray(out)

    def decode_range_into(self, lo: int, hi: int, out: np.ndarray,
                          col_off: int = 0) -> None:
        """Decode global variants [lo, hi) into ``out[:, col_off:...]``
        — the zero-copy drive the prefetch staging ring uses
        (ingest/prefetch.py): cached/warmed chunks are block-copied,
        everything else decodes STRAIGHT into the destination slab
        through the native entry, with no intermediate dense buffer."""
        if not 0 <= lo <= hi <= self.n_variants:
            raise ValueError(
                f"variant range [{lo}, {hi}) out of bounds for a "
                f"{self.n_variants}-variant store"
            )
        for i, rec in self.manifest.chunks_for_range(lo, hi):
            a, b = max(lo, rec.start), min(hi, rec.stop)
            dst = col_off + (a - lo)
            cached = self.cache.get(("dense", i))
            if cached is None and self._ra is not None:
                cached = self._ra.consume(("dense", i))  # re-raises
            if cached is None and rec.codec != codecmod.RAW and (
                    a > rec.start or b < rec.stop):
                # A PARTIAL span of a cold compressed chunk: the native
                # entry always inflates the whole payload, so decoding
                # straight to the slab here would re-pay the full
                # inflate for every covering block (2x+ whenever the
                # block grid is finer than the chunk grid). Decode once
                # into the cache and block-copy instead — the
                # zero-intermediate path is reserved for spans that
                # cover the chunk, and for raw chunks (whose partial
                # unpack reads only the span's bytes off the mmap).
                cached = self._decode_chunk(i)
            if cached is not None:
                np.copyto(out[:, dst:dst + (b - a)],
                          cached[:, a - rec.start:b - rec.start])
                continue
            with telemetry.span("store.chunk_read", cat="store", chunk=i):
                self._decode_span_into(i, a - rec.start, b - rec.start,
                                       out, dst)

    # -- streaming transports ----------------------------------------------

    def _grid(self, block_variants: int):
        """(idx, lo, hi, contig) for every block of the store's grid:
        per-contig-segment, restarting at each run boundary (the same
        geometry VCF/PLINK streams produce, so contigs stay exact)."""
        bounds = self.manifest.segment_bounds()
        runs = self.manifest.contig_runs
        idx = 0
        for s in range(len(bounds) - 1):
            contig = runs[s][0]
            for lo in range(bounds[s], bounds[s + 1], block_variants):
                hi = min(lo + block_variants, bounds[s + 1])
                yield idx, lo, hi, contig
                idx += 1

    def _meta(self, idx, lo, hi, contig) -> BlockMeta:
        pos = self.positions
        return BlockMeta(idx, lo, hi, contig,
                         pos[lo:hi] if pos is not None else None)

    def block_spans(self, block_variants: int, start_variant: int = 0):
        """(lo, hi, meta) for every dense-grid block — the decode-free
        twin of :meth:`blocks` that lets a caller owning the
        destination buffers (the prefetch staging ring) drive
        :meth:`decode_range_into` itself. Same grid, same resume
        semantics, same readahead scheduling."""
        for idx, lo, hi, contig in self._grid(block_variants):
            if lo < start_variant:
                continue
            covering = self.manifest.chunks_for_range(lo, hi)
            if covering:
                self._schedule_ahead(covering[-1][0])
            yield lo, hi, self._meta(idx, lo, hi, contig)

    def blocks(self, block_variants: int, start_variant: int = 0):
        """Dense blocks at any width; resume skips blocks starting
        before the cursor (ceil-align for mid-block cursors, exact for
        self-produced stops — the contract every geometry here keeps)."""
        for lo, hi, meta in self.block_spans(block_variants, start_variant):
            yield self.read_range(lo, hi), meta

    def packed_blocks(self, block_variants: int, start_variant: int = 0):
        """2-bit packed blocks for the packed transport. Zero-copy when
        a block falls inside one raw-codec chunk on the byte grid (the
        common case: bv dividing chunk_variants); compressed chunks
        substitute their cached inflated payload for the mmap;
        re-packed from the dense decode otherwise — same bytes
        semantics every way (tail pad codes are MISSING, free to every
        gram piece)."""
        if block_variants % bitpack.VARIANTS_PER_BYTE:
            raise ValueError(
                f"packed_blocks needs block_variants divisible by "
                f"{bitpack.VARIANTS_PER_BYTE}, got {block_variants}"
            )
        vpb = bitpack.VARIANTS_PER_BYTE
        for idx, lo, hi, contig in self._grid(block_variants):
            if lo < start_variant:
                continue
            covering = self.manifest.chunks_for_range(lo, hi)
            if covering:
                self._schedule_ahead(covering[-1][0], packed=True)
            if len(covering) == 1 and (lo - covering[0][1].start) % vpb == 0:
                i, rec = covering[0]
                if self._ra is not None:
                    warmed = self._ra.consume(("packed", i))  # re-raises
                    raw = (warmed if warmed is not None
                           else self._payload(i))
                else:
                    raw = self._payload(i)
                b0 = (lo - rec.start) // vpb
                b1 = bitpack.packed_width(hi - rec.start)
                pblock = np.ascontiguousarray(raw[:, b0:b1])
            else:
                pblock = bitpack.pack_dosages(self.read_range(lo, hi))
            yield pblock, self._meta(idx, lo, hi, contig)

    # -- range queries (the catalog's partitioner surface) -----------------

    def variant_range(self, lo: int, hi: int) -> "StoreRangeSource":
        """A GenotypeSource over global variants [lo, hi) — arbitrary
        bounds, chunk- and block-grid independent."""
        return StoreRangeSource(self, lo, hi)

    def contig_source(self, contig: str) -> "StoreRangeSource":
        lo, hi = self.manifest.contig_span(contig)
        return StoreRangeSource(self, lo, hi)

    def position_span(self, contig: str, start: int, end: int) -> tuple[int, int]:
        """Global variant range covering positions [start, end) on
        ``contig`` — the reference's ``searchVariants`` range semantics,
        answered from the catalog + position index without touching a
        single chunk. Empty span when nothing matches."""
        lo, hi = self.manifest.contig_span(contig)
        if hi <= lo:
            return 0, 0
        pos = self.positions
        if pos is None:
            raise ValueError(
                "this store was compacted from a source without "
                "positions — position-range queries need them; "
                "variant_range/contig_source still work"
            )
        seg = pos[lo:hi]
        a = lo + int(np.searchsorted(seg, start, side="left"))
        b = lo + int(np.searchsorted(seg, end, side="left"))
        return a, b

    def restrict(self, references) -> object:
        """The ``--references CONTIG:START:END`` filter over the store:
        one range source per reference, chained in order — the catalog
        analog of the reference fork's genomic-range partitioners."""
        from spark_examples_tpu.ingest.source import ChainSource, EmptyShare

        parts = []
        for ref in references:
            lo, hi = self.position_span(ref.contig, ref.start, ref.end)
            if hi > lo:
                parts.append(StoreRangeSource(self, lo, hi))
        if not parts:
            return EmptyShare(self)
        if len(parts) == 1:
            return parts[0]
        return ChainSource(parts)


class StoreRangeSource:
    """A contiguous global-variant window [lo, hi) of a store, with
    LOCAL indexing — the unit a range query returns. Unlike
    ``WindowSource`` it accepts arbitrary (unaligned) bounds: the store
    decodes at chunk granularity anyway, so re-gridding from ``lo`` is
    free. Blocks still never span a contig run."""

    def __init__(self, store: StoreSource, lo: int, hi: int):
        if not 0 <= lo <= hi <= store.n_variants:
            raise ValueError(
                f"range [{lo}, {hi}) out of bounds for a "
                f"{store.n_variants}-variant store"
            )
        self.store = store
        self.lo = lo
        self.hi = hi

    @property
    def n_samples(self) -> int:
        return self.store.n_samples

    @property
    def n_variants(self) -> int:
        return self.hi - self.lo

    @property
    def sample_ids(self) -> list[str]:
        return self.store.sample_ids

    @property
    def exact_n_variants(self) -> bool:
        bounds = self.store.manifest.segment_bounds()
        inner = [b for b in bounds if self.lo < b < self.hi]
        return not inner

    def _grid(self, block_variants: int):
        """(idx, lo, hi, contig) over the window's own block grid
        (GLOBAL lo/hi, never spanning a contig run) — shared by
        :meth:`blocks` and :meth:`block_spans`."""
        bounds = self.store.manifest.segment_bounds()
        runs = self.store.manifest.contig_runs
        idx = 0
        for s in range(len(bounds) - 1):
            seg_lo = max(bounds[s], self.lo)
            seg_hi = min(bounds[s + 1], self.hi)
            if seg_hi <= seg_lo:
                continue
            for lo in range(seg_lo, seg_hi, block_variants):
                hi = min(lo + block_variants, seg_hi)
                yield idx, lo, hi, runs[s][0]
                idx += 1

    def blocks(self, block_variants: int, start_variant: int = 0):
        for lo, hi, meta in self.block_spans(block_variants, start_variant):
            yield self.store.read_range(self.lo + lo, self.lo + hi), meta

    def block_spans(self, block_variants: int, start_variant: int = 0):
        """The column-window read path: (lo, hi, meta) in the window's
        LOCAL coordinates, the decode-free twin of :meth:`blocks` that
        lets a caller owning destination buffers (the prefetch staging
        ring, the multi-host per-process feed) drive
        :meth:`decode_range_into` itself — each process then decodes
        only its own mesh shard's variant slice straight into its slab,
        with no intermediate dense block and no post-decode slicing.
        Same grid, same resume semantics, same readahead scheduling as
        the full-store span path."""
        for idx, lo, hi, contig in self._grid(block_variants):
            local_lo = lo - self.lo
            if local_lo < start_variant:
                continue
            covering = self.store.manifest.chunks_for_range(lo, hi)
            if covering:
                self.store._schedule_ahead(covering[-1][0])
            meta = self.store._meta(idx, lo, hi, contig)
            yield local_lo, hi - self.lo, _dc_replace(
                meta, start=local_lo, stop=hi - self.lo,
            )

    def decode_range_into(self, lo: int, hi: int, out: np.ndarray,
                          col_off: int = 0) -> None:
        """Decode LOCAL window variants [lo, hi) into ``out`` — the
        window offset applied, then straight through the store's native
        decode-to-slab entry."""
        if not 0 <= lo <= hi <= self.n_variants:
            raise ValueError(
                f"variant range [{lo}, {hi}) out of bounds for a "
                f"{self.n_variants}-variant window"
            )
        self.store.decode_range_into(self.lo + lo, self.lo + hi, out,
                                     col_off)

"""Output writers — the reference's ``saveAsTextFile`` tail (SURVEY.md
§2.1 "Output writers": text rows of sample-name + coordinates).

Matrices are persisted with a ``<path>.meta.json`` sidecar recording the
sample ids and whether the matrix holds similarities or distances, so the
SimilarityMatrix -> PCoA job handoff (SURVEY.md §3.2-3.3) is
self-describing: the PCoA job cannot silently center a similarity matrix
as if it were distances, and ``.npy`` outputs keep their cohort labels.
"""

from __future__ import annotations

import json
import os

import numpy as np


def write_coords_tsv(path: str, sample_ids: list[str], coords: np.ndarray) -> None:
    """``sample<TAB>pc1<TAB>pc2...`` — the reference's PCA output shape."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    k = coords.shape[1]
    with open(path, "w") as f:
        f.write("sample\t" + "\t".join(f"pc{i + 1}" for i in range(k)) + "\n")
        for sid, row in zip(sample_ids, np.asarray(coords)):
            f.write(sid + "\t" + "\t".join(f"{v:.6g}" for v in row) + "\n")


def write_matrix(
    path: str,
    sample_ids: list[str],
    matrix: np.ndarray,
    kind: str | None = None,
    col_ids: list[str] | None = None,
) -> None:
    """Matrix as TSV (header row of column ids) or ``.npy``, plus the
    self-description sidecar. ``kind``: similarity | distance.
    ``col_ids``: for rectangular matrices (cross-cohort kinship) whose
    columns index a DIFFERENT cohort than the rows; square matrices
    leave it None (columns = rows)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    cols = col_ids if col_ids is not None else sample_ids
    if path.endswith(".npy"):
        np.save(path, matrix)
    else:
        with open(path, "w") as f:
            f.write("sample\t" + "\t".join(cols) + "\n")
            for sid, row in zip(sample_ids, np.asarray(matrix)):
                f.write(sid + "\t" + "\t".join(f"{v:.6g}" for v in row) + "\n")
    meta = {"kind": kind, "sample_ids": list(sample_ids)}
    if col_ids is not None:
        meta["col_ids"] = list(col_ids)
    with open(path + ".meta.json", "w") as f:
        json.dump(meta, f)


def read_matrix(path: str) -> tuple[list[str], np.ndarray, str | None]:
    """Inverse of write_matrix for SQUARE matrices:
    (sample_ids, matrix, kind-or-None).

    Rectangular stores (cross-cohort kinship, whose sidecar carries
    ``col_ids``) are rejected loudly — every consumer of this function
    (the pcoa two-job handoff) assumes rows and columns index the same
    cohort, and feeding a cross matrix through would mislabel rows with
    the other cohort's ids before crashing on the shape.
    """
    kind = None
    sidecar_ids = None
    meta_path = path + ".meta.json"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        kind = meta.get("kind")
        sidecar_ids = meta.get("sample_ids")
        if meta.get("col_ids") is not None:
            raise ValueError(
                f"{path}: rectangular cross-cohort matrix (rows and "
                "columns index different cohorts) — not consumable by "
                "the square-matrix jobs (pcoa --matrix-path)"
            )
    if path.endswith(".npy"):
        m = np.load(path)
        ids = sidecar_ids or [f"S{i:06d}" for i in range(m.shape[0])]
        return ids, m, kind
    with open(path) as f:
        header = f.readline().rstrip("\n").split("\t")[1:]
        rows = [line.rstrip("\n").split("\t")[1:] for line in f]
    m = np.asarray(rows, dtype=np.float64)
    if m.shape[0] != m.shape[1]:
        raise ValueError(
            f"{path}: matrix is {m.shape[0]}x{m.shape[1]} — read_matrix "
            "serves the square similarity/distance handoff only"
        )
    return header, m, kind

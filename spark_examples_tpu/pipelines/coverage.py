"""Coverage pipeline — the ``SearchReadsExample*`` tier (SURVEY.md §3.4).

The reference's read examples computed per-base coverage / read counts
over BAM regions via the API. The TPU-native form: read (start, length)
batches become difference-array scatter-adds (+1 at start, -1 past end)
on device, and per-base depth is one inclusive ``cumsum`` scan — both
XLA-native, no per-read host loop. Depth histograms and mean coverage
come off the same array.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest.reads import ReadsSource


@partial(jax.jit, static_argnames=("span",))
def _diff_accumulate(diff, starts, lengths, offset, span):
    """Scatter +1/-1 read boundaries into the difference array."""
    s = jnp.clip(starts - offset, 0, span - 1)
    e = jnp.clip(starts + lengths - offset, 0, span)  # exclusive end
    diff = diff.at[s].add(1.0)
    diff = diff.at[e].add(-1.0)  # index `span` lands in the sentinel slot
    return diff


@jax.jit
def _depth_from_diff(diff):
    return jnp.cumsum(diff[:-1])


@dataclass
class CoverageResult:
    reference: ReferenceRange
    depth: np.ndarray  # per-base coverage, len = range span
    n_reads: int

    @property
    def mean(self) -> float:
        return float(self.depth.mean()) if self.depth.size else 0.0

    def histogram(self, max_depth: int = 100) -> np.ndarray:
        return np.bincount(
            np.minimum(self.depth.astype(np.int64), max_depth),
            minlength=max_depth + 1,
        )


def coverage(source: ReadsSource, batch: int = 262144) -> list[CoverageResult]:
    """Per-base coverage for every range of the source."""
    out = []
    for ref in source.ranges():
        span = ref.end - ref.start
        diff = jnp.zeros(span + 1, jnp.float32)  # +1 sentinel for ends
        n_reads = 0
        for starts, lengths in source.read_batches(ref, batch):
            diff = _diff_accumulate(diff, starts, lengths, ref.start, span)
            n_reads += len(starts)
        depth = np.asarray(_depth_from_diff(diff))
        out.append(CoverageResult(ref, depth, n_reads))
    return out

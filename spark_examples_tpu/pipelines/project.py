"""Out-of-sample PCoA projection (Nystrom / Gower extension).

The reference family's flagship use case was placing cohorts in
1000-Genomes ancestry space (SURVEY.md §0, §4 "Golden values"); the
workflow people actually run is *fit once on a reference panel, then
project new samples into the same coordinates* — refitting on
reference+new moves every axis. This module adds that second half:

1. ``pcoa --save-model`` persists the fitted embedding: eigenvectors V,
   eigenvalues lambda, and the reference D^2 column/grand means the
   Gower centering needs (:func:`save_model` — one .npz).
2. ``project`` streams the NEW cohort against the REFERENCE genotypes
   (same variants), accumulating the cross statistics as int32 matmul
   products (:func:`spark_examples_tpu.ops.genotype.cross_stats` — the
   same MXU shape as the symmetric gram), finalizes the (A, N_ref)
   distance block on device, and applies Gower's out-of-sample formula:

       b_a   = -1/2 (d2_a - mean(d2_a) - colmean_ref + grand_ref)
       y_a   = b_a V diag(lambda)^{-1/2}

   Projecting the reference's own samples through this path reproduces
   their fitted coordinates exactly (B V = V diag(lambda)), which is the
   invariant the tests pin.

Projectability is a KERNEL capability: a gram-path kernel declaring a
:class:`spark_examples_tpu.kernels.CrossSpec` (the cross statistics to
stream plus the squared-distance finalize — ibs and jaccard today) is
projectable as a PCoA model through the Gower extension above, with no
changes here. The flagship PCA over shared-alt similarities stays its
own kind (``pca --save-model``; a new row's cross similarity is
centered with the reference's column/grand means and projected onto V
— training rows reproduce their fitted coordinates exactly, since
C V = V Λ).

The long-lived ONLINE counterpart of this module is
``spark_examples_tpu/serve/``: the serving engine stages the panel
device-resident and reuses this module's jitted cross-update and
finalize programs (and :func:`load_model` / :func:`clear_caches`),
which is what makes served coordinates bit-identical to this offline
path.
"""

from __future__ import annotations

import hashlib
import zipfile
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu import kernels
from spark_examples_tpu.core import meshes
from spark_examples_tpu.core.config import JobConfig
from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync
from spark_examples_tpu.ingest.prefetch import stream_to_device
from spark_examples_tpu.ops import genotype
from spark_examples_tpu.pipelines import io as pio
from spark_examples_tpu.pipelines import runner as R
from spark_examples_tpu.pipelines.jobs import CoordsOutput

# (model kind, metric) -> cross statistics to stream. Keyed on BOTH: a
# shared-alt PCoA model (valid to fit) is not projectable — gating on
# metric alone would pass it through and crash after the expensive
# cross-stream pass. The pcoa rows are DERIVED from the kernel
# registry: any kernel declaring a CrossSpec is servable/projectable,
# so adding one never touches this module. PCA keeps its dedicated
# similarity-projection row.
PROJECTABLE = {
    **{("pcoa", k.name): k.cross.stats
       for k in kernels.all_kernels() if k.cross is not None},
    ("pca", "shared-alt"): ("s",),
}

# Saved-model schema version. Bump when a field is added/renamed/
# re-semanticized; load_model refuses files it cannot interpret with a
# friendly error instead of a raw KeyError — the serving layer hot-
# reloads models, and "which field is missing/old" must be diagnosable
# from the exception alone. Version 2 = the first versioned schema
# (version 1, retroactively, is the unversioned pre-serving format).
SCHEMA_VERSION = 2

# Required archive members per model kind (beyond schema_version itself).
_MODEL_KEYS = {
    "pcoa": ("kind", "metric", "eigvecs", "eigvals", "d2_colmean",
             "d2_grand", "sample_ids"),
    "pca": ("kind", "metric", "eigvecs", "eigvals", "s_colmean",
            "s_grand", "sample_ids"),
}


class ModelFormatError(ValueError):
    """A saved-model .npz that cannot be safely interpreted: truncated/
    corrupt archive, pre-versioning file, future schema, or a missing
    required field — always with the offending field/cause named."""


@dataclass(frozen=True)
class ProjectionModel:
    """A loaded, validated saved model — everything projection needs.

    ``colmean``/``grand`` are the kind-appropriate centering statistics
    (reference D^2 column/grand means for PCoA, similarity column/grand
    means for PCA); arrays are float64 exactly as persisted (consumers
    cast to f32 at the device boundary, matching the offline path).
    """

    kind: str
    metric: str
    eigvecs: np.ndarray
    eigvals: np.ndarray
    colmean: np.ndarray
    grand: float
    sample_ids: list[str]
    schema_version: int = SCHEMA_VERSION
    # Which accuracy-ladder rung fitted the eigenpairs (core.config
    # SOLVER_LADDER). Optional in the archive (older files predate the
    # ladder and were all dense): absent reads as "exact". Today only
    # exact-rung models exist on disk — the sketch rungs cannot persist
    # the dense centering statistics projection needs — but the field is
    # the forward-compatible provenance record the ladder mandates.
    solver: str = "exact"

    @property
    def n_ref(self) -> int:
        return int(self.eigvecs.shape[0])

    @property
    def n_components(self) -> int:
        return int(self.eigvecs.shape[1])

    def digest(self) -> str:
        """Content fingerprint — namespaces the serving result cache so
        a hot-reloaded model can never serve a stale cached result."""
        h = hashlib.sha256()
        h.update(
            f"{self.kind}:{self.metric}:{self.schema_version}".encode()
        )
        for a in (self.eigvecs, self.eigvals, self.colmean):
            h.update(np.ascontiguousarray(a).tobytes())
        h.update(np.float64(self.grand).tobytes())
        return h.hexdigest()[:16]


def load_model(path: str) -> ProjectionModel:
    """Load + validate a saved model, friendly-erroring on bad files.

    Every failure mode a long-lived server can hit on reload gets a
    :class:`ModelFormatError` naming the cause: unreadable/truncated
    archive, a pre-versioning model (no ``schema_version``), a model
    from a NEWER build, an unknown ``kind``, or a missing required
    field. A raw ``KeyError``/``BadZipFile`` never escapes."""
    try:
        npz = np.load(path, allow_pickle=False)
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        raise ModelFormatError(
            f"model file {path!r} is not a readable .npz archive "
            f"({e}) — truncated or corrupt? refit with "
            "pcoa/pca --save-model"
        ) from None
    try:
        with npz as mdl:
            names = set(mdl.files)
            if "schema_version" not in names:
                raise ModelFormatError(
                    f"model file {path!r} has no 'schema_version' field "
                    "— written by a pre-versioning build; refit it with "
                    "pcoa/pca --save-model to upgrade"
                )
            version = int(mdl["schema_version"])
            if version > SCHEMA_VERSION:
                raise ModelFormatError(
                    f"model file {path!r} has schema_version {version}, "
                    f"newer than this build understands "
                    f"({SCHEMA_VERSION}) — upgrade the code or refit"
                )
            if "kind" not in names:
                raise ModelFormatError(
                    f"model file {path!r} is missing the 'kind' field"
                )
            kind = str(mdl["kind"])
            if kind == "factorized":
                # Sketch-ladder artifact (models/factorized.py) — same
                # archive container, its own field set and family
                # dispatch. Lazy import: this module must stay loadable
                # without the models package (and the reverse import,
                # factorized -> ModelFormatError, is top-level there).
                from spark_examples_tpu.models import factorized as FZ

                return FZ.parse_factorized(mdl, path, version)
            if kind not in _MODEL_KEYS:
                raise ModelFormatError(
                    f"model file {path!r} has unknown kind {kind!r} "
                    f"(supported: {sorted(_MODEL_KEYS)})"
                )
            missing = [k for k in _MODEL_KEYS[kind] if k not in names]
            if missing:
                raise ModelFormatError(
                    f"model file {path!r} (kind={kind!r}, "
                    f"schema_version {version}) is missing required "
                    f"field(s) {missing} — truncated save or a file "
                    "from an incompatible build; refit with "
                    "pcoa/pca --save-model"
                )
            cm, gr = (("s_colmean", "s_grand") if kind == "pca"
                      else ("d2_colmean", "d2_grand"))
            return ProjectionModel(
                kind=kind,
                metric=str(mdl["metric"]),
                eigvecs=np.asarray(mdl["eigvecs"], np.float64),
                eigvals=np.asarray(mdl["eigvals"], np.float64),
                colmean=np.asarray(mdl[cm], np.float64),
                grand=float(mdl[gr]),
                sample_ids=[str(s) for s in mdl["sample_ids"]],
                schema_version=version,
                solver=(str(mdl["solver"]) if "solver" in names
                        else "exact"),
            )
    except (ValueError, OSError, zipfile.BadZipFile) as e:
        # Member reads of a truncated-but-openable archive fail here.
        if isinstance(e, ModelFormatError):
            raise
        raise ModelFormatError(
            f"model file {path!r} could not be decoded ({e}) — "
            "truncated or corrupt? refit with pcoa/pca --save-model"
        ) from None


def check_projectable(model) -> tuple[str, ...]:
    """The (kind, metric) projectability gate, shared by the offline job
    and the serving engine — returns the cross statistics to stream."""
    if getattr(model, "kind", None) == "factorized":
        from spark_examples_tpu.models import factorized as FZ

        return FZ.check_factorized_projectable(model)
    stats = PROJECTABLE.get((model.kind, model.metric))
    if stats is None:
        raise ValueError(
            f"model (kind={model.kind!r}, metric={model.metric!r}) is "
            f"not projectable (supported: {sorted(PROJECTABLE)})"
        )
    return stats


def check_reference_panel(model: ProjectionModel, source_ref) -> None:
    """Refuse a reference source that is not the panel the model was
    fitted on (shared by the offline job and the serving engine) —
    cross-statistics against the wrong genotypes would project silently
    wrong coordinates."""
    if model.sample_ids != list(source_ref.sample_ids):
        raise ValueError(
            "reference source sample ids do not match the panel the "
            f"model was fitted on ({source_ref.n_samples} vs "
            f"{len(model.sample_ids)} samples"
            + (
                "; ids differ"
                if source_ref.n_samples == len(model.sample_ids)
                else ""
            )
            + ") — cross-distances against the wrong genotypes "
            "would project silently wrong coordinates"
        )


def save_model(
    path: str,
    coords: np.ndarray,
    eigenvalues: np.ndarray,
    distance: np.ndarray,
    sample_ids: list[str],
    metric: str,
    solver: str = "exact",
) -> None:
    """Persist a fitted PCoA embedding for later projection.

    ``coords`` = V sqrt(lambda) (the job output), so V is recovered by
    dividing out sqrt(lambda); components with lambda <= 0 are dropped
    (they carry no metric information and their V is undefined).
    ``solver`` records which accuracy-ladder rung fitted the eigenpairs.
    """
    vals = np.asarray(eigenvalues, np.float64)
    keep = vals > 0
    v = np.asarray(coords, np.float64)[:, keep] / np.sqrt(vals[keep])
    d2 = np.asarray(distance, np.float64) ** 2
    np.savez(
        path,
        schema_version=np.int64(SCHEMA_VERSION),
        kind=np.asarray("pcoa"),
        eigvecs=v,
        eigvals=vals[keep],
        d2_colmean=d2.mean(axis=0),
        d2_grand=np.float64(d2.mean()),
        sample_ids=np.asarray(sample_ids),
        metric=np.asarray(metric),
        solver=np.asarray(solver),
    )


def save_pca_model(
    path: str,
    coords: np.ndarray,
    eigenvalues: np.ndarray,
    similarity: np.ndarray,
    sample_ids: list[str],
    solver: str = "exact",
) -> None:
    """Persist a fitted PCA embedding (the flagship driver) for later
    projection.

    ``coords`` = V lambda (projection C v = lambda v), so V is
    recovered by dividing out lambda; zero eigenvalues are dropped.
    Projection of a new row needs the REFERENCE similarity's column
    means and grand mean (the J ... J centering applied to cross rows).
    ``solver`` records which accuracy-ladder rung fitted the eigenpairs.
    """
    vals = np.asarray(eigenvalues, np.float64)
    keep = np.abs(vals) > 1e-12
    v = np.asarray(coords, np.float64)[:, keep] / vals[keep]
    s = np.asarray(similarity, np.float64)
    np.savez(
        path,
        schema_version=np.int64(SCHEMA_VERSION),
        kind=np.asarray("pca"),
        eigvecs=v,
        eigvals=vals[keep],
        s_colmean=s.mean(axis=0),
        s_grand=np.float64(s.mean()),
        sample_ids=np.asarray(sample_ids),
        metric=np.asarray("shared-alt"),
        solver=np.asarray(solver),
    )


@partial(jax.jit, donate_argnums=(0,))
def _update_cross(acc, bn, br):
    upd = genotype.cross_stats(bn, br, tuple(acc))
    return {k: acc[k] + upd[k] for k in acc}


@dataclass(frozen=True)
class CrossPlan:
    """Distribution plan for the (A, N_ref) cross accumulation.

    ``tile2d`` mirrors the symmetric gram's config-4 layout applied to
    the rectangular case: the accumulator is tiled — NEW-cohort rows
    over mesh axis ``i``, REFERENCE columns over ``j`` — and the two
    genotype blocks are row-sharded to match (bn over ``i``, br over
    ``j``). Every device then owns both operand slices its tile needs,
    so the update contracts the shared variant axis with NO collectives
    and no device ever holds a full (A, N_ref) leaf — the property that
    lets projection/cross-kinship scale to the 76k reference panels the
    symmetric path already handles (VERDICT r4 weak #5).
    """

    mesh: Mesh
    mode: str  # replicated | tile2d

    @property
    def acc_sharding(self) -> NamedSharding:
        if self.mode == "tile2d":
            return meshes.tile2d(self.mesh)
        return meshes.replicated(self.mesh)

    @property
    def new_block_sharding(self) -> NamedSharding | None:
        # None = default single-device placement: in replicated mode the
        # update runs on one chip, and a replicated device_put would
        # multiply the ingest-bound host->device traffic by the device
        # count for nothing.
        if self.mode == "tile2d":
            return meshes.rows_i(self.mesh)
        return None

    @property
    def ref_block_sharding(self) -> NamedSharding | None:
        if self.mode == "tile2d":
            return meshes.rows_j(self.mesh)
        return None


def cross_plan_for(
    mesh: Mesh, a: int, n_ref: int, n_stats: int, mode: str = "auto"
) -> CrossPlan:
    """Pick (or validate) a cross-accumulation mode.

    ``auto`` tiles when the accumulators would blow the per-chip budget
    (same threshold as the symmetric planner); tiling requires both
    sample axes divisible by their mesh axis — the replicated fallback
    is chosen otherwise (an uneven tile grid would need shard_map
    padding nothing currently justifies). Multi-host jobs always run
    replicated (per-process accumulation over each ingest partition,
    one additive merge at the end): the tile2d transport row-shards
    blocks over a process-spanning mesh, which per-process partitioned
    streams cannot feed — auto never selects it there, and asking for
    it explicitly is refused with the remedy named.
    """
    n_i, n_j = mesh.devices.shape
    divisible = a % n_i == 0 and n_ref % n_j == 0
    multihost = jax.process_count() > 1
    if mode == "tile2d" and multihost:
        raise ValueError(
            "the tile2d cross plan is single-host; multi-host cross "
            "jobs run replicated (per-process accumulation, additive "
            "merge) — use --gram-mode replicated (or auto), or run on "
            "one host to tile across its chips"
        )
    if mode == "variant":
        # The symmetric planner's variant mode has no cross analogue
        # (there is no psum-merged replicated product here) — a job
        # config carrying --gram-mode variant gets the replicated cross
        # path, exactly as it did before cross plans existed.
        mode = "replicated"
    if mode == "auto":
        from spark_examples_tpu.parallel.gram_sharded import _ACC_BUDGET

        acc_bytes = 4 * a * n_ref * max(1, n_stats)
        mode = (
            "tile2d"
            if not multihost and mesh.devices.size > 1 and divisible
            and acc_bytes > _ACC_BUDGET
            else "replicated"
        )
    if mode == "tile2d" and not divisible:
        raise ValueError(
            f"cross tile2d needs ({a}, {n_ref}) divisible by the mesh "
            f"{mesh.devices.shape}"
        )
    # graftlint: disable=registry-literal  # the cross plan's OWN two-mode set, not the gram-mode registry: variant mode has no cross analogue (a cross block is consumed once, never accumulated variant-sharded)
    if mode not in ("replicated", "tile2d"):
        raise ValueError(f"unknown cross mode {mode!r}")
    return CrossPlan(mesh, mode)


# Explicit, clearable memo of compiled tiled cross updates (was a
# module-level @lru_cache: in a long-lived server its entries pin mesh/
# sharding objects and compiled shard_map closures for the life of the
# process, across model hot-reloads — clear_caches() is the reload
# hook). Bounded LRU so even a pathological plan churn cannot grow it
# past the old lru_cache ceiling.
_CROSS_UPDATE_CACHE: OrderedDict = OrderedDict()
_CROSS_UPDATE_CAPACITY = 32


def _cross_update_tiled(plan: CrossPlan, stats: tuple[str, ...]):
    key = (plan, stats)
    fn = _CROSS_UPDATE_CACHE.get(key)
    if fn is not None:
        _CROSS_UPDATE_CACHE.move_to_end(key)
        return fn
    fn = _build_cross_update_tiled(plan, stats)
    _CROSS_UPDATE_CACHE[key] = fn
    while len(_CROSS_UPDATE_CACHE) > _CROSS_UPDATE_CAPACITY:
        _CROSS_UPDATE_CACHE.popitem(last=False)
    return fn


def clear_caches() -> None:
    """Drop every compiled-closure cache this module holds: the tiled
    cross-update memo above and the shape-keyed jit caches of the
    module-level compiled functions. A long-lived server calls this on
    model hot-reload so stale meshes/shardings/compiled programs cannot
    accumulate across reloads (tests pin that the caches do not grow
    unboundedly under a reload loop)."""
    _CROSS_UPDATE_CACHE.clear()
    for fn in (_update_cross, _af_moments, _cross_phi, _project,
               _project_pca, _den_diag, _project_factorized_dual):
        clear = getattr(fn, "clear_cache", None)
        if clear is not None:
            clear()


def _build_cross_update_tiled(plan: CrossPlan, stats: tuple[str, ...]):
    """shard_map cross update: each device contracts its (rows_i bn,
    rows_j br) operand slices into its own tile — collective-free by
    construction (the same reasoning as the symmetric tile2d update:
    jit annotations alone let the SPMD partitioner pick pathological
    re-shardings, so the choreography is explicit)."""
    acc_specs = {k: P(meshes.AXIS_I, meshes.AXIS_J) for k in stats}

    def body(acc, bn, br):
        upd = genotype.cross_stats(bn, br, stats)
        return {k: acc[k] + upd[k] for k in stats}

    fn = meshes.shard_map(
        body, mesh=plan.mesh,
        in_specs=(acc_specs, P(meshes.AXIS_I, None),
                  P(meshes.AXIS_J, None)),
        out_specs=acc_specs, check_vma=False,
    )
    acc_sh = {k: plan.acc_sharding for k in stats}
    return jax.jit(
        fn,
        in_shardings=(acc_sh, plan.new_block_sharding,
                      plan.ref_block_sharding),
        out_shardings=acc_sh,
        donate_argnums=(0,),
    )


@jax.jit
def _af_moments(bn, br):
    """Per-block sufficient statistics for the cross-cohort allele-
    frequency correlation: (count, Sx, Sy, Sxy, Sxx, Syy) over variants
    called in BOTH cohorts. Six scalars per block — the streaming
    Pearson-r between the cohorts' AFs, the cheap detector for swapped
    REF/ALT coding (flips send r strongly negative). Per-block values
    stay small (<= block width); the caller reduces across blocks in
    float64 on the host, where f32 running sums would erode the
    cancellation-prone variance terms at the 40M-variant scale."""
    x, cx, _, _ = genotype.af_stats(bn)
    y, cy, _, _ = genotype.af_stats(br)
    both = ((cx > 0) & (cy > 0)).astype(jnp.float32)
    x = x * both
    y = y * both
    return jnp.stack([
        both.sum(), x.sum(), y.sum(), (x * y).sum(),
        (x * x).sum(), (y * y).sum(),
    ])


def _check_af_concordance(moments: np.ndarray, a: int, n_ref: int) -> None:
    """Warn when the cohorts' allele frequencies disagree — the classic
    silent killer of cross-dataset analyses is REF/ALT coding swapped
    in one cohort (dosage g becomes 2-g), which degrades projection and
    kinship with no error anywhere.

    Two regimes, because AF estimates from a SMALL cohort are noisy
    (per-variant sampling variance ~ E[p(1-p)]/2A attenuates the
    correlation toward 0 even for perfectly concordant coding — a
    single projected sample tops out around r ~ 0.3-0.5):

    - r strongly NEGATIVE: sampling noise only attenuates toward zero,
      never below it, so this always indicates allele flips — warn at
      any cohort size.
    - r merely LOW: only meaningful when both cohorts are large enough
      (>= 20 samples each) that attenuation is a few percent; then a
      sub-0.5 correlation indicates a variant-order or coding mismatch.
    """
    n, sx, sy, sxy, sxx, syy = (float(v) for v in moments)
    if n < 20:
        return  # too few shared variants to judge
    vx = sxx - sx * sx / n
    vy = syy - sy * sy / n
    if vx <= 0 or vy <= 0:
        return  # a cohort with constant AF carries no signal
    r = (sxy - sx * sy / n) / np.sqrt(vx * vy)
    flip = r < -0.2
    low = r < 0.5 and min(a, n_ref) >= 20
    if flip or low:
        import warnings

        warnings.warn(
            f"cross-cohort allele-frequency correlation is {r:.3f} "
            "(expected ~1 for the same variant set with the same "
            "REF/ALT coding)"
            + (
                " — negative correlation means one cohort's alleles "
                "are swapped (dosage 2-g)"
                if flip
                else " — likely a variant-order or coding mismatch"
            )
            + "; results will be wrong until the cohorts are harmonized",
            RuntimeWarning,
            stacklevel=3,
        )


def _accumulate_cross(job, source_new, source_ref,
                      stats: tuple[str, ...], timer,
                      plan: CrossPlan | None = None,
                      den_metric: str | None = None):
    """Stream BOTH cohorts in lockstep and accumulate the requested
    cross statistics — the shared engine of projection and
    cross-kinship. Zips manually so a length mismatch is an ERROR, not
    a silent prefix (and without consulting n_variants up front — for
    VCF/filtered sources that property is a full extra parse); block
    boundaries and, when available, positions are validated per block.
    Returns (accumulators, n_variants, qden); under a tile2d ``plan``
    the accumulators stay tiled across the mesh (no full (A, N_ref)
    leaf on any device — verified per job by an assert_tiled check).
    ``den_metric`` additionally folds that dual-sketch metric's query
    denominator diagonal (the (A,) self-term of factorized-pcoa
    projection) into the same pass; qden is None when unset."""
    multihost = jax.process_count() > 1
    a = source_new.n_samples
    n_ref = source_ref.n_samples
    bv = job.ingest.block_variants
    if plan is None:
        plan = cross_plan_for(
            meshes.make_mesh(shape=job.compute.mesh_shape), a, n_ref,
            len(stats), job.compute.gram_mode,
        )
    if multihost and plan.mode == "tile2d":
        # Defensive: cross_plan_for already refuses this (auto never
        # selects it multi-host); only a hand-built CrossPlan can get
        # here, and proceeding would corrupt the accumulation.
        raise ValueError(
            "the tile2d cross plan is single-host; multi-host cross "
            "jobs run replicated"
        )
    if plan.mode == "tile2d":
        update = _cross_update_tiled(plan, tuple(stats))
        # Tiles allocate directly on their devices — a host-side zeros
        # here would materialize the very (A, N_ref) leaf the tiling
        # exists to avoid (~23 GB at the 76k-vs-76k regime).
        acc = {
            k: jnp.zeros((a, n_ref), jnp.int32, device=plan.acc_sharding)
            for k in stats
        }
    else:
        update = _update_cross
        acc = {k: jnp.zeros((a, n_ref), jnp.int32) for k in stats}
    moment_blocks = []  # tiny per-block device vectors, reduced in f64
    qden = (jnp.zeros((a,), jnp.float32)
            if den_metric is not None else None)
    n_variants = 0
    n_matmuls = sum(len(genotype.CROSS_STATS[s]) for s in stats)
    with timer.phase("gram"):
        depth = job.ingest.prefetch_blocks
        it_new = iter(stream_to_device(
            source_new, bv, prefetch=depth,
            sharding=plan.new_block_sharding,
        ))
        it_ref = iter(stream_to_device(
            source_ref, bv, prefetch=depth,
            sharding=plan.ref_block_sharding,
        ))
        while True:
            nxt_new = next(it_new, None)
            nxt_ref = next(it_ref, None)
            if (nxt_new is None) != (nxt_ref is None):
                short = "new" if nxt_new is None else "reference"
                raise ValueError(
                    f"the {short} cohort stream ended first — both "
                    "cohorts must carry the same variant set (a silent "
                    "prefix-zip would compute statistics on partial "
                    "data)"
                )
            if nxt_new is None:
                break
            (bn, mn), (br, mr) = nxt_new, nxt_ref
            if (mn.start, mn.stop) != (mr.start, mr.stop):
                raise ValueError(
                    "new/reference streams diverged: new block "
                    f"[{mn.start}, {mn.stop}) vs ref [{mr.start}, "
                    f"{mr.stop}) — both cohorts must carry the same "
                    "variants (same sites, same order)"
                )
            if (
                mn.positions is not None
                and mr.positions is not None
                and not np.array_equal(mn.positions, mr.positions)
            ):
                raise ValueError(
                    f"new/reference positions differ in block "
                    f"[{mn.start}, {mn.stop}) — not the same variant set"
                )
            acc = update(acc, bn, br)
            if qden is not None:
                qden = _den_diag(qden, bn, metric=den_metric)
            moment_blocks.append(_af_moments(bn, br))
            timer.add("gram_flops",
                      2.0 * a * n_ref * bn.shape[1] * n_matmuls)
            timer.add("ingest_bytes", bn.size + br.size)
            n_variants = mn.stop
        acc = hard_sync(acc)
    if plan.mode == "tile2d":
        from spark_examples_tpu.parallel.pcoa_sharded import assert_tiled

        for k, v in acc.items():
            assert_tiled(v, plan, f"cross accumulator {k!r}")
    # One stacked fetch, then a float64 host reduction — per-block
    # f32 values are small and exact-ish; the cross-block sums (and
    # the cancellation-prone variance terms downstream) are not.
    moments = (
        np.asarray(jnp.stack(moment_blocks), np.float64).sum(axis=0)
        if moment_blocks else np.zeros(6, np.float64)
    )
    if multihost:
        # Additive cross-process merge — the cross path's analogue of
        # the symmetric gram's psum: every process accumulated only its
        # variant partition, and every statistic here is a sum over
        # variants. The matrices ride a device-side all-reduce (one
        # array's worth of DCN traffic, not P host copies); the merged
        # counts fit int32 whenever the job's budget does (the caller's
        # _check_int32_budget sees the merged n_variants). Processes
        # with empty partitions carry zero accumulators and MUST still
        # enter these collectives. The (6,) moment vector stays on the
        # control-plane allgather: jax's default f32 would round its
        # f64 cancellation-prone sums.
        from spark_examples_tpu.parallel import multihost as mh

        acc = {
            k: jnp.asarray(mh.allreduce_sum(np.asarray(v)))
            for k, v in acc.items()
        }
        if qden is not None:
            # Integer-valued f32 per-process partial sums — the merged
            # diagonal is exact for the same reason the per-process
            # one is (totals far below 2^24).
            qden = jnp.asarray(mh.allreduce_sum(np.asarray(qden)))
        n_variants = int(mh.allgather(np.int64(n_variants)).sum())
        moments = mh.allgather(moments).sum(axis=0)
    if moments[0] > 0:
        _check_af_concordance(moments, a, n_ref)
    return acc, n_variants, qden


@partial(jax.jit, static_argnames=())
def _cross_phi(hh, opp, hcn, hcr):
    """KING-robust kinship between cohorts (same estimator as the
    symmetric ops/distances.py 'king' branch, both het counts over
    pairwise-complete variants). No diagonal to pin: rows and columns
    are different samples — a phi ~ 0.5 entry IS the finding (the same
    individual present in both cohorts)."""
    den = (hcn + hcr).astype(jnp.float32)
    num = (hh - 2 * opp).astype(jnp.float32)
    return jnp.where(den > 0, num / den, 0.0)


def cross_kinship_job(job, source_new, source_ref):
    """(A, N_ref) KING-robust kinship between two cohorts — the
    cross-dataset QC screen: phi ~ 0.5 flags the same individual in
    both cohorts, ~0.25 first-degree relatives, ~0 unrelated. Streams
    both cohorts once; only the (A, N_ref) phi matrix comes home."""
    from spark_examples_tpu.pipelines.runner import SimilarityResult

    timer = PhaseTimer()
    acc, n_variants, _ = _accumulate_cross(
        job, source_new, source_ref, ("hh", "opp", "hcn", "hcr"), timer
    )
    R._check_int32_budget("king", n_variants, 2)
    with timer.phase("finalize"):
        phi = np.asarray(hard_sync(_cross_phi(
            acc["hh"], acc["opp"], acc["hcn"], acc["hcr"]
        )))
    if job.output_path and jax.process_index() == 0:
        # Multi-host: the merged statistics are identical on every
        # process; exactly one owns the output files.
        pio.write_matrix(job.output_path, source_new.sample_ids, phi,
                         kind="similarity",
                         col_ids=source_ref.sample_ids)
    return SimilarityResult(
        similarity=phi,
        distance=np.maximum(0.5 - phi, 0.0),
        sample_ids=source_new.sample_ids,
        metric="king",
        timer=timer,
        n_variants=n_variants,
    )


@partial(jax.jit, static_argnames=("metric",))
def _project(acc, d2_colmean, d2_grand, eigvecs, eigvals, metric):
    """Gower out-of-sample projection: the kernel's declared cross
    squared-distance (``CrossSpec.d2`` — e.g. ibs's ``(d1/2m)^2``,
    jaccard's ``2 - 2J``) centered with the reference statistics, then
    projected onto the fitted eigenvectors. ``metric`` is static — each
    projectable kernel compiles its own finalize once."""
    d2 = kernels.get(metric).cross.d2(acc)
    b = -0.5 * (
        d2
        - d2.mean(axis=1, keepdims=True)
        - d2_colmean[None, :]
        + d2_grand
    )
    return (b @ eigvecs) / jnp.sqrt(eigvals)[None, :]


@partial(jax.jit, static_argnames=())
def _project_pca(s, s_colmean, s_grand, eigvecs):
    """PCA out-of-sample: center the cross similarity row with the
    reference's column/grand means (J S J applied to a new row), then
    project onto the eigenvectors — for a training row this reproduces
    c_row @ V = lambda v_row = its fitted coordinates exactly."""
    c = (
        s.astype(jnp.float32)
        - s.mean(axis=1, keepdims=True)
        - s_colmean[None, :]
        + s_grand
    )
    return c @ eigvecs


@partial(jax.jit, static_argnames=("metric",), donate_argnums=(0,))
def _den_diag(qden, block, metric):
    """Accumulate the QUERY side of a dual-sketch metric's denominator
    diagonal from one genotype block: the kernel's declared ``den_terms``
    evaluated row-against-itself (the (q, q) entry of the denominator
    gram, never the matrix). Matches the fit-side exact diagonal the
    corrected rung streamed into the saved model's ``scale`` — both are
    plain sums of integer-valued per-variant terms, so the f32 running
    sum here is exact (and partition-invariant) up to 2^24, far above
    any per-sample total a 65k-variant panel can produce."""
    spec = kernels.get(metric).sketch
    ops = spec.operands(block)
    for (left, right, w) in spec.den_terms:
        qden = qden + w * (ops[left] * ops[right]).sum(axis=1)
    return qden


@partial(jax.jit, static_argnames=("metric",))
def _project_factorized_dual(acc, qden, scale, scale_floor, colmean,
                             grand, eigvecs, eigvals, metric):
    """Factorized out-of-sample projection for a pcoa-family model: the
    kernel's cross NUMERATOR scaled by both denominator diagonals gives
    the scaled similarity s~; with self-similarity pinned at 1 the
    Gower double-centering of d2 = 2 - 2 s~ reduces exactly to
    ``b = s~ - rowmean - colmean + grand`` in s~-space (the identity
    the saved colmean/grand are expressed in), then coordinates are
    ``(b @ V) / sqrt(lambda)`` — an (A, k) product, no (N, N) anywhere.
    The query scale gets the same floor the fit applied to the panel's."""
    num = kernels.get(metric).cross.num(acc)
    aq = jnp.maximum(jnp.sqrt(jnp.maximum(qden, 0.0)), scale_floor)
    s = num / (aq[:, None] * scale[None, :])
    b = s - s.mean(axis=1, keepdims=True) - colmean[None, :] + grand
    return (b @ eigvecs) / jnp.sqrt(eigvals)[None, :]


def pcoa_project_job(
    job: JobConfig,
    model_path: str,
    source_new,
    source_ref,
) -> CoordsOutput:
    """Project ``source_new``'s samples into a fitted reference space.

    Both sources must stream the SAME variants in the same order (the
    reference workflow: both cohorts genotyped at the panel's sites);
    block widths and, when available, positions are validated as the
    two streams are zipped.
    """
    model = load_model(model_path)
    kind, metric = model.kind, model.metric
    stats = check_projectable(model)
    check_reference_panel(model, source_ref)
    eigvecs = jnp.asarray(model.eigvecs, jnp.float32)
    eigvals = jnp.asarray(model.eigvals, jnp.float32)
    center_stats = (
        jnp.asarray(model.colmean, jnp.float32),
        jnp.float32(model.grand),
    )
    # Factorized models project family-wise: the pca family reuses the
    # dense _project_pca program verbatim; the pcoa family needs the
    # query denominator diagonal folded into the same cross pass.
    family = getattr(model, "family", kind)
    needs_qden = kind == "factorized" and family == "pcoa"

    timer = PhaseTimer()
    acc, n_variants, qden = _accumulate_cross(
        job, source_new, source_ref, stats, timer,
        den_metric=metric if needs_qden else None,
    )
    # Same int32-exactness guard as the symmetric path (the kernel's
    # registered increment bound); warns when counts may have wrapped.
    R._check_int32_budget(metric, n_variants, 2)
    # One fused device step: finalize cross statistics + out-of-sample
    # centering + eigvec products; only the (A, k) coordinates come home.
    with timer.phase("eigh"):
        if family == "pca":
            coords = np.asarray(hard_sync(_project_pca(
                acc["s"], center_stats[0], center_stats[1], eigvecs
            )))
        elif needs_qden:
            coords = np.asarray(hard_sync(_project_factorized_dual(
                acc, qden,
                jnp.asarray(model.scale, jnp.float32),
                jnp.float32(model.scale_floor),
                center_stats[0], center_stats[1],
                eigvecs, eigvals, metric=metric,
            )))
        else:
            coords = np.asarray(hard_sync(_project(
                acc, center_stats[0], center_stats[1],
                eigvecs, eigvals, metric=metric,
            )))
    out = CoordsOutput(source_new.sample_ids, coords,
                       np.asarray(eigvals), timer, n_variants)
    if job.output_path and jax.process_index() == 0:
        pio.write_coords_tsv(job.output_path, out.sample_ids, out.coords)
    return out

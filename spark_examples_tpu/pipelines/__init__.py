from spark_examples_tpu.pipelines import examples, io, jobs, runner  # noqa: F401
from spark_examples_tpu.pipelines.jobs import (  # noqa: F401
    pcoa_job,
    similarity_matrix_job,
    variants_pca_job,
)

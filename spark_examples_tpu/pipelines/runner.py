"""The streaming job core shared by every pipeline entrypoint.

Call stack mirror of the reference's ``VariantsPcaDriver.main``
(SURVEY.md §3.1), with each Spark-shaped stage replaced by its TPU-native
successor:

    conf parse            -> core.config dataclasses
    SparkContext          -> core.meshes (mesh + jax.distributed)
    VariantsRDD ingest    -> ingest.GenotypeSource streaming blocks
    pair-emit/reduceByKey -> parallel.gram_sharded accumulation (psum)
    collect + MLlib eigh  -> on-device centering + ops.eigh
    saveAsTextFile        -> TSV/npy writers (pipelines.io)

``--backend=cpu-reference`` routes the same job through the NumPy oracle
instead — the stand-in for the reference's Spark-MLlib baseline and the
measured denominator of BASELINE.md.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import numpy as np

from spark_examples_tpu import kernels
from spark_examples_tpu.core import checkpoint as ckpt
from spark_examples_tpu.core import meshes, telemetry
from spark_examples_tpu.core.config import (
    BRAYCURTIS_METHODS,
    PACK_STREAMS,
    IngestConfig,
    JobConfig,
)
from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync
from spark_examples_tpu.ingest import (
    PlinkSource,
    SyntheticSource,
    VcfSource,
    load_packed,
)
from spark_examples_tpu.ingest.prefetch import stream_to_device
from spark_examples_tpu.ops import distances, gram
from spark_examples_tpu.parallel import gram_sharded
from spark_examples_tpu.utils import oracle


# finalize is cheap math over N x N pieces, but run eagerly it dispatches
# one tunnel round-trip per op — jit it once per metric.
_finalize_jit = jax.jit(distances.finalize, static_argnames=("metric",))


@partial(jax.jit, static_argnames=("metric", "field"))
def finalize_field(acc, metric: str, field: str):
    """One finalized matrix ("similarity" or "distance"), left on device.

    The device-resident job routes (pcoa/pca) consume exactly one of the
    two finalize outputs and never materialize it on the host — at
    N=2504 the full pair is ~50 MB, a multi-second D2H round-trip on a
    slow host link that run_similarity pays only because a persisted
    matrix is that job's actual output."""
    return distances.finalize(acc, metric)[field]


def _maybe_partitioned(cls, cfg: IngestConfig):
    """Range-filterable file source, optionally split into concurrent
    sub-range readers — the reference's FixedContigSplits(n): one reader
    per sub-range, read concurrently, consumed in range order (identical
    stream for position-sorted non-overlapping ranges — the
    partitioner's own precondition). Applies uniformly to every source
    class taking ``(path, references=...)``."""
    if cfg.splits_per_contig > 1 and cfg.references:
        from spark_examples_tpu.ingest.partitioned import PartitionedSource
        from spark_examples_tpu.ingest.source import partition_ranges

        parts = [
            cls(cfg.path, references=(r,))
            for r in partition_ranges(cfg.references, cfg.splits_per_contig)
        ]
        return PartitionedSource(parts, max_workers=cfg.ingest_workers)
    return cls(cfg.path, references=tuple(cfg.references))


def build_source(cfg: IngestConfig):
    """IngestConfig -> GenotypeSource (the reference's L2/L3 factory),
    with QC and LD-prune stream transforms layered on per config
    (QC first — pruning monomorphic/high-missing variants is the QC
    filter's job, and LD r^2 on them is undefined-ish anyway).

    Under ``jax.distributed`` (process_count > 1) the returned source is
    this process's *partition* of the input — a genomic-range share for
    ``--references``-driven file sources, a block-aligned variant window
    otherwise — so each host reads only its slice (the reference's
    one-partition-per-executor split). Stream transforms then apply
    per-partition; for LD pruning that means windows do not see LD
    context across partition boundaries (same contract as its existing
    per-contig resets).
    """
    meshes.maybe_init_distributed()
    if jax.process_count() > 1:
        src = _build_local_partition(cfg)
    else:
        src = _build_raw_source(cfg)
    if cfg.maf > 0.0 or cfg.max_missing < 1.0:
        from spark_examples_tpu.ingest.filters import FilteredSource

        src = FilteredSource(src, maf=cfg.maf,
                             max_missing=cfg.max_missing)
    if cfg.ld_r2 > 0.0:
        from spark_examples_tpu.ingest.ldprune import LdPruneSource

        carry = cfg.ld_carry or max(1, cfg.ld_window // 4)
        src = LdPruneSource(src, r2=cfg.ld_r2, window=cfg.ld_window,
                            carry=carry)
    return src


def _build_local_partition(cfg: IngestConfig):
    """This process's share of the input (multi-host ingest partition).

    File sources with ``--references``: each contig range is split into
    ``process_count`` sub-ranges (partition_ranges — the reference's
    FixedContigSplits applied across hosts) and this process keeps its
    index's share of every contig. Random-access sources (synthetic
    generation, memmapped packed/array stores): a block-aligned variant
    window. Streaming file sources WITHOUT references would force every
    process to parse the whole file just to discard most of it — that
    defeats partitioned ingest, so it is rejected with the fix named.
    """
    from spark_examples_tpu.ingest.source import (
        EmptyShare,
        WindowSource,
        partition_ranges,
        window_for_process,
    )

    p, n_proc = jax.process_index(), jax.process_count()
    if cfg.source in ("vcf", "plink", "parquet", "store") and cfg.references:
        mine = []
        for ref in cfg.references:
            parts = partition_ranges([ref], n_proc)
            mine.extend(parts[p::n_proc])
        if not mine:
            # references=[] would mean "no filter" (read EVERYTHING) —
            # a process whose share came out empty must stream nothing.
            return EmptyShare(_build_raw_source(cfg))
        sub = dataclasses.replace(cfg, references=mine)
        return _build_raw_source(sub)
    if cfg.source in ("vcf", "parquet"):
        raise ValueError(
            f"multi-host {cfg.source} ingest needs --references so each "
            "process can read only its genomic range; alternatively "
            "`pack` the file once and run the job from the packed store"
        )
    src = _build_raw_source(cfg)
    start, stop = window_for_process(
        src.n_variants, cfg.block_variants, p, n_proc
    )
    return WindowSource(src, start, stop)


def _maybe_retrying(src, cfg: IngestConfig, reopen=None):
    """Wrap a file-backed source in the transient-IO retry boundary
    (ingest/resilient.py): a flaky read re-opens and seeks back to the
    cursor instead of killing a 40M-variant job. Synthetic sources do
    no IO and stay unwrapped; io_retries=0 disables.

    ``reopen`` (a fresh-source factory) is required for sources whose
    file state lives on the object (the packed store's memmap) — without
    it a retry would re-slice the same dead mapping; handle-per-blocks()
    sources (VCF/plink/parquet) re-open naturally."""
    if cfg.io_retries <= 0:
        return src
    from spark_examples_tpu.ingest.resilient import RetryingSource, RetryPolicy

    return RetryingSource(
        src,
        policy=RetryPolicy(max_retries=cfg.io_retries,
                           backoff_s=cfg.io_retry_backoff_s),
        # Mix the process index into the jitter seed: hosts sharing one
        # flaky filesystem must NOT retry in lockstep (identical seeds
        # would synchronize every backoff and re-trigger the overload
        # the jitter exists to spread out).
        seed=cfg.seed + jax.process_index(),
        reopen=reopen,
    )


def _build_raw_source(cfg: IngestConfig):
    if cfg.source == "synthetic":
        return SyntheticSource(
            n_samples=cfg.n_samples,
            n_variants=cfg.n_variants,
            n_populations=cfg.n_populations,
            seed=cfg.seed,
        )
    if cfg.source == "vcf":
        if not cfg.path:
            raise ValueError("vcf source requires ingest.path")
        return _maybe_retrying(_maybe_partitioned(VcfSource, cfg), cfg)
    if cfg.source == "packed":
        if not cfg.path:
            raise ValueError("packed source requires ingest.path")
        return _maybe_retrying(load_packed(cfg.path), cfg,
                               reopen=lambda: load_packed(cfg.path))
    if cfg.source == "store":
        if not cfg.path:
            raise ValueError(
                "store source requires ingest.path (the compacted "
                "store directory — `ingest --output-path <dir>`), or "
                "the one-flag form --source store:<dir>"
            )
        from spark_examples_tpu.store import open_store

        def _open():
            src = open_store(cfg.path,
                             cache_bytes=cfg.store_cache_mb << 20,
                             readahead_chunks=cfg.readahead_chunks,
                             readahead_chunks_max=cfg.readahead_chunks_max,
                             replicas=tuple(cfg.store_replicas))
            # --references answered from the catalog's position index
            # (the range-partitioner surface), no chunk touched.
            if cfg.references:
                return src.restrict(cfg.references)
            return src

        # mmap-backed like the packed store: a retry must rebuild the
        # mapping, not re-slice a dead one.
        return _maybe_retrying(_open(), cfg, reopen=_open)
    if cfg.source == "plink":
        if not cfg.path:
            raise ValueError(
                "plink source requires ingest.path (fileset prefix or "
                ".bed path)"
            )
        return _maybe_retrying(_maybe_partitioned(PlinkSource, cfg), cfg)
    if cfg.source == "parquet":
        if not cfg.path:
            raise ValueError("parquet source requires ingest.path")
        from spark_examples_tpu.ingest.parquet import ParquetSource

        return _maybe_retrying(_maybe_partitioned(ParquetSource, cfg), cfg)
    raise ValueError(f"unknown source {cfg.source!r}")


@dataclass
class SimilarityResult:
    similarity: np.ndarray
    distance: np.ndarray
    sample_ids: list[str]
    metric: str
    timer: PhaseTimer
    n_variants: int


@dataclass
class GramRun:
    """A finished accumulation whose N x N state is still on-device,
    laid out per ``plan`` — the handoff between the streaming stage and
    either host materialization (run_similarity) or the fully-sharded
    solve (parallel/pcoa_sharded, the 76k route where no host/device
    ever sees the whole matrix)."""

    acc: dict
    plan: gram_sharded.GramPlan
    sample_ids: list[str]
    metric: str
    timer: PhaseTimer
    n_variants: int


def plan_for_job(job: JobConfig, source) -> gram_sharded.GramPlan:
    """The distribution plan this job will run under (mesh + mode)."""
    meshes.maybe_init_distributed()
    mesh = meshes.make_mesh(shape=job.compute.mesh_shape)
    metric = job.compute.metric or "ibs"
    return gram_sharded.plan_for(
        mesh, source.n_samples, metric, job.compute.gram_mode
    )


def run_gram(job: JobConfig, source, timer: PhaseTimer,
             plan: gram_sharded.GramPlan | None = None,
             on_block=None) -> GramRun:
    """Stream the cohort through the sharded accumulator (the reference's
    pair-emit/reduceByKey stage). Device-resident result; finalization is
    the caller's choice of route.

    ``on_block(acc, blocks_done, meta)``: optional hook after each
    block's update — the streaming incremental-PCoA driver refreshes
    its eigpair sketch here. Must treat ``acc`` as read-only.
    """
    cfg = job.compute
    n = source.n_samples
    metric = cfg.metric or "ibs"
    if plan is None:
        plan = plan_for_job(job, source)
    if cfg.pack_stream not in PACK_STREAMS:
        raise ValueError(f"unknown pack_stream {cfg.pack_stream!r}")
    # auto: pack only kernels declaring pack_auto (inputs are dosages
    # by definition) — dot/euclidean accept arbitrary int8 tables the
    # 2-bit codec cannot represent, and their registrations say so.
    kern = kernels.get(metric)
    packed = cfg.pack_stream == "packed" or (
        cfg.pack_stream == "auto" and kern.pack_auto
    )
    # tile2d block reassembly: resolve "auto" HERE, where the job's
    # block shape is known, so the ring/gather choice is one decision
    # per plan (the kernel's FLOPs model against a shard hop —
    # gram_sharded.resolve_transport) instead of per block; the
    # ring-divisibility contract is checked at the same spot, with the
    # flags named, before any tracing.
    transport = cfg.tile2d_transport
    if plan.mode == "tile2d" and plan.mesh.devices.size > 1:
        if transport == "auto":
            transport = gram_sharded.resolve_transport(
                plan, metric, n, job.ingest.block_variants, packed)
        if transport == "ring":
            from spark_examples_tpu.ingest.prefetch import padded_width

            gram_sharded.check_ring_divisible(
                padded_width(job.ingest.block_variants,
                             plan.block_shards, packed),
                plan, packed,
            )
    else:
        transport = "gather"
    # Contraction lowering: auto resolves to the fused packed Pallas
    # kernel on real TPU hardware (and downgrades to reference wherever
    # fused cannot run); an explicit --gram-lowering fused raises with
    # the blocker named. The gauge makes the choice observable — the
    # bench fused column and the glossary read it.
    lowering = gram.resolve_gram_lowering(
        cfg.gram_lowering, metric, packed,
        n_devices=plan.mesh.devices.size, plan_mode=plan.mode,
    )
    telemetry.gauge_set("gram.lowering",
                        1.0 if lowering == "fused" else 0.0)
    update = gram_sharded.make_update(
        plan, metric, packed=packed, grm_precise=cfg.grm_precise,
        transport=transport, lowering=lowering,
    )

    bv = job.ingest.block_variants
    start_variant = 0
    acc = None
    # Only kernels whose int32 budget scales with the table's values
    # (dot/euclidean: value_scaled_budget) consume the producer-side
    # max; other metrics skip the per-block host scan entirely.
    stream_stats: dict | None = (
        {} if kern.value_scaled_budget and not packed else None
    )
    if cfg.checkpoint_dir:
        restored = ckpt.load(cfg.checkpoint_dir, metric, source.sample_ids,
                             block_variants=bv, plan=plan)
        if restored is not None:
            acc, start_variant, saved_stats = restored
            if stream_stats is not None:
                stream_stats.update(saved_stats)
    if acc is None:
        acc = gram_sharded.init_sharded(plan, n, metric)

    if jax.process_count() > 1:
        return _finish_gram_multihost(
            job, source, timer, plan, update, acc, start_variant, metric,
            packed, stream_stats, on_block,
        )

    # Variant-sharded placement needs the variant axis divisible by the
    # mesh size; padding with MISSING is semantically free.
    n_shards = plan.block_shards
    blocks_done = 0
    last_stop = start_variant
    with timer.phase("gram"):
        # Per-block span: the full block PERIOD — producer/queue wait,
        # H2D transfer, update dispatch, hooks, checkpoint — begun
        # before each pull so the timeline shows where the wall-clock
        # actually went (the histogram under the same name feeds the
        # bench digest's block p50/p95).
        sp = telemetry.begin("gram.block", cat="gram")
        for block, meta in stream_to_device(
            source, bv, start_variant, sharding=plan.block_sharding,
            pad_multiple=n_shards, pack=packed, stats=stream_stats,
            prefetch=job.ingest.prefetch_blocks,
        ):
            acc = update(acc, block)
            # FLOP credit uses the TRUE streamed variant span, not the
            # padded device width (a ragged final block pads to the
            # byte/shard grid with missing calls, which contribute no
            # matmul work worth crediting) — the multihost loop already
            # counts meta spans, and the bench fused column divides by
            # the same honest denominator on both lowerings.
            v_eff = meta.stop - meta.start
            timer.add("gram_flops", gram.flops_per_block(n, v_eff, metric))
            timer.add("ingest_bytes", block.size)  # bytes actually shipped
            blocks_done += 1
            last_stop = meta.stop
            if on_block is not None:
                on_block(acc, blocks_done, meta)
            if (
                cfg.checkpoint_dir
                and cfg.checkpoint_every_blocks
                and blocks_done % cfg.checkpoint_every_blocks == 0
            ):
                hard_sync(acc)
                ckpt.save(
                    cfg.checkpoint_dir, acc, meta.stop, metric, bv,
                    source.sample_ids, stream_stats=stream_stats,
                    plan=plan,
                )
            sp.end(index=blocks_done, stop=meta.stop)
            sp = telemetry.begin("gram.block", cat="gram")
        sp.cancel()  # the final begin only saw the stream's end
        acc = hard_sync(acc)

    # The stream already counted the variants (meta.stop of the final
    # block) — avoid source.n_variants, which for VCF may re-parse the file.
    n_variants = last_stop if last_stop > 0 else source.n_variants
    _check_int32_budget(
        metric, n_variants, (stream_stats or {}).get("max_value", 2)
    )
    return GramRun(acc, plan, source.sample_ids, metric, timer, n_variants)


def _finish_gram_multihost(job, source, timer, plan, update, acc,
                           start_variant, metric, packed, stream_stats,
                           on_block) -> GramRun:
    """The multi-host tail of run_gram: consensus-stepped streaming of
    per-process partitions into the shared accumulator
    (parallel/multihost.py). ``source`` is this process's partition
    (build_source already windowed/range-split it); cursors and
    checkpoints are per-process over the local partition."""
    from spark_examples_tpu.parallel import multihost as mh

    cfg = job.compute
    n = source.n_samples
    bv = job.ingest.block_variants
    blocks_done = 0
    last_stop = start_variant
    with timer.phase("gram"):
        sp = telemetry.begin("gram.block", cat="gram")
        for gblock, meta in mh.stream_global_blocks(
            source, bv, start_variant, plan, packed, stats=stream_stats,
            prefetch=job.ingest.prefetch_blocks,
        ):
            acc = update(acc, gblock)
            blocks_done += 1
            if meta is not None:
                # FLOP/byte credit: this process's own share only (the
                # per-process timers are per-host truths; the global
                # numbers are their allgathered sums).
                w_local = meta.stop - meta.start
                timer.add("gram_flops",
                          gram.flops_per_block(n, w_local, metric))
                from spark_examples_tpu.ingest import bitpack

                timer.add(
                    "ingest_bytes",
                    n * (bitpack.packed_width(w_local) if packed
                         else w_local),
                )
                last_stop = meta.stop
            if on_block is not None:
                on_block(acc, blocks_done, meta)
            if (
                cfg.checkpoint_dir
                and cfg.checkpoint_every_blocks
                and blocks_done % cfg.checkpoint_every_blocks == 0
            ):
                hard_sync(acc)
                ckpt.save(
                    cfg.checkpoint_dir, acc, last_stop, metric, bv,
                    source.sample_ids, stream_stats=stream_stats,
                    plan=plan,
                )
            # A consensus step where this process fed an all-MISSING
            # padding slab is NOT a block: recording it into gram.block
            # would drag the idle rank's p50/p95 toward zero and make
            # the straggler comparison read the starved rank as the
            # fast one. It gets an instant marker instead.
            if meta is not None:
                sp.end(index=blocks_done)
            else:
                sp.cancel()
                telemetry.event("gram.pad_step", cat="gram",
                                index=blocks_done)
            sp = telemetry.begin("gram.block", cat="gram")
        sp.cancel()
        acc = hard_sync(acc)

    # Global totals: sum of every process's partition.
    n_variants = int(mh.allgather(np.int64(last_stop)).sum())
    if stream_stats is not None:
        stream_stats["max_value"] = int(
            mh.allgather(
                np.int64(stream_stats.get("max_value", 0))
            ).max()
        )
    _check_int32_budget(
        metric, n_variants, (stream_stats or {}).get("max_value", 2)
    )
    return GramRun(acc, plan, source.sample_ids, metric, timer, n_variants)


def run_sketch_pass(
    job: JobConfig,
    source,
    timer: PhaseTimer,
    plan: gram_sharded.GramPlan,
    update,
    state: dict,
    start_variant: int = 0,
    packed: bool = False,
    block_flops=None,
    save_cb=None,
):
    """One streamed pass of the sketch solver (solvers/): the SAME
    staged-ring feed, ``gram.block`` spans, cursor semantics, and
    checkpoint cadence as :func:`run_gram` — only the accumulator is the
    (N, r) sketch state instead of N x N pieces, so the supervisor's
    heartbeat progress token, the bench telemetry digest, and the
    kill/resume machinery all see a sketch job exactly as they see a
    gram job.

    ``block_flops(v_effective)``: per-block FLOP credit (the sketch's
    two skinny matmuls — crediting the dense gram count here would fake
    a ~N/r speedup). ``save_cb(state, cursor)``: checkpoint hook, called
    at the job's ``checkpoint_every_blocks`` cadence after a hard sync;
    the driver owns the manifest extras (pass index, probe seed).

    Returns ``(state, n_variants)`` with the state hard-synced.
    """
    cfg = job.compute
    bv = job.ingest.block_variants
    n_shards = plan.block_shards
    blocks_done = 0
    last_stop = start_variant
    with timer.phase("gram"):
        sp = telemetry.begin("gram.block", cat="gram")
        for block, meta in stream_to_device(
            source, bv, start_variant, sharding=plan.block_sharding,
            pad_multiple=n_shards, pack=packed,
            prefetch=job.ingest.prefetch_blocks,
        ):
            state = update(state, block)
            if block_flops is not None:
                v_eff = block.shape[1] * (4 if packed else 1)
                timer.add("gram_flops", block_flops(v_eff))
            timer.add("ingest_bytes", block.size)
            blocks_done += 1
            last_stop = meta.stop
            if (
                save_cb is not None
                and cfg.checkpoint_every_blocks
                and blocks_done % cfg.checkpoint_every_blocks == 0
            ):
                hard_sync(state)
                save_cb(state, meta.stop)
            sp.end(index=blocks_done, stop=meta.stop)
            sp = telemetry.begin("gram.block", cat="gram")
        sp.cancel()  # the final begin only saw the stream's end
        state = hard_sync(state)
    n_variants = last_stop if last_stop > 0 else source.n_variants
    return state, n_variants


def run_similarity(job: JobConfig, source=None) -> SimilarityResult:
    """Stream the cohort and produce the pairwise similarity + distance
    matrices (the SimilarityMatrix job surface, SURVEY.md §3.2)."""
    timer = PhaseTimer()
    cfg = job.compute
    if source is None:
        with timer.phase("ingest_setup"):
            source = build_source(job.ingest)
    metric = cfg.metric or "ibs"  # None -> driver default

    # Table-family kernels (braycurtis) carry their own dense-table
    # runner instead of riding the gram accumulator — the registry
    # capability flag, so adding one never touches this dispatch.
    table = kernels.get(metric).table_runner
    if table is not None:
        return table(job, source, timer)

    if cfg.backend == "cpu-reference":
        return _run_similarity_cpu(job, source, timer)

    g = run_gram(job, source, timer)
    with timer.phase("finalize"):
        out = hard_sync(_finalize_jit(g.acc, metric))
    from spark_examples_tpu.parallel.multihost import fetch_replicated

    return SimilarityResult(
        similarity=fetch_replicated(out["similarity"]),
        distance=fetch_replicated(out["distance"]),
        sample_ids=g.sample_ids,
        metric=metric,
        timer=timer,
        n_variants=g.n_variants,
    )


def _check_int32_budget(metric: str, n_variants: int, max_value: int) -> None:
    """Warn when a stream outruns the int32 accumulators' exactness bound.

    Counts are bit-exact while worst-per-variant-increment * n_variants
    < 2^31 (ops/genotype.py): each kernel registers its increment bound
    (gram.MAX_INCREMENT, from the registry); kernels with
    value_scaled_budget (dot/euclidean on arbitrary int8 tables) are
    bounded by max_value^2 (tracked by the prefetch producer). Float-
    accumulating kernels (GRM) — rounding, not wraparound, is their
    failure mode — register no bound and are exempt.
    """
    kern = kernels.maybe_get(metric)
    if kern is None or kern.max_increment is None:
        return
    inc = kern.max_increment
    if kern.value_scaled_budget:
        inc = max(inc, max(1, int(max_value)) ** 2)
    if inc * n_variants >= 2**31:
        import warnings

        warnings.warn(
            f"metric {metric!r}: {n_variants} variants with per-variant "
            f"increment bound {inc} exceeds the int32 accumulator budget "
            f"(2^31) — pairwise counts may have wrapped; split the stream "
            "into shorter jobs and merge finalized statistics instead",
            RuntimeWarning,
            stacklevel=2,
        )


def _materialize(source, block_variants: int) -> np.ndarray:
    blocks = [b for b, _ in source.blocks(block_variants)]
    return np.concatenate(blocks, axis=1)


def _run_braycurtis(job: JobConfig, source, timer: PhaseTimer) -> SimilarityResult:
    """Bray-Curtis path: dense (N, F) abundance table, blocked elementwise
    kernel (BASELINE.md config 3). The dosage matrix doubles as the count
    table when the source is genotypes."""
    with timer.phase("ingest"):
        x = _materialize(source, job.ingest.block_variants)
        x = np.maximum(x, 0)  # missing (-1) counts as absence
    method = job.compute.braycurtis_method
    if method not in BRAYCURTIS_METHODS:
        raise ValueError(
            f"unknown braycurtis_method {method!r}; "
            f"valid: {' | '.join(BRAYCURTIS_METHODS)}"
        )
    # Pallas is both the fastest and an exact lowering on real TPU
    # hardware (BASELINE.md config 3: 0.33 s vs matmul 1.25 s at N=10k)
    # — but it is a Mosaic kernel, TPU-only, so every other backend
    # (CPU, GPU) takes the portable exact path: the same shared
    # auto-lowering rule the gram fused path follows.
    method = kernels.resolve_lowering(
        method, jax.default_backend(), fused="pallas", reference="exact"
    )
    if job.compute.backend == "cpu-reference":
        with timer.phase("distance"):
            d = oracle.cpu_braycurtis(x)
    elif method == "matmul":
        with timer.phase("distance"):
            d = np.asarray(
                distances.braycurtis_matmul(
                    x, levels=job.compute.braycurtis_levels
                )
            )
    elif method == "pallas":
        from spark_examples_tpu.ops.pallas.braycurtis_kernel import (
            braycurtis_pallas,
        )

        # Mosaic compiles only for TPU; on the CPU backend (tests,
        # local[*] analogue) run the same kernel under the interpreter.
        interpret = jax.default_backend() == "cpu"
        with timer.phase("distance"):
            d = np.asarray(braycurtis_pallas(x, interpret=interpret))
    else:
        with timer.phase("distance"):
            d = np.asarray(distances.braycurtis(x))
    return SimilarityResult(
        similarity=1.0 - d,
        distance=d,
        sample_ids=source.sample_ids,
        metric="braycurtis",
        timer=timer,
        n_variants=source.n_variants,
    )


def _run_similarity_cpu(job: JobConfig, source, timer: PhaseTimer) -> SimilarityResult:
    """The measured CPU baseline (stand-in for Spark MLlib, SURVEY.md §5)."""
    metric = job.compute.metric or "ibs"
    n = source.n_samples
    kern = kernels.get(metric)
    if kern.family == "float":
        # Float-family kernels carry their own whole-matrix oracle
        # (the GRM's within-matrix allele frequencies need the full
        # table, not additive raw products).
        with timer.phase("gram"):
            x = _materialize(source, job.ingest.block_variants)
            g = kern.oracle_similarity(x)
        return SimilarityResult(
            similarity=g,
            distance=np.asarray(distances.similarity_to_distance(g)),
            sample_ids=source.sample_ids,
            metric=metric,
            timer=timer,
            n_variants=source.n_variants,
        )
    needed = gram.PIECES_FOR_METRIC[metric]
    acc = {k: np.zeros((n, n)) for k in needed}
    with timer.phase("gram"):
        for block, _meta in source.blocks(job.ingest.block_variants):
            prods = oracle.cpu_gram_products(block, needed)
            for k in acc:
                acc[k] += prods[k]
            timer.add(
                "gram_flops", gram.flops_per_block(n, block.shape[1], metric)
            )
    with timer.phase("finalize"):
        out = oracle.cpu_finalize(gram.combine(acc, metric), metric)
    return SimilarityResult(
        similarity=out["similarity"],
        distance=out["distance"],
        sample_ids=source.sample_ids,
        metric=metric,
        timer=timer,
        n_variants=source.n_variants,
    )

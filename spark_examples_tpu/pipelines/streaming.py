"""Streaming incremental PCoA — benchmark config 5.

The reference family's aspiration (BASELINE.json:11): ingest streams in
(the BigQuery path), and principal coordinates are available *during*
the stream, not only after a terminal batch solve. The TPU-native design
exploits two facts:

- the Gram/similarity accumulator is resident and associative — after
  any block its partial state is a valid (smaller-cohort-of-variants)
  similarity matrix;
- the eigensolve's randomized subspace (ops/eigh.subspace_iterate) can
  be *warm-started*: between refreshes the accumulator changes by a
  ~1/blocks_done relative delta, so tracking the top-k eigenspace needs
  a single power step (two sharded B @ Q matmuls) per refresh instead
  of a cold solve — this is the rank-k incremental eig update named by
  the config.

Every refresh is matmul-shaped and respects the gram plan's shardings
(tile2d accumulators never widen). Snapshots are emitted every
``stream_refresh_blocks`` blocks; the final coordinates take a few
extra tightening iterations from the tracked subspace and must match a
full recompute (pinned by tests/test_streaming.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import numpy as np

from spark_examples_tpu.core import meshes, telemetry
from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync
from spark_examples_tpu.ops import distances
from spark_examples_tpu.ops.centering import gower_center
from spark_examples_tpu.ops.eigh import (
    coords_from_eigpairs,
    init_probes,
    subspace_iterate,
)
from spark_examples_tpu.parallel.gram_sharded import GramPlan, _acc_shardings
from spark_examples_tpu.pipelines import runner as R
from spark_examples_tpu.pipelines.jobs import CoordsOutput, _emit_coords

OVERSAMPLE = 32  # matches randomized_eigh's default subspace width
FINAL_ITERS = 4  # tightening steps for the terminal solve


@dataclass
class StreamSnapshot:
    """Coordinates emitted mid-stream, after ``n_variants`` variants.

    Values are materialized lazily: the refresh that produced them was
    dispatched asynchronously into the device queue (see ``on_block``
    below), so fetching at emission time would stall the stream."""

    n_variants: int
    eigenvalues: np.ndarray
    coords: np.ndarray

    def materialize(self) -> "StreamSnapshot":
        self.eigenvalues = np.asarray(self.eigenvalues)
        self.coords = np.asarray(self.coords)
        return self


@lru_cache(maxsize=32)
def _center_jit(plan: GramPlan, metric: str):
    """acc -> Gower-centered B, plan-sharded. No donation: the live
    accumulator keeps streaming after each refresh."""
    return jax.jit(
        lambda acc: gower_center(
            distances.finalize(acc, metric)["distance"]
        ),
        in_shardings=(_acc_shardings(plan, metric),),
        out_shardings=plan.acc_sharding,
    )


@lru_cache(maxsize=32)
def _refresh_jit(plan: GramPlan, k: int, iters: int):
    """(B, q) -> (vals, vecs, q_new): warm subspace refresh with the
    N x N input plan-sharded and the skinny subspace replicated."""
    repl = meshes.replicated(plan.mesh)
    return jax.jit(
        lambda b, q: subspace_iterate.__wrapped__(b, q, k, iters),
        in_shardings=(plan.acc_sharding, repl),
        out_shardings=(repl, repl, repl),
    )


def incremental_pcoa_job(
    job, source=None
) -> tuple[CoordsOutput, list[StreamSnapshot]]:
    """PCoA with mid-stream coordinate snapshots (config 5).

    Streams blocks through the sharded gram accumulator exactly like
    ``pcoa_job``; every ``compute.stream_refresh_blocks`` blocks a
    warm subspace refresh is dispatched (async — it overlaps the
    stream's transfers) and emits a snapshot. Returns the final
    coordinates (tightened from the tracked subspace) plus the
    snapshot history. The ``stream_refresh`` timer phase counts only
    dispatch; the honest refresh cost is end-to-end — streamed time
    with refreshes minus without — which bench config 5 reports.
    """
    cfg = job.compute
    refresh_every = cfg.stream_refresh_blocks
    if refresh_every <= 0:
        raise ValueError(
            "incremental_pcoa_job requires compute.stream_refresh_blocks > 0"
        )
    metric = cfg.metric or "ibs"
    if cfg.backend == "cpu-reference" or metric == "braycurtis":
        raise ValueError(
            "streaming pcoa runs on the jax backend with a gram metric"
        )
    if cfg.eigh_mode == "dense":
        raise ValueError(
            "streaming pcoa is the rank-k subspace path by construction; "
            "eigh_mode='dense' would be silently ignored — use the batch "
            "pcoa job for a dense solve"
        )
    if cfg.solver != "exact":
        raise ValueError(
            "--solver sketch/corrected applies to the batch pcoa/pca "
            "solve; the streaming incremental route tracks its own warm "
            "subspace over the LIVE N x N accumulator and would silently "
            "shadow the sketch state — drop --stream-refresh-blocks to "
            "run the sketch solver, or --solver exact to stream snapshots"
        )
    timer = PhaseTimer()
    if source is None:
        with timer.phase("ingest_setup"):
            source = R.build_source(job.ingest)
    plan = R.plan_for_job(job, source)
    k = cfg.num_pc
    n = source.n_samples
    center = _center_jit(plan, metric)
    refresh = _refresh_jit(plan, k, iters=1)

    q0 = init_probes(jax.random.key(0), n, k + OVERSAMPLE)  # p clamped to N
    state = {
        "q": jax.device_put(q0, meshes.replicated(plan.mesh)),
        "snapshots": [],
        # Last refresh's centered matrix + its variant cursor: when the
        # stream ends exactly on a refresh boundary (the common case),
        # the terminal solve reuses it instead of redoing a full N x N
        # finalize+center on a byte-identical accumulator. The
        # backpressure below bounds live B buffers: at most the held one
        # plus the one being dispatched.
        "b": None,
        "b_variants": -1,
        # Last local cursor seen — multi-host consensus steps where THIS
        # process fed a padding slab pass meta=None, but the refresh jit
        # is a collective program every process must still enter in
        # lockstep (blocks_done is the shared consensus step count).
        "last_stop": 0,
    }

    def on_block(acc, blocks_done, meta):
        if meta is not None:
            state["last_stop"] = meta.stop
        if blocks_done % refresh_every:
            return
        # Backpressure: materialize the PREVIOUS refresh's snapshot
        # before dispatching a new one. The fetch completes only after
        # the previous refresh executed, so at most one refresh (and
        # one fresh N x N centered matrix) is ever pending — unbounded
        # async dispatch would pin a B per pending refresh and blow HBM
        # at the 76k regime. The wait tracks how far device execution
        # lags the dispatch front (the transfer backlog), all of which
        # OVERLAPS the stream's own transfers — end-to-end cost ~zero
        # (bench config 5) — so it gets its own phase: charging it to
        # stream_refresh would zero out the gram-GFLOPS denominator,
        # and to gram would hide that the wall-clock was spent in
        # transfer, not refresh math.
        if state["snapshots"]:
            with timer.phase("stream_drain"):
                state["snapshots"][-1].materialize()
        with timer.phase("stream_refresh"):
            state["b"] = None  # free the held B before building the next
            # Dispatch only — NO sync on the new refresh. A barrier here
            # would wait for every in-flight block transfer ahead of it
            # in the device queue (seconds each on a slow host link),
            # charging queue-drain to the refresh phase; dispatched
            # async, the refresh runs in chip cycles a transfer-bound
            # stream leaves idle, so its true end-to-end cost is near
            # zero (bench config 5 measures it as streamed-with minus
            # streamed-without). The refresh itself is matmul-shaped and
            # tiny: one centered finalize + two B @ Q products.
            b = center(acc)
            vals, vecs, q = refresh(b, state["q"])
            coords = coords_from_eigpairs(vals, vecs)
        stop = state["last_stop"]
        state.update(q=q, b=b, b_variants=stop)
        state["snapshots"].append(StreamSnapshot(stop, vals, coords))
        # Timeline marker only — the refresh's dispatch cost is the
        # phase.stream_refresh span around it; its drain cost is
        # phase.stream_drain; its honest end-to-end cost is bench
        # config 5's streamed-with minus streamed-without.
        telemetry.event("stream.snapshot", cat="stream",
                        n_variants=stop, blocks_done=blocks_done)

    grun = R.run_gram(job, source, timer, plan=plan, on_block=on_block)
    for snap in state["snapshots"]:
        snap.materialize()  # stream is done; fetches no longer stall it

    # Terminal solve: a few tightening iterations from the tracked
    # subspace — warm, so far cheaper than a cold randomized solve.
    final = _refresh_jit(plan, k, iters=FINAL_ITERS)
    with timer.phase("eigh"):
        if state["b_variants"] == grun.n_variants and state["b"] is not None:
            b = state["b"]
        else:
            b = center(grun.acc)
        vals, vecs, _q = hard_sync(final(b, state["q"]))
    v = np.asarray(vals)
    coords = np.asarray(coords_from_eigpairs(vals, vecs))
    # eigh_iters must mirror the terminal solve actually run — the
    # _emit_coords default tracks randomized_eigh's cold-start defaults,
    # not this warm path's tightening count.
    out = _emit_coords(job, grun.sample_ids, coords, v, timer,
                       grun.n_variants, method="randomized",
                       eigh_iters=FINAL_ITERS)
    return out, state["snapshots"]

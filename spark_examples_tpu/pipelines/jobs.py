"""Job entrypoints mirroring the reference's driver surface.

- :func:`similarity_matrix_job` — the Stanford fork's SimilarityMatrix
  entrypoint (SURVEY.md §3.2): stream cohort -> persist N x N matrix.
- :func:`pcoa_job` — the fork's PCoA entrypoint (SURVEY.md §3.3): load or
  build a distance matrix -> double-center -> eig -> coords.
- :func:`variants_pca_job` — the flagship ``VariantsPcaDriver``
  (SURVEY.md §3.1): shared-alt similarity -> center -> PCs -> coords.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import numpy as np

import jax

from spark_examples_tpu.parallel.multihost import fetch_replicated
from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import (
    EIGH_ITERS_DEFAULT,
    SOLVER_RUNG_ID,
    JobConfig,
)
from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync
from spark_examples_tpu.models.pca import fit_pca
from spark_examples_tpu.models.pcoa import fit_pcoa
from spark_examples_tpu.ops.eigh import eigh_flops
from spark_examples_tpu.pipelines import io as pio
from spark_examples_tpu.pipelines.runner import SimilarityResult, run_similarity
from spark_examples_tpu.utils import oracle


@dataclass
class CoordsOutput:
    sample_ids: list[str]
    coords: np.ndarray
    eigenvalues: np.ndarray
    timer: PhaseTimer
    n_variants: int = 0
    # Fraction of TOTAL inertia per component (trace(B) = sum of ALL N
    # eigenvalues, available without computing them) — set by the PCoA
    # routes; None where no honest total exists (streaming subspace,
    # projection against a persisted model).
    proportion: np.ndarray | None = None


def similarity_matrix_job(job: JobConfig, source=None) -> SimilarityResult:
    result = run_similarity(job, source=source)
    if job.output_path and jax.process_index() == 0:
        pio.write_matrix(job.output_path, result.sample_ids,
                         result.similarity, kind="similarity")
    return result


def pcoa_job(
    job: JobConfig,
    source=None,
    matrix_path: str | None = None,
    matrix_kind: str = "auto",
) -> CoordsOutput:
    """Distance -> PCoA coords; optionally from a persisted matrix (the
    reference fork's two-job handoff), else end-to-end.

    ``matrix_kind``: whether a persisted matrix holds distances or
    similarities (similarities are Gower-transformed first — feeding a
    similarity matrix straight into -1/2 J D^2 J silently yields
    degenerate coordinates). ``auto`` trusts the file's sidecar (the
    similarity job records what it wrote) and falls back to distance.
    """
    k = job.compute.num_pc
    if matrix_path is not None and job.model_path:
        raise ValueError(
            "--save-model cannot be combined with --matrix-path: the "
            "persisted matrix does not record which metric built it, "
            "and a model stamped with the wrong metric would project "
            "silently wrong coordinates — fit the model from a cohort "
            "stream instead"
        )
    if matrix_path is not None and job.compute.solver != "exact":
        raise ValueError(
            "--solver sketch/corrected streams the cohort to avoid "
            "materializing N x N; a persisted --matrix-path IS the "
            "materialized matrix — consume it with --solver exact"
        )
    if matrix_path is not None:
        sample_ids, m, file_kind = pio.read_matrix(matrix_path)
        kind = matrix_kind if matrix_kind != "auto" else (file_kind or "distance")
        if kind == "distance":
            dist = m
        elif kind == "similarity":
            from spark_examples_tpu.ops.distances import similarity_to_distance

            dist = np.asarray(similarity_to_distance(m.astype(np.float32)))
        else:
            raise ValueError(
                f"matrix_kind must be distance|similarity, got {kind!r}"
            )
        timer = PhaseTimer()
        n_variants = 0
    else:
        timer = PhaseTimer()
        if source is None:
            with timer.phase("ingest_setup"):
                from spark_examples_tpu.pipelines.runner import build_source

                source = build_source(job.ingest)
        if job.compute.solver != "exact":
            return _sketch_route(job, source, timer, kind="pcoa")
        routed = _pcoa_device_route(job, source, timer)
        if routed is not None:
            return routed
        sim = run_similarity(job, source=source)
        # Fold the pre-route phases (ingest_setup) into the sim timer.
        sim.timer.phases.update(timer.phases)
        sample_ids, dist, timer = sim.sample_ids, sim.distance, sim.timer
        n_variants = sim.n_variants

    n = dist.shape[0]
    if job.compute.backend == "cpu-reference":
        method = "dense"
        with timer.phase("eigh"):
            coords, vals, prop = oracle.pcoa(dist, k=k)
    else:
        method = _eigh_method(job.compute.eigh_mode, n)
        with timer.phase("eigh"):
            res = hard_sync(
                fit_pcoa(dist.astype(np.float32), k=k, method=method,
                         iters=job.compute.eigh_iters,
                         oversample=job.compute.eigh_oversample)
            )
        coords, vals = fetch_replicated(res.coords), fetch_replicated(res.eigenvalues)
        prop = fetch_replicated(res.proportion_explained)
    _maybe_save_model(job, dist, coords, vals, sample_ids)
    return _emit_coords(job, sample_ids, coords, vals, timer, n_variants,
                        method=method, eigh_iters=job.compute.eigh_iters,
                        proportion=prop)


def _maybe_save_model(job, dist, coords, vals, sample_ids) -> None:
    """Persist the fitted embedding when the job asks for it
    (pipelines/project.py consumes it to place new samples)."""
    if not job.model_path or jax.process_index() != 0:
        return
    from spark_examples_tpu.pipelines.project import save_model

    save_model(job.model_path, coords, vals, fetch_replicated(dist),
               sample_ids, job.compute.metric or "ibs",
               solver=job.compute.solver)


def _sketch_route(job: JobConfig, source, timer, kind: str) -> CoordsOutput:
    """The sketch/corrected rungs of the accuracy ladder (solvers/):
    streamed range sketch + Nystrom/Rayleigh solve, no N x N anywhere.
    ``method="sketch"`` threads the solver-matched FLOP credit through
    ``_emit_coords`` (the streamed passes' FLOPs were already credited
    to gram_flops by the pass loop)."""
    from spark_examples_tpu.solvers import run_sketch_solve

    res = run_sketch_solve(job, source, timer, kind=kind)
    _maybe_save_factorized_model(job, kind, res)
    return _emit_coords(job, res.sample_ids, res.coords, res.eigenvalues,
                        timer, res.n_variants, method="sketch",
                        eigh_iters=res.passes, proportion=res.proportion)


def _maybe_save_factorized_model(job, kind: str, res) -> None:
    """Persist a sketch-rung fit as a factorized artifact when the job
    asks for it — the sketch ladder's --save-model (the savable
    rung/metric combinations were validated at config time and again by
    the driver; by here ``res`` carries the basis and the streamed
    centering statistics)."""
    if not job.model_path or jax.process_index() != 0:
        return
    from spark_examples_tpu.models.factorized import save_factorized_model

    metric = ("shared-alt" if kind == "pca"
              else (job.compute.metric or "ibs"))
    save_factorized_model(
        job.model_path,
        family="pca" if kind == "pca" else "pcoa",
        metric=metric,
        eigenvectors=res.eigvecs,
        eigenvalues=res.eigenvalues,
        colmean=res.colmean,
        grand=res.grand,
        sample_ids=res.sample_ids,
        solver=res.rung,
        rank=res.rank,
        seed=res.seed,
        scale=res.scale,
        scale_floor=res.scale_floor,
    )


def _emit_coords(job: JobConfig, sample_ids, coords, vals, timer,
                 n_variants: int, method: str,
                 eigh_iters: int = EIGH_ITERS_DEFAULT,
                 proportion=None) -> CoordsOutput:
    """Shared output tail of every PCoA route: solver-matched FLOP
    credit, result assembly, optional TSV persistence. ``eigh_iters``
    must match the randomized solver's actual iteration count (the
    sharded PCA route runs more than the default); the oversample is
    always the job's own knob — every randomized call site passes
    ``job.compute.eigh_oversample`` to its solver."""
    # FLOP credit must match the solver actually run (the randomized
    # path's whole point is doing far fewer FLOPs than dense ~9n^3) —
    # including the probe width k + oversample, which scales every
    # B @ Q product (ADVICE r5 finding 3). The sketch method's probe
    # width is --sketch-rank (its B @ Q products were streamed and
    # already credited to gram_flops; this is the solve-stage residue),
    # so its effective oversample is rank - k and ``eigh_iters`` carries
    # the pass count.
    oversample = (job.compute.sketch_rank - job.compute.num_pc
                  if method == "sketch" else job.compute.eigh_oversample)
    timer.add("eigh_flops", eigh_flops(len(sample_ids), method=method,
                                       k=job.compute.num_pc,
                                       oversample=oversample,
                                       iters=eigh_iters))
    # Every coords-emitting job records its accuracy-ladder rung — the
    # sketch driver also publishes it up front, but the exact routes
    # only pass through here, and a rung that is only observable for
    # two of its three values is a glossary lie.
    telemetry.gauge_set(
        "solver.rung", float(SOLVER_RUNG_ID[job.compute.solver])
    )
    out = CoordsOutput(
        sample_ids, fetch_replicated(coords), fetch_replicated(vals), timer,
        n_variants,
        proportion=(fetch_replicated(proportion)
                    if proportion is not None else None),
    )
    # Multi-host: exactly one process owns the output files (the
    # reference's driver-writes-output contract).
    if job.output_path and jax.process_index() == 0:
        pio.write_coords_tsv(job.output_path, sample_ids, out.coords)
    return out


def _pcoa_device_route(job: JobConfig, source, timer) -> CoordsOutput | None:
    """Device-resident streamed PCoA: gram accumulators -> finalize ->
    center -> eigh -> coords without the N x N matrix ever touching the
    host (only the (N, k) coordinates come back). Two variants by plan:

    - tile2d (the config-4 / 76k-exome regime): everything stays
      tile-sharded via parallel.pcoa_sharded — no single *device* holds
      the full matrix either;
    - replicated/variant: the matrix is device-dense, but still skips
      run_similarity's host materialization (similarity + distance D2H
      plus the eigh re-upload — ~75 MB of round-trip at N=2504 that a
      slow host link turns into many seconds of dead time).

    Returns None when the job needs a dense host route instead
    (cpu-reference backend, braycurtis's table path, dense eigh on a
    tiled plan); the caller falls back to run_similarity.
    """
    from spark_examples_tpu.models.pcoa import fit_pcoa
    from spark_examples_tpu.parallel.pcoa_sharded import pcoa_coords_sharded
    from spark_examples_tpu.pipelines import runner

    cfg = job.compute
    metric = cfg.metric or "ibs"
    if cfg.backend == "cpu-reference":
        return None
    from spark_examples_tpu import kernels

    if not kernels.get(metric).is_gram:
        return None  # table-family kernels take the dense host route
    plan = runner.plan_for_job(job, source)
    if plan.mode == "tile2d" and cfg.eigh_mode == "dense":
        return None  # dense eigh requires the materialized matrix
    if plan.mode == "tile2d" and job.model_path:
        # Fail BEFORE streaming the cohort: discovering this after a
        # multi-hour 76k-regime accumulation would discard all of it.
        raise ValueError(
            "--save-model needs the dense distance matrix for the "
            "projection centering statistics; the tile2d plan never "
            "materializes it — fit the model with gram_mode=variant"
        )
    grun = runner.run_gram(job, source, timer, plan=plan)
    if plan.mode == "tile2d":
        res = pcoa_coords_sharded(plan, grun.acc, metric, k=cfg.num_pc,
                                  oversample=cfg.eigh_oversample,
                                  iters=cfg.eigh_iters, timer=timer)
        method = "randomized"
    else:
        with timer.phase("finalize"):
            dist = hard_sync(
                runner.finalize_field(grun.acc, metric, "distance")
            )
        method = _eigh_method(cfg.eigh_mode, dist.shape[0])
        with timer.phase("eigh"):
            res = hard_sync(fit_pcoa(dist, k=cfg.num_pc, method=method,
                                     iters=cfg.eigh_iters,
                                     oversample=cfg.eigh_oversample))
        _maybe_save_model(job, dist, fetch_replicated(res.coords),
                          fetch_replicated(res.eigenvalues), grun.sample_ids)
    return _emit_coords(job, grun.sample_ids, fetch_replicated(res.coords),
                        fetch_replicated(res.eigenvalues), timer,
                        grun.n_variants, method=method,
                        eigh_iters=cfg.eigh_iters,
                        proportion=fetch_replicated(res.proportion_explained))


def variants_pca_job(job: JobConfig, source=None) -> CoordsOutput:
    """The flagship driver: shared-alt similarity -> centered PCA.

    The metric is fixed by the driver's definition (the reference's
    VariantsPcaDriver counts shared alt carriers); a config explicitly
    naming any other metric is warned about rather than silently
    overridden (the CLI rejects it outright). ``metric=None`` — the
    dataclass default — means "driver's choice" and is silent.
    """
    if job.compute.metric not in (None, "shared-alt"):
        import warnings

        warnings.warn(
            f"variants_pca_job ignores compute.metric={job.compute.metric!r} "
            "and always uses 'shared-alt'",
            UserWarning,
            stacklevel=2,
        )
    job = job.replace(
        compute=dataclasses.replace(job.compute, metric="shared-alt")
    )
    k = job.compute.num_pc

    if job.compute.backend != "cpu-reference":
        # Device-resident route: similarity never leaves the chip; only
        # the (N, k) projections come home (see _pcoa_device_route).
        from spark_examples_tpu.pipelines import runner

        timer = PhaseTimer()
        if source is None:
            with timer.phase("ingest_setup"):
                from spark_examples_tpu.pipelines.runner import build_source

                source = build_source(job.ingest)
        if job.compute.solver != "exact":
            return _sketch_route(job, source, timer, kind="pca")
        plan = runner.plan_for_job(job, source)
        if plan.mode == "tile2d" and job.model_path:
            # Fail BEFORE streaming (projection needs the dense
            # similarity's centering statistics, which the tile2d route
            # never materializes).
            raise ValueError(
                "--save-model needs the dense similarity matrix for "
                "the projection centering statistics; fit the model "
                "with gram_mode=variant"
            )
        grun = runner.run_gram(job, source, timer, plan=plan)
        if plan.mode == "tile2d":
            # The 76k regime: similarity -> center -> top-|lambda| eig
            # all tile2d-sharded (parallel/pcoa_sharded.py) — the host
            # fallback would materialize the N x N matrix the tiling
            # exists to avoid.
            from spark_examples_tpu.parallel.pcoa_sharded import (
                pca_coords_sharded,
            )

            iters = job.compute.eigh_iters
            res = pca_coords_sharded(plan, grun.acc, "shared-alt", k=k,
                                     oversample=job.compute.eigh_oversample,
                                     iters=iters, timer=timer)
            return _emit_coords(job, grun.sample_ids,
                                fetch_replicated(res.coords),
                                fetch_replicated(res.eigenvalues), timer,
                                grun.n_variants, method="randomized",
                                eigh_iters=iters)  # honest FLOP credit
        with timer.phase("finalize"):
            sim_dev = hard_sync(
                runner.finalize_field(grun.acc, "shared-alt",
                                      "similarity")
            )
        with timer.phase("eigh"):
            res = hard_sync(fit_pca(sim_dev, k=k))
        # sim_dev passed as-is: the helper's early return keeps the
        # N x N matrix on device unless a model save actually needs it
        # (the route's contract: only (N, k) projections come home).
        _maybe_save_pca_model(job, sim_dev, fetch_replicated(res.coords),
                              fetch_replicated(res.eigenvalues),
                              grun.sample_ids)
        return _emit_coords(job, grun.sample_ids,
                            fetch_replicated(res.coords),
                            fetch_replicated(res.eigenvalues), timer,
                            grun.n_variants, method="dense")

    # cpu-reference backend only (the jax backend always returned above):
    # the measured MLlib-route oracle.
    if job.compute.solver != "exact":
        raise ValueError(
            "--solver sketch/corrected runs on the jax backend; the CPU "
            "oracle implements the dense reference route only"
        )
    sim = run_similarity(job, source=source)
    with sim.timer.phase("eigh"):
        coords, vals = oracle.pca_mllib_route(
            sim.similarity, k=k, return_values=True
        )
    _maybe_save_pca_model(job, sim.similarity, coords, vals,
                          sim.sample_ids)
    return _emit_coords(job, sim.sample_ids, coords, vals, sim.timer,
                        sim.n_variants, method="dense")


def _maybe_save_pca_model(job, similarity, coords, vals, sample_ids):
    if not job.model_path or jax.process_index() != 0:
        return  # before any np.asarray: no D2H unless actually saving
    from spark_examples_tpu.pipelines.project import save_pca_model

    save_pca_model(job.model_path, coords, vals, fetch_replicated(similarity),
                   sample_ids, solver=job.compute.solver)


def _eigh_method(eigh_mode: str, n: int) -> str:
    if eigh_mode == "auto":
        return "randomized" if n > 16384 else "dense"
    return {"dense": "dense", "randomized": "randomized"}[eigh_mode]

"""Smoke-test-tier example jobs — the reference's ``SearchVariantsExample*``
drivers (SURVEY.md §3.4: Klotho rs9536314 / BRCA1 genotype histograms
across a cohort) rebuilt over the block-streaming ingest.

The per-variant genotype histogram is one jitted reduction over the
sample axis per block (4 one-hot sums), so the "search" tier rides the
same ingest machinery as the flagship pipeline — as it did in the
reference (same VariantsRDD, no linear-algebra tail).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _block_histogram(block: jnp.ndarray) -> jnp.ndarray:
    """(N, v) int8 dosages -> (v, 4) counts of [hom-ref, het, hom-alt,
    missing] across samples."""
    return jnp.stack(
        [
            (block == 0).sum(axis=0),
            (block == 1).sum(axis=0),
            (block == 2).sum(axis=0),
            (block == -1).sum(axis=0),
        ],
        axis=1,
    )


@dataclass
class VariantCounts:
    contig: str | None
    position: int  # genomic position when known, else global index
    hom_ref: int
    het: int
    hom_alt: int
    missing: int

    @property
    def allele_freq(self) -> float:
        called = self.hom_ref + self.het + self.hom_alt
        return (self.het + 2 * self.hom_alt) / (2 * called) if called else 0.0


@jax.jit
def _block_sample_counts(block: jnp.ndarray) -> jnp.ndarray:
    """(N, v) int8 dosages -> (N, 3) per-sample counts of
    [called, het, hom-alt] over the block's variants."""
    return jnp.stack(
        [
            (block >= 0).sum(axis=1),
            (block == 1).sum(axis=1),
            (block == 2).sum(axis=1),
        ],
        axis=1,
    )


@dataclass
class SampleStats:
    sample_id: str
    n_variants: int
    n_called: int
    n_het: int
    n_hom_alt: int

    @property
    def call_rate(self) -> float:
        return self.n_called / self.n_variants if self.n_variants else 0.0

    @property
    def het_rate(self) -> float:
        """Heterozygosity over CALLED genotypes — the standard per-sample
        QC statistic (outliers flag contamination or inbreeding)."""
        return self.n_het / self.n_called if self.n_called else 0.0


def sample_stats(source, block_variants: int = 8192) -> list[SampleStats]:
    """Per-sample QC statistics over one streaming pass: call rate and
    heterozygosity (the cohort-side complement of the per-variant
    ``genotype_histogram`` tier). The accumulator is an (N, 3) int32
    vector resident on device; blocks ride the same ingest machinery as
    every other pipeline."""
    acc = None
    n_variants = 0
    for block, meta in source.blocks(block_variants):
        counts = _block_sample_counts(block)
        acc = counts if acc is None else acc + counts
        n_variants = meta.stop
    if acc is None:
        return []
    a = np.asarray(acc)
    return [
        SampleStats(sid, n_variants, int(a[i, 0]), int(a[i, 1]),
                    int(a[i, 2]))
        for i, sid in enumerate(source.sample_ids)
    ]


def genotype_histogram(
    source,
    block_variants: int = 8192,
    positions: set[int] | None = None,
) -> list[VariantCounts]:
    """Genotype histograms per variant, optionally restricted to a set of
    genomic positions (the Klotho/BRCA1 'search' shape).

    Per block the work is one jitted reduction plus vectorized position
    matching — a filtered search touches no per-variant Python at all on
    blocks with no hits, and a full scan builds its result rows from one
    ``tolist()`` per block rather than per-element array indexing."""
    out: list[VariantCounts] = []
    # None = no filter (full scan); an EMPTY set matches nothing —
    # distinct cases, so test identity, not truthiness.
    pos_arr = (
        np.fromiter(positions, dtype=np.int64)
        if positions is not None
        else None
    )
    if pos_arr is not None and pos_arr.size == 0:
        return out
    for block, meta in source.blocks(block_variants):
        blk_pos = (
            np.asarray(meta.positions, dtype=np.int64)
            if meta.positions is not None
            else np.arange(meta.start, meta.stop, dtype=np.int64)
        )
        if pos_arr is not None:
            keep = np.nonzero(np.isin(blk_pos, pos_arr))[0]
            if keep.size == 0:
                continue  # no matches: skip the reduction entirely
        else:
            keep = None
        hist = np.asarray(_block_histogram(block))
        if keep is not None:
            hist, blk_pos = hist[keep], blk_pos[keep]
        out.extend(
            VariantCounts(meta.contig, int(p), h0, h1, h2, hm)
            for p, (h0, h1, h2, hm) in zip(blk_pos, hist.tolist())
        )
    return out

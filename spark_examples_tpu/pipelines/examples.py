"""Smoke-test-tier example jobs — the reference's ``SearchVariantsExample*``
drivers (SURVEY.md §3.4: Klotho rs9536314 / BRCA1 genotype histograms
across a cohort) rebuilt over the block-streaming ingest.

The per-variant genotype histogram is one jitted reduction over the
sample axis per block (4 one-hot sums), so the "search" tier rides the
same ingest machinery as the flagship pipeline — as it did in the
reference (same VariantsRDD, no linear-algebra tail).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _block_histogram(block: jnp.ndarray) -> jnp.ndarray:
    """(N, v) int8 dosages -> (v, 4) counts of [hom-ref, het, hom-alt,
    missing] across samples."""
    return jnp.stack(
        [
            (block == 0).sum(axis=0),
            (block == 1).sum(axis=0),
            (block == 2).sum(axis=0),
            (block == -1).sum(axis=0),
        ],
        axis=1,
    )


@dataclass
class VariantCounts:
    contig: str | None
    position: int  # genomic position when known, else global index
    hom_ref: int
    het: int
    hom_alt: int
    missing: int

    @property
    def allele_freq(self) -> float:
        called = self.hom_ref + self.het + self.hom_alt
        return (self.het + 2 * self.hom_alt) / (2 * called) if called else 0.0


def genotype_histogram(
    source,
    block_variants: int = 8192,
    positions: set[int] | None = None,
) -> list[VariantCounts]:
    """Genotype histograms per variant, optionally restricted to a set of
    genomic positions (the Klotho/BRCA1 'search' shape)."""
    out: list[VariantCounts] = []
    for block, meta in source.blocks(block_variants):
        hist = None
        for j in range(block.shape[1]):
            pos = (
                int(meta.positions[j])
                if meta.positions is not None
                else meta.start + j
            )
            if positions is not None and pos not in positions:
                continue
            if hist is None:
                hist = np.asarray(_block_histogram(block))
            h = hist[j]
            out.append(
                VariantCounts(meta.contig, pos, int(h[0]), int(h[1]),
                              int(h[2]), int(h[3]))
            )
    return out

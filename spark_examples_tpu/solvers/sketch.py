"""Streaming range sketch: the N x N-free half of the sketch solver.

The dense routes accumulate N x N Gram pieces and eigensolve them —
which caps the cohort at single-chip HBM (ROADMAP item 1). This module
replaces the N x N state with an (N, r) **range sketch** folded
block-by-block during the SAME single variant pass, following the
distributed randomized PCA/SVD construction of arXiv:1612.08709 and the
TPU dense-linear-algebra tactics of arXiv:2112.09017 (PAPERS.md):

For every sketchable metric (``core.config.SKETCH_METRICS``) the
centered solve operator is an exact Gram of per-block streamable
features ``A = [A_1 | A_2 | ...]``:

    B  =  (J A)(J A)^T / denom,      J = I - 11^T/N

- ``shared-alt``: ``A_b = [G_b >= 1]`` (alt-carrier indicators); the PCA
  driver's centered similarity ``J S J`` and the PCoA operator coincide.
- ``grm``: ``A_b = Z_b`` (VanRaden standardization,
  :func:`ops.gram.grm_standardize` — the SAME per-block definition the
  exact route uses), ``denom = nvar``.
- ``dot`` / ``euclidean``: ``A_b = max(G_b, 0)`` masked raw values
  (euclidean is exact when no calls are missing; with missingness the
  sketch models zero-imputed dosages).

Because ``B J = J B = B``, a matvec block against any probe block Q is

    B Q = J * sum_b A_b (A_b^T (J Q)) / denom

so the streamed update per genotype block is two skinny matmuls —
``(v, N) x (N, r)`` then ``(N, v) x (v, r)`` — at ``4 N v r`` FLOPs
instead of the dense route's ``2 N^2 v``: for N = 100k, r = 64 that is
the difference between representable and not. Under a multi-device plan
the block arrives variant-sharded exactly as in the gram path and the
``A_b @ W`` contraction psums over the mesh; the sketch state stays
replicated (an (N, r) f32 leaf is ~25 MB at N = 100k — noise).

The state is a plain accumulator dict (``y``/``qc``/``trace``/``nvar``)
so it rides the existing checkpoint machinery unchanged: deterministic
per-block adds, resumable from any block cursor bit-identically
(tests/test_kill_matrix.py pins this under the supervisor).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu import kernels
from spark_examples_tpu.core import meshes
from spark_examples_tpu.ops import gram as gram_ops
from spark_examples_tpu.parallel.gram_sharded import GramPlan

# Checkpointable accumulator leaves (core/checkpoint.py saves them like
# any gram accumulator; the pass index rides in the manifest's extra).
# ``cm`` is the streamed column-mass vector S @ 1 = A (A^T 1) — the
# per-sample similarity column sums the factorized model's projection
# centering (colmean/grand) is finalized from, accumulated in the SAME
# block update as the sketch so kill/resume keeps it bit-identical.
STATE_LEAVES = ("y", "qc", "trace", "nvar", "cm")

# The dual-sketch (ratio-metric) state: numerator sketch ``y``,
# denominator sketch ``yd``, the EXACT streamed denominator diagonal
# ``d`` (per-sample pair-count mass — one rowsum per term per block),
# the orthonormal test basis ``q``, the streamed probe block ``qc``
# (= q / a per row after pass 0), the rank-1 denominator factor
# ``scale`` (= a = sqrt(d); ones until pass 0 ends), and ``cm`` — the
# scaled-similarity column mass NUM @ (1/a), streamed on passes >= 1
# only (the scale does not exist during pass 0), which is why the dual
# centering stats — and --save-model — need the corrected rung.
DUAL_STATE_LEAVES = ("y", "yd", "d", "q", "qc", "scale", "cm")


def check_sketchable(metric: str, solver: str) -> None:
    """The one runtime gate (config-time validation cannot see a
    ``metric=None`` driver default resolve to ibs). Delegates to the
    kernel registry's gate — one builder, no drift."""
    kernels.check_sketchable(metric, solver)


def probes(n: int, rank: int, seed: int) -> jnp.ndarray:
    """Deterministic (N, min(rank, N)) Gaussian probe block — recomputed
    from ``--sketch-seed`` on resume, never checkpointed (the state that
    IS checkpointed already absorbed it)."""
    key = jax.random.key(seed)
    return jax.random.normal(key, (n, min(rank, n)), jnp.float32)


def center_cols(x: jnp.ndarray) -> jnp.ndarray:
    """J x for the sample-axis centering operator J = I - 11^T/N:
    subtract each column's mean over samples. The only form of J the
    sketch ever applies — always to an (N, r) skinny block, never to
    anything N x N."""
    return x - x.mean(axis=0, keepdims=True)


def _features(block, metric: str, grm_precise: bool):
    """(N, v) int8 dosages -> (A_b, kept): the streamed Gram factor's
    columns for this block (the kernel's declared FactorSketch
    features), plus the variant count feeding the nvar denominator.
    Padding columns (all MISSING) produce all-zero feature columns —
    zero contribution to y, trace, and nvar alike."""
    spec = kernels.get(metric).sketch
    if not isinstance(spec, kernels.FactorSketch):
        # static arg — a typo dies at trace time, not as wrong math
        raise ValueError(f"metric {metric!r} has no factor sketch")
    return spec.features(block, grm_precise)


def _update_impl(state, block, metric: str, packed: bool,
                 grm_precise: bool):
    """One block into the sketch: y += A_b (A_b^T qc), trace/nvar ride
    along. ``trace`` accumulates trace(B*denom) = ||J A||_F^2 =
    sum_v (||a_v||^2 - (1^T a_v)^2 / N) — the PCoA total-inertia
    denominator, streamed without any N x N."""
    if packed:
        from spark_examples_tpu.ingest.bitpack import unpack_dosages

        block = unpack_dosages(block)
    a, kept = _features(block, metric, grm_precise)
    qc = state["qc"]
    # (v, r): contract the sample axis (replicated) — local everywhere.
    w = jax.lax.dot_general(
        a, qc, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # (N, r): contract the (possibly mesh-sharded) variant axis — under
    # a multi-device plan XLA inserts the per-block psum here, the same
    # collective pattern as the gram accumulation.
    y = state["y"] + jax.lax.dot_general(
        a, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    af = a.astype(jnp.float32)
    colsum = af.sum(axis=0)
    n = a.shape[0]
    tr = state["trace"] + (af * af).sum() - (colsum * colsum).sum() / n
    # Column mass S @ 1 = A_b (A_b^T 1): the same (N, v) x (v,) shape as
    # the sketch's second matmul, so under a multi-device plan XLA
    # inserts the identical per-block psum over the variant shards.
    cm = state["cm"] + jax.lax.dot_general(
        af, colsum, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return {"y": y, "qc": qc, "trace": tr,
            "nvar": state["nvar"] + kept, "cm": cm}


@lru_cache(maxsize=64)
def _jitted_update(plan: GramPlan, metric: str, packed: bool,
                   grm_precise: bool):
    repl = meshes.replicated(plan.mesh)
    state_sh = {k: repl for k in STATE_LEAVES}
    return jax.jit(
        partial(_update_impl, metric=metric, packed=packed,
                grm_precise=grm_precise),
        in_shardings=(state_sh, plan.block_sharding),
        out_shardings=state_sh,
        donate_argnums=(0,),
    )


def make_update(plan: GramPlan, metric: str, packed: bool = False,
                grm_precise: bool = False):
    """Jitted ``(state, block) -> state`` with the plan's block transport
    pinned — the sketch twin of ``gram_sharded.make_update``. Blocks
    normally arrive already placed by ``stream_to_device``; host arrays
    are padded/placed here the same way the gram update does."""
    check_sketchable(metric, "sketch")
    jitted = _jitted_update(plan, metric, packed, grm_precise)
    n_shards = plan.block_shards

    def update(state, block):
        if not (isinstance(block, jax.Array)
                and block.sharding == plan.block_sharding):
            block = np.asarray(block)
            if block.shape[1] % n_shards:
                from spark_examples_tpu.ingest.prefetch import (
                    pad_block, pad_packed,
                )

                width = -(-block.shape[1] // n_shards) * n_shards
                block = (pad_packed(block, width) if packed
                         else pad_block(block, width))
            block = jax.device_put(block, plan.block_sharding)
        return jitted(state, block)

    return update


def init_state(plan: GramPlan, n: int, rank: int, seed: int) -> dict:
    """Fresh sketch state: zero sketch, centered probes, zero stats."""
    repl = meshes.replicated(plan.mesh)
    qc = center_cols(probes(n, rank, seed))
    return {
        "y": jax.device_put(jnp.zeros((n, min(rank, n)), jnp.float32), repl),
        "qc": jax.device_put(qc, repl),
        "trace": jax.device_put(jnp.zeros((), jnp.float32), repl),
        "nvar": jax.device_put(jnp.zeros((), jnp.float32), repl),
        "cm": jax.device_put(jnp.zeros((n,), jnp.float32), repl),
    }


def reset_for_pass(plan: GramPlan, state: dict, qc: jnp.ndarray) -> dict:
    """Fresh accumulators for the next streamed pass, tracking ``qc``
    (the orthonormalized subspace the corrected rung iterates). ``cm``
    re-accumulates to the identical value every pass (it never depends
    on qc), so zeroing keeps the leaf pass-local and resumable."""
    repl = meshes.replicated(plan.mesh)
    return {
        "y": jax.device_put(jnp.zeros_like(state["y"]), repl),
        "qc": jax.device_put(qc, repl),
        "trace": jax.device_put(jnp.zeros((), jnp.float32), repl),
        "nvar": jax.device_put(jnp.zeros((), jnp.float32), repl),
        "cm": jax.device_put(jnp.zeros_like(state["cm"]), repl),
    }


@partial(jax.jit, static_argnames=("is_grm",))
def finalize_pass(y, trace, nvar, is_grm: bool = False):
    """Completed-pass accumulators -> (B @ q_in, trace(B)): apply the
    outer J and the metric denominator. Skinny math only."""
    denom = jnp.maximum(nvar, 1.0) if is_grm else jnp.float32(1.0)
    return center_cols(y) / denom, trace / denom


# --------------------------------------------------------------------
# Dual sketch: ratio metrics (similarity = NUM ⊘ DEN) stream numerator
# AND pair-count denominator as two low-rank sketches in the same
# variant pass (kernels/base.py DualSketch; arXiv:1911.04200's
# communication-efficient direction recast onto the range-sketch
# machinery). After pass 0 the denominator's dominant (Perron) rank-1
# factor a a^T is extracted from ITS sketch, and every later pass (and
# the terminal solve) targets the scaled operator
#
#     B = J diag(1/a) NUM diag(1/a) J  ~  J (NUM ⊘ DEN) J
#
# — EXACT when DEN is rank-1 (IBS pair counts with no missing calls),
# a controlled approximation otherwise. The matvec of B is exactly
# streamable (NUM is a sum of cross-Grams of per-block features), so
# the corrected rung's subspace iteration runs true power steps.


def _dual_update_impl(state, block, metric: str, packed: bool,
                      with_den: bool):
    """One block into the sketches: y += NUM_b @ qc and — on pass 0
    only (``with_den``) — yd += DEN_b @ qc plus the exact denominator
    diagonal. Passes >= 1 are pure power steps of the scaled operator:
    the scale and defect are fixed once after pass 0, so re-streaming
    the denominator there would be dead matmuls.

    Each distinct right operand is contracted against the probes once
    ((v, r), local under variant sharding); each term then adds one
    (N, v) x (v, r) product — under a multi-device plan XLA inserts the
    per-block psum there, the same collective as the factor sketch."""
    if packed:
        from spark_examples_tpu.ingest.bitpack import unpack_dosages

        block = unpack_dosages(block)
    spec = kernels.get(metric).sketch
    ops = spec.operands(block)
    qc = state["qc"]
    terms = spec.num_terms + (spec.den_terms if with_den else ())
    rights = {}
    for (_l, r, _w) in terms:
        if r not in rights:
            rights[r] = jax.lax.dot_general(
                ops[r], qc, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    def apply(terms, y):
        for (l, r, w) in terms:
            contrib = jax.lax.dot_general(
                ops[l], rights[r], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = y + (contrib * w if w != 1.0 else contrib)
        return y

    # diag(DEN) streams EXACTLY: diag(L R^T) is one elementwise rowsum
    # per term, O(Nv) next to the sketches' matmuls — the scale the
    # solve divides by is never itself an estimate.
    d = state["d"]
    if with_den:
        for (l, r, w) in spec.den_terms:
            d = d + w * (ops[l] * ops[r]).sum(axis=1)

    # Scaled column mass NUM @ u (u = 1/a): the factorized model's
    # centering colmean/grand come from this (NUM is symmetric for the
    # registered ratio metrics, so NUM^T u = NUM u). Streams only once
    # the scale exists — i.e. on the corrected rung's power passes.
    cm = state["cm"]
    if not with_den:
        u = 1.0 / state["scale"]
        for (l, r, w) in spec.num_terms:
            ru = jax.lax.dot_general(
                ops[r], u, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            contrib = jax.lax.dot_general(
                ops[l], ru, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            cm = cm + (contrib * w if w != 1.0 else contrib)

    return {
        "y": apply(spec.num_terms, state["y"]),
        "yd": (apply(spec.den_terms, state["yd"]) if with_den
               else state["yd"]),
        "d": d,
        "q": state["q"],
        "qc": qc,
        "scale": state["scale"],
        "cm": cm,
    }


@lru_cache(maxsize=64)
def _jitted_dual_update(plan: GramPlan, metric: str, packed: bool,
                        with_den: bool):
    repl = meshes.replicated(plan.mesh)
    state_sh = {k: repl for k in DUAL_STATE_LEAVES}
    return jax.jit(
        partial(_dual_update_impl, metric=metric, packed=packed,
                with_den=with_den),
        in_shardings=(state_sh, plan.block_sharding),
        out_shardings=state_sh,
        donate_argnums=(0,),
    )


def make_dual_update(plan: GramPlan, metric: str, packed: bool = False,
                     with_den: bool = True):
    """Jitted dual-sketch ``(state, block) -> state`` — the ratio-metric
    twin of :func:`make_update`, same block transport handling.
    ``with_den=False`` builds the pass->=1 variant that streams only
    the numerator (the denominator work is pass-0-only)."""
    check_sketchable(metric, "corrected")
    jitted = _jitted_dual_update(plan, metric, packed, with_den)
    n_shards = plan.block_shards

    def update(state, block):
        if not (isinstance(block, jax.Array)
                and block.sharding == plan.block_sharding):
            block = np.asarray(block)
            if block.shape[1] % n_shards:
                from spark_examples_tpu.ingest.prefetch import (
                    pad_block, pad_packed,
                )

                width = -(-block.shape[1] // n_shards) * n_shards
                block = (pad_packed(block, width) if packed
                         else pad_block(block, width))
            block = jax.device_put(block, plan.block_sharding)
        return jitted(state, block)

    return update


def init_dual_state(plan: GramPlan, n: int, rank: int, seed: int) -> dict:
    """Fresh dual state: zero sketches, CENTERED probes as both the
    test basis and the streamed input, unit scale (pass 0 streams the
    UNSCALED operators — the scale does not exist until the
    denominator's exact diagonal has been seen once).

    Centered deliberately: both NUM and DEN carry an enormous
    near-constant rank-1 component (the per-pair count mass, ~100x the
    structure), which is exactly what the downstream double centering
    annihilates — streaming against J q means the rank budget is spent
    on the components B actually keeps, not on re-discovering the
    Perron direction. This is only possible because the SCALE does not
    come from the denominator sketch (diag(DEN) streams exactly in the
    same pass); yd's remaining job — pricing the rank-1 residual — is
    normalized against the exact trace mass, not against ||DEN J q||."""
    repl = meshes.replicated(plan.mesh)
    r = min(rank, n)
    # q and qc start numerically equal but MUST be distinct buffers:
    # the jitted update donates the whole state pytree, and aliased
    # leaves would be donated twice (host round-trip for the copy).
    qc = np.asarray(center_cols(probes(n, rank, seed)))
    return {
        "y": jax.device_put(jnp.zeros((n, r), jnp.float32), repl),
        "yd": jax.device_put(jnp.zeros((n, r), jnp.float32), repl),
        "d": jax.device_put(jnp.zeros((n,), jnp.float32), repl),
        "q": jax.device_put(qc, repl),
        "qc": jax.device_put(np.array(qc), repl),
        "scale": jax.device_put(jnp.ones((n,), jnp.float32), repl),
        "cm": jax.device_put(jnp.zeros((n,), jnp.float32), repl),
    }


@jax.jit
def _dual_scale_impl(d, yd, qc):
    """Rank-1 factor ``a = sqrt(diag(DEN))`` from the EXACTLY streamed
    denominator diagonal, plus the honesty number: how far DEN actually
    is from ``a a^T``, measured against the denominator SKETCH
    (``defect = ||yd - a (a^T qc)||_F / ||yd||_F`` — yd = DEN qc, so
    this is a probe-space estimate of the rank-1 residual the scaled
    operator absorbs).

    sqrt(diag) — not the Perron eigenvector — deliberately: it needs no
    eigen-estimation (DEN is INDEFINITE for union-count denominators,
    so Nystrom would NaN), it is bit-deterministic, it equals the
    Perron factor exactly whenever DEN IS rank-1 (the regime the dual
    rungs are exact in), and it pins the scaled similarity's diagonal
    at NUM_ii/DEN_ii = 1 — the self-similarity the downstream Gower
    centering hinges on. Samples with an empty denominator are floored
    at 1e-3 of the mean scale so they get a bounded, not infinite,
    scaling."""
    a = jnp.sqrt(jnp.maximum(d, 0.0))
    a = jnp.maximum(a, 1e-3 * jnp.maximum(a.mean(), 1e-30))
    resid = yd - a[:, None] * (a @ qc)[None, :]
    # ||E J||_F estimate (gaussian probes: E||A q||_F^2 = r ||A||_F^2)
    # over the EXACT trace mass sum(d) = tr(DEN) (= ||a a^T||_F when
    # DEN is rank-1) — NOT over ||yd||: the centered probes annihilate
    # most of DEN's rank-1 mass, so that ratio would read ~1 even for
    # a nearly-exact denominator.
    r = qc.shape[1]
    defect = (jnp.linalg.norm(resid) / jnp.sqrt(1.0 * r)) / jnp.maximum(
        d.sum(), 1e-30)
    return a, defect


def dual_scale(state: dict, plan: GramPlan):
    """The denominator's rank-1 scale factor (and its measured rank-1
    defect) from the completed pass-0 state — state leaves are
    replicated under every plan, so this is collective-free."""
    return _dual_scale_impl(state["d"], state["yd"], state["qc"])


@jax.jit
def _dual_apply_impl(y, scale):
    return center_cols(y / scale[:, None])


def dual_apply(state: dict):
    """Completed-pass numerator sketch -> the scaled, centered factor
    ``J diag(1/a) (NUM @ qc)`` — for passes >= 1 (qc = Dinv q) this IS
    ``B @ q``; for pass 0 it is the starting block whose range the
    corrected rung orthonormalizes."""
    return _dual_apply_impl(state["y"], state["scale"])


def reset_dual_pass(plan: GramPlan, state: dict, q_next) -> dict:
    """Fresh sketches for the next streamed pass: track the orthonormal
    basis ``q_next`` and stream against ``q_next / a`` so the pass
    computes NUM @ (diag(1/a) q) — the inner half of B's matvec.

    ``d`` is CARRIED, not zeroed: passes >= 1 never touch it (with_den
    is False), and the saved model's query-side scale floor is
    finalized from it — zeroing would lose the floor on a run that
    resumed past pass 0. ``cm`` re-accumulates to the identical value
    on every scaled pass (it depends only on the fixed scale), so
    zeroing keeps it pass-local and resumable."""
    repl = meshes.replicated(plan.mesh)
    return {
        "y": jax.device_put(jnp.zeros_like(state["y"]), repl),
        "yd": jax.device_put(jnp.zeros_like(state["yd"]), repl),
        "d": state["d"],
        "q": jax.device_put(q_next, repl),
        "qc": jax.device_put(q_next / state["scale"][:, None], repl),
        "scale": state["scale"],
        "cm": jax.device_put(jnp.zeros_like(state["cm"]), repl),
    }


def factor_centering(state: dict) -> tuple[np.ndarray, float]:
    """Completed-pass factor-sketch state -> (colmean, grand): the
    double-centering statistics of S = A A^T the factorized model
    serves projection with, finalized on host in f64 from the streamed
    column mass ``cm = S @ 1``. Identical formula to the exact route's
    dense stats (colmean_j = (1/N) sum_i S_ij; grand = mean(S)) — the
    projection path downstream is shared, bit for bit."""
    cm = np.asarray(state["cm"], dtype=np.float64)
    n = cm.shape[0]
    return (cm / n).astype(np.float32), float(cm.sum() / (n * n))


def dual_centering(state: dict) -> tuple[np.ndarray, float, float]:
    """Completed-pass dual state -> (colmean, grand, scale_floor) of
    the SCALED similarity s~_ij = NUM_ij / (a_i a_j), whose diagonal is
    pinned at 1 — so the served Gower centering needs no dense
    diagonal. From cm = NUM @ u (u = 1/a, symmetric NUM):

        colmean_j = (1/N) sum_i s~_ij = u_j cm_j / N
        grand     = (1/N^2) u^T NUM u = (1/N^2) sum_j u_j cm_j

    ``scale_floor`` re-derives the :func:`_dual_scale_impl` floor from
    the carried exact diagonal ``d`` so query-side scales are floored
    by the same rule the fit applied."""
    cm = np.asarray(state["cm"], dtype=np.float64)
    a = np.asarray(state["scale"], dtype=np.float64)
    d = np.asarray(state["d"], dtype=np.float64)
    n = cm.shape[0]
    u = cm / a
    colmean = (u / n).astype(np.float32)
    grand = float(u.sum() / (n * n))
    a_raw = np.sqrt(np.maximum(d, 0.0))
    floor = 1e-3 * max(float(a_raw.mean()), 1e-30)
    return colmean, grand, floor


def dual_state_bytes(n: int, rank: int) -> int:
    """Peak dual-solver state residency: four (N, r) f32 leaves plus
    the (N,) diagonal, scale, and column-mass vectors."""
    r = min(rank, n)
    return (4 * n * r + 3 * n) * 4


def dual_flops_per_block(n: int, v: int, rank: int, metric: str,
                         with_den: bool = True) -> float:
    """Skinny-matmul work of one dual-sketch block update: one (v, r)
    probe contraction per distinct right operand plus one (N, v) x
    (v, r) product per streamed term — num+den on pass 0, num only on
    the later passes (honest credit for the work actually run)."""
    spec = kernels.get(metric).sketch
    terms = spec.num_terms + (spec.den_terms if with_den else ())
    n_rights = len({r for (_l, r, _w) in terms})
    return 2.0 * n * v * min(rank, n) * (n_rights + len(terms))


def state_bytes(n: int, rank: int) -> int:
    """Peak solver-state residency: y + qc f32 leaves plus the (N,)
    column-mass vector (the scalars are noise). THE 'peak solver
    memory' number bench reports — compare against nxn_bytes(...) for
    what the dense route would have held."""
    r = min(rank, n)
    return (2 * n * r + n) * 4


def nxn_bytes(n: int, metric: str) -> int:
    """What the dense route's accumulators would have allocated for this
    cohort/metric — the allocation the sketch path exists to avoid.
    Live-registry count of the N x N leaves (scalar leaves like grm's
    nvar are noise and excluded)."""
    kern = kernels.maybe_get(metric)
    n_acc = (max(len(kern.acc_leaves) - len(kern.scalar_leaves), 1)
             if kern is not None else 1)
    return 4 * n * n * n_acc


def flops_per_block(n: int, v: int, rank: int) -> float:
    """The two skinny matmuls of one block's sketch update."""
    return 4.0 * n * v * min(rank, n)

"""Streaming range sketch: the N x N-free half of the sketch solver.

The dense routes accumulate N x N Gram pieces and eigensolve them —
which caps the cohort at single-chip HBM (ROADMAP item 1). This module
replaces the N x N state with an (N, r) **range sketch** folded
block-by-block during the SAME single variant pass, following the
distributed randomized PCA/SVD construction of arXiv:1612.08709 and the
TPU dense-linear-algebra tactics of arXiv:2112.09017 (PAPERS.md):

For every sketchable metric (``core.config.SKETCH_METRICS``) the
centered solve operator is an exact Gram of per-block streamable
features ``A = [A_1 | A_2 | ...]``:

    B  =  (J A)(J A)^T / denom,      J = I - 11^T/N

- ``shared-alt``: ``A_b = [G_b >= 1]`` (alt-carrier indicators); the PCA
  driver's centered similarity ``J S J`` and the PCoA operator coincide.
- ``grm``: ``A_b = Z_b`` (VanRaden standardization,
  :func:`ops.gram.grm_standardize` — the SAME per-block definition the
  exact route uses), ``denom = nvar``.
- ``dot`` / ``euclidean``: ``A_b = max(G_b, 0)`` masked raw values
  (euclidean is exact when no calls are missing; with missingness the
  sketch models zero-imputed dosages).

Because ``B J = J B = B``, a matvec block against any probe block Q is

    B Q = J * sum_b A_b (A_b^T (J Q)) / denom

so the streamed update per genotype block is two skinny matmuls —
``(v, N) x (N, r)`` then ``(N, v) x (v, r)`` — at ``4 N v r`` FLOPs
instead of the dense route's ``2 N^2 v``: for N = 100k, r = 64 that is
the difference between representable and not. Under a multi-device plan
the block arrives variant-sharded exactly as in the gram path and the
``A_b @ W`` contraction psums over the mesh; the sketch state stays
replicated (an (N, r) f32 leaf is ~25 MB at N = 100k — noise).

The state is a plain accumulator dict (``y``/``qc``/``trace``/``nvar``)
so it rides the existing checkpoint machinery unchanged: deterministic
per-block adds, resumable from any block cursor bit-identically
(tests/test_kill_matrix.py pins this under the supervisor).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.core import meshes
from spark_examples_tpu.core.config import (
    SKETCH_METRICS,
    unsketchable_metric_error,
)
from spark_examples_tpu.ops import gram as gram_ops
from spark_examples_tpu.parallel.gram_sharded import GramPlan

# Checkpointable accumulator leaves (core/checkpoint.py saves them like
# any gram accumulator; the pass index rides in the manifest's extra).
STATE_LEAVES = ("y", "qc", "trace", "nvar")


def check_sketchable(metric: str, solver: str) -> None:
    """The one runtime gate (config-time validation cannot see a
    ``metric=None`` driver default resolve to ibs). Same message text
    as the config-time rejection — one builder, no drift."""
    if metric not in SKETCH_METRICS:
        raise ValueError(unsketchable_metric_error(metric, solver))


def probes(n: int, rank: int, seed: int) -> jnp.ndarray:
    """Deterministic (N, min(rank, N)) Gaussian probe block — recomputed
    from ``--sketch-seed`` on resume, never checkpointed (the state that
    IS checkpointed already absorbed it)."""
    key = jax.random.key(seed)
    return jax.random.normal(key, (n, min(rank, n)), jnp.float32)


def center_cols(x: jnp.ndarray) -> jnp.ndarray:
    """J x for the sample-axis centering operator J = I - 11^T/N:
    subtract each column's mean over samples. The only form of J the
    sketch ever applies — always to an (N, r) skinny block, never to
    anything N x N."""
    return x - x.mean(axis=0, keepdims=True)


def _features(block, metric: str, grm_precise: bool):
    """(N, v) int8 dosages -> (A_b, kept): the streamed Gram factor's
    columns for this block, plus the variant count feeding the grm
    denominator. Padding columns (all MISSING) produce all-zero feature
    columns — zero contribution to y, trace, and nvar alike."""
    if metric == "shared-alt":
        a = (block >= 1).astype(jnp.float32)
        kept = jnp.float32(0.0)  # denominator unused
    elif metric == "grm":
        # Same standardization as the exact route; the sketch's matmuls
        # then run f32 regardless of grm_precise (they are ~N/r cheaper
        # than the dense update, so there is no rate to buy back).
        a, keep = gram_ops.grm_standardize(block, grm_precise)
        a = a.astype(jnp.float32)
        kept = keep.sum().astype(jnp.float32)
    elif metric in ("dot", "euclidean"):
        a = jnp.where(block >= 0, block, 0).astype(jnp.float32)
        kept = jnp.float32(0.0)
    else:  # static arg — a typo dies at trace time, not as wrong math
        raise ValueError(f"metric {metric!r} is not sketchable")
    return a, kept


def _update_impl(state, block, metric: str, packed: bool,
                 grm_precise: bool):
    """One block into the sketch: y += A_b (A_b^T qc), trace/nvar ride
    along. ``trace`` accumulates trace(B*denom) = ||J A||_F^2 =
    sum_v (||a_v||^2 - (1^T a_v)^2 / N) — the PCoA total-inertia
    denominator, streamed without any N x N."""
    if packed:
        from spark_examples_tpu.ingest.bitpack import unpack_dosages

        block = unpack_dosages(block)
    a, kept = _features(block, metric, grm_precise)
    qc = state["qc"]
    # (v, r): contract the sample axis (replicated) — local everywhere.
    w = jax.lax.dot_general(
        a, qc, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # (N, r): contract the (possibly mesh-sharded) variant axis — under
    # a multi-device plan XLA inserts the per-block psum here, the same
    # collective pattern as the gram accumulation.
    y = state["y"] + jax.lax.dot_general(
        a, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    af = a.astype(jnp.float32)
    colsum = af.sum(axis=0)
    n = a.shape[0]
    tr = state["trace"] + (af * af).sum() - (colsum * colsum).sum() / n
    return {"y": y, "qc": qc, "trace": tr, "nvar": state["nvar"] + kept}


@lru_cache(maxsize=64)
def _jitted_update(plan: GramPlan, metric: str, packed: bool,
                   grm_precise: bool):
    repl = meshes.replicated(plan.mesh)
    state_sh = {"y": repl, "qc": repl, "trace": repl, "nvar": repl}
    return jax.jit(
        partial(_update_impl, metric=metric, packed=packed,
                grm_precise=grm_precise),
        in_shardings=(state_sh, plan.block_sharding),
        out_shardings=state_sh,
        donate_argnums=(0,),
    )


def make_update(plan: GramPlan, metric: str, packed: bool = False,
                grm_precise: bool = False):
    """Jitted ``(state, block) -> state`` with the plan's block transport
    pinned — the sketch twin of ``gram_sharded.make_update``. Blocks
    normally arrive already placed by ``stream_to_device``; host arrays
    are padded/placed here the same way the gram update does."""
    check_sketchable(metric, "sketch")
    jitted = _jitted_update(plan, metric, packed, grm_precise)
    n_shards = plan.block_shards

    def update(state, block):
        if not (isinstance(block, jax.Array)
                and block.sharding == plan.block_sharding):
            block = np.asarray(block)
            if block.shape[1] % n_shards:
                from spark_examples_tpu.ingest.prefetch import (
                    pad_block, pad_packed,
                )

                width = -(-block.shape[1] // n_shards) * n_shards
                block = (pad_packed(block, width) if packed
                         else pad_block(block, width))
            block = jax.device_put(block, plan.block_sharding)
        return jitted(state, block)

    return update


def init_state(plan: GramPlan, n: int, rank: int, seed: int) -> dict:
    """Fresh sketch state: zero sketch, centered probes, zero stats."""
    repl = meshes.replicated(plan.mesh)
    qc = center_cols(probes(n, rank, seed))
    return {
        "y": jax.device_put(jnp.zeros((n, min(rank, n)), jnp.float32), repl),
        "qc": jax.device_put(qc, repl),
        "trace": jax.device_put(jnp.zeros((), jnp.float32), repl),
        "nvar": jax.device_put(jnp.zeros((), jnp.float32), repl),
    }


def reset_for_pass(plan: GramPlan, state: dict, qc: jnp.ndarray) -> dict:
    """Fresh accumulators for the next streamed pass, tracking ``qc``
    (the orthonormalized subspace the corrected rung iterates)."""
    repl = meshes.replicated(plan.mesh)
    return {
        "y": jax.device_put(jnp.zeros_like(state["y"]), repl),
        "qc": jax.device_put(qc, repl),
        "trace": jax.device_put(jnp.zeros((), jnp.float32), repl),
        "nvar": jax.device_put(jnp.zeros((), jnp.float32), repl),
    }


@partial(jax.jit, static_argnames=("is_grm",))
def finalize_pass(y, trace, nvar, is_grm: bool = False):
    """Completed-pass accumulators -> (B @ q_in, trace(B)): apply the
    outer J and the metric denominator. Skinny math only."""
    denom = jnp.maximum(nvar, 1.0) if is_grm else jnp.float32(1.0)
    return center_cols(y) / denom, trace / denom


def state_bytes(n: int, rank: int) -> int:
    """Peak solver-state residency: y + qc f32 leaves (the scalars are
    noise). THE 'peak solver memory' number bench reports — compare
    against nxn_bytes(...) for what the dense route would have held."""
    r = min(rank, n)
    return 2 * n * r * 4


def nxn_bytes(n: int, metric: str) -> int:
    """What the dense route's accumulators would have allocated for this
    cohort/metric — the allocation the sketch path exists to avoid."""
    n_acc = max(len(gram_ops.PIECES_FOR_METRIC.get(metric, ("zz",))), 1)
    return 4 * n * n * n_acc


def flops_per_block(n: int, v: int, rank: int) -> float:
    """The two skinny matmuls of one block's sketch update."""
    return 4.0 * n * v * min(rank, n)

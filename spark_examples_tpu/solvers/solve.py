"""Distributed solve stage: eigenpairs from the sketch, no N x N eigh.

Everything here operates on (N, r) skinny blocks and (r, r) cores — the
N x N operator only ever existed implicitly, as the streamed passes in
:mod:`solvers.sketch`. Under a multi-device plan the skinny blocks are
row-sharded over the flattened mesh (``meshes.rows_flat``): each (r, r)
contraction (``Y^T Y``, ``Q^T Y``) lowers to a local product plus one
psum over the mesh — the per-iteration collective the design calls for
— while the (r, r) math (Cholesky, triangular solve, eigh) runs
replicated: at r ~ 64 it is microseconds, irrelevant next to a streamed
pass. This is the TPU-shaped division of labor of arXiv:2112.09017
applied to the randomized solve of arXiv:1612.08709.

Two terminal solves, one per ladder rung:

- :func:`nystrom_eigs` — single-pass rung: with ``Y = B Omega`` and the
  core ``C = Omega^T Y = Omega^T B Omega``, the Nystrom approximation
  ``B ~ Y C^+ Y^T`` yields eigenpairs from a shifted Cholesky of C, a
  triangular solve against Y, and an (r, r) eigh.
- :func:`rayleigh_eigs` — corrected rung: the last streamed pass was
  ``Y = B Q`` with Q orthonormal (subspace iteration), so the Rayleigh
  quotient ``T = Q^T Y`` gives Ritz pairs directly.

Orthonormalization between passes is **shifted CholeskyQR2** — two
rounds of ``W = chol(Y^T Y + eps I)^-T`` — the communication-minimal
tall-skinny QR (one psum per round, no column-by-column Householder
traffic), robust at f32 for the conditioning subspace iteration
produces.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from spark_examples_tpu.core import meshes
from spark_examples_tpu.parallel.gram_sharded import GramPlan

# Relative Cholesky shift: large enough to keep chol finite on a
# rank-deficient core at f32, small enough to be noise against any
# eigenvalue the sketch can resolve at all.
_SHIFT = 1e-6


def _shifted_chol(g: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(r, r) gram/core -> (lower Cholesky factor of g + shift I, shift)."""
    g = 0.5 * (g + g.T)
    r = g.shape[0]
    shift = _SHIFT * jnp.maximum(jnp.trace(g), 1e-30) / r
    return jnp.linalg.cholesky(g + shift * jnp.eye(r, dtype=g.dtype)), shift


def _pin_rows(plan: GramPlan | None, x: jnp.ndarray) -> jnp.ndarray:
    """Row-shard an (N, r) block over the mesh (no-op without a plan or
    on a single device) — placed inside the jits so XLA sees the layout
    and inserts the psums."""
    if plan is None or plan.mesh.devices.size == 1:
        return x
    return jax.lax.with_sharding_constraint(x, meshes.rows_flat(plan.mesh))


def _chol_qr_once(y, plan):
    g = jax.lax.dot_general(  # (r, r): local product + one psum
        y, y, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    l, _ = _shifted_chol(g)
    # y @ L^-T via a triangular solve on the SKINNY side.
    w = jax.scipy.linalg.solve_triangular(
        l, jnp.eye(l.shape[0], dtype=l.dtype), lower=True
    )
    return _pin_rows(plan, y @ w.T)


def _orthonormalize_impl(y, plan):
    y = _pin_rows(plan, y)
    y = _chol_qr_once(y, plan)
    return _chol_qr_once(y, plan)  # CholeskyQR2: second round -> ~f32 ortho


def _nystrom_impl(y, qc, k: int, plan):
    y = _pin_rows(plan, y)
    qc = _pin_rows(plan, qc)
    core = jax.lax.dot_general(  # Omega^T B Omega: local + psum
        qc, y, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    l, shift = _shifted_chol(core)
    # F = Y L^-T: B ~ F F^T, so eig(B) = eig(F^T F) (r x r).
    w = jax.scipy.linalg.solve_triangular(
        l, jnp.eye(l.shape[0], dtype=l.dtype), lower=True
    )
    f = _pin_rows(plan, y @ w.T)
    g = jax.lax.dot_general(
        f, f, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    e, s = jnp.linalg.eigh(0.5 * (g + g.T))  # ascending
    vals = e[::-1][:k]
    vecs = f @ (s[:, ::-1][:, :k] / jnp.sqrt(jnp.maximum(e[::-1][:k], 1e-30)))
    # Undo the stabilizing shift (the shifted-Nystrom estimator); clamp
    # at zero — B is PSD by construction for every sketchable metric.
    return jnp.maximum(vals - shift, 0.0), vecs


def _nystrom_scaled_impl(y, qc, g, k: int, plan):
    """Nystrom eigenpairs of a *congruence-transformed* operator: the
    core is still ``qc^T y = qc^T NUM qc`` (PSD when NUM is), but the
    outer factor is ``g = M y`` for some row transform M (the dual
    sketch's ``J diag(1/a)``), giving ``B = M NUM M^T ~ g C^+ g^T`` —
    the single-pass rung of the dual-sketch ladder."""
    y = _pin_rows(plan, y)
    qc = _pin_rows(plan, qc)
    g = _pin_rows(plan, g)
    core = jax.lax.dot_general(  # qc^T NUM qc: local + psum
        qc, y, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    l, shift = _shifted_chol(core)
    w = jax.scipy.linalg.solve_triangular(
        l, jnp.eye(l.shape[0], dtype=l.dtype), lower=True
    )
    f = _pin_rows(plan, g @ w.T)
    gm = jax.lax.dot_general(
        f, f, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    e, s = jnp.linalg.eigh(0.5 * (gm + gm.T))  # ascending
    vals = e[::-1][:k]
    vecs = f @ (s[:, ::-1][:, :k] / jnp.sqrt(jnp.maximum(e[::-1][:k], 1e-30)))
    return jnp.maximum(vals - shift, 0.0), vecs


def _rayleigh_impl(y, q, k: int, plan):
    y = _pin_rows(plan, y)
    q = _pin_rows(plan, q)
    t = jax.lax.dot_general(  # Q^T B Q: local + psum
        q, y, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    e, s = jnp.linalg.eigh(0.5 * (t + t.T))  # ascending
    vals = e[::-1][:k]
    vecs = q @ s[:, ::-1][:, :k]
    return vals, vecs


@lru_cache(maxsize=32)
def _orthonormalize_jit(plan: GramPlan | None):
    repl = None if plan is None else meshes.replicated(plan.mesh)
    kw = {} if repl is None else {
        "in_shardings": (repl,), "out_shardings": repl,
    }
    return jax.jit(lambda y: _orthonormalize_impl(y, plan), **kw)


@lru_cache(maxsize=32)
def _nystrom_jit(plan: GramPlan | None, k: int):
    repl = None if plan is None else meshes.replicated(plan.mesh)
    kw = {} if repl is None else {
        "in_shardings": (repl, repl), "out_shardings": (repl, repl),
    }
    return jax.jit(lambda y, qc: _nystrom_impl(y, qc, k, plan), **kw)


@lru_cache(maxsize=32)
def _nystrom_scaled_jit(plan: GramPlan | None, k: int):
    repl = None if plan is None else meshes.replicated(plan.mesh)
    kw = {} if repl is None else {
        "in_shardings": (repl, repl, repl), "out_shardings": (repl, repl),
    }
    return jax.jit(lambda y, qc, g: _nystrom_scaled_impl(y, qc, g, k, plan),
                   **kw)


@lru_cache(maxsize=32)
def _rayleigh_jit(plan: GramPlan | None, k: int):
    repl = None if plan is None else meshes.replicated(plan.mesh)
    kw = {} if repl is None else {
        "in_shardings": (repl, repl), "out_shardings": (repl, repl),
    }
    return jax.jit(lambda y, q: _rayleigh_impl(y, q, k, plan), **kw)


def orthonormalize(y: jnp.ndarray, plan: GramPlan | None = None):
    """Shifted CholeskyQR2 of an (N, r) block -> orthonormal columns
    spanning the same space (the between-pass step of the corrected
    rung). The output stays centered when the input is (it is a right
    multiplication)."""
    return _orthonormalize_jit(plan)(y)


def nystrom_eigs(y: jnp.ndarray, qc: jnp.ndarray, k: int,
                 plan: GramPlan | None = None):
    """Top-k eigenpairs of the single-pass Nystrom approximation built
    from sketch ``y = B @ omega`` and test block ``qc``. Returns
    (vals (k,) descending >= 0, vecs (N, k) orthonormal)."""
    return _nystrom_jit(plan, k)(y, qc)


def nystrom_eigs_scaled(y: jnp.ndarray, qc: jnp.ndarray, g: jnp.ndarray,
                        k: int, plan: GramPlan | None = None):
    """Top-k eigenpairs of ``B = M NUM M^T`` from the NUM sketch
    ``y = NUM @ qc`` and its row-transformed twin ``g = M y`` (the dual
    sketch's scaled/centered factor). NUM must be PSD (the core is its
    Nystrom core) — the registry's ``num_psd`` gate."""
    return _nystrom_scaled_jit(plan, k)(y, qc, g)


def rayleigh_eigs(y: jnp.ndarray, q: jnp.ndarray, k: int,
                  plan: GramPlan | None = None):
    """Top-k Ritz pairs from the last subspace-iteration pass
    (``y = B q``, q orthonormal). Returns (vals (k,) descending,
    vecs (N, k))."""
    return _rayleigh_jit(plan, k)(y, q)


def stage_runtimes(n: int, rank: int, plan: GramPlan | None = None,
                   k: int = 10, repeats: int = 3,
                   seed: int = 0) -> dict[str, float]:
    """Measured wall-clock of the distributed solve stages at an
    ``(n, rank)`` sketch shape on ``plan``'s mesh (best of ``repeats``
    after a compile+warm run, per stage, in seconds):

    - ``cholqr2_s`` — one shifted CholeskyQR2 orthonormalization (the
      between-pass step: two local r x r grams + psums, two triangular
      solves, two skinny matmuls over the row-sharded block);
    - ``nystrom_s`` — the single-pass terminal Nystrom solve;
    - ``rayleigh_s`` — the corrected rung's terminal Rayleigh solve.

    This is the bench entry the multi-chip row uses (bench.py
    --multichip) to measure the row-sharded stages at the N=100k
    shapes ROADMAP item 4 names, on whatever mesh exists — the same
    jits production solves run, not a proxy. Inputs are seeded normal
    blocks: stage wall-clock is shape-, not spectrum-, dependent
    (fixed operation count; the one data-dependent op is an r x r
    eigh, microseconds at these ranks)."""
    import time

    from spark_examples_tpu.core.profiling import hard_sync

    y = hard_sync(jax.random.normal(jax.random.key(seed), (n, rank),
                                    jnp.float32))
    qc = hard_sync(jax.random.normal(jax.random.key(seed + 1), (n, rank),
                                     jnp.float32))
    out: dict[str, float] = {}
    for name, fn in (
        ("cholqr2_s", lambda: orthonormalize(y, plan)),
        ("nystrom_s", lambda: nystrom_eigs(y, qc, k, plan)),
        ("rayleigh_s", lambda: rayleigh_eigs(y, qc, k, plan)),
    ):
        hard_sync(fn())  # compile + warm
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            hard_sync(fn())
            best = min(best, time.perf_counter() - t0)
        out[name] = best
    return out

"""The sketch-solver job driver: passes -> solve, under the ladder.

Orchestrates the pieces of the subsystem into the pipeline-facing call
(:func:`run_sketch_solve`, consumed by ``pipelines/jobs.py``):

- stream 1 + extra passes over the cohort through
  :func:`pipelines.runner.run_sketch_pass` (the same staged-ring feed,
  ``gram.block`` spans, and checkpoint cadence as the gram routes — a
  supervised sketch job is killed/resumed by exactly the machinery that
  supervises a gram job);
- between passes of the ``corrected`` rung, orthonormalize the sketch
  (shifted CholeskyQR2) and iterate — textbook subspace iteration where
  every B@Q product is a streamed pass, never a materialized matmul;
- terminal solve per rung: single-pass Nystrom (``sketch``) or Rayleigh
  Ritz pairs (``corrected``); ``exact`` never reaches this module.

Checkpoint/resume: the sketch state is an ordinary accumulator dict to
``core/checkpoint.py`` (leaves ``y``/``qc``/``trace``/``nvar`` plus the
``passno`` cursor), namespaced under ``solver:<metric>`` so a sketch
checkpoint can never be confused with a gram one, with the rung/rank/
seed recorded as the manifest's ``extra`` — a resume under different
probe settings is rejected, not silently mixed. Probes themselves are
re-derived from ``--sketch-seed``, so a killed job resumes
bit-identically (tests/test_kill_matrix.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np

from spark_examples_tpu.core import checkpoint as ckpt
from spark_examples_tpu.core import meshes, telemetry
from spark_examples_tpu.core.config import SOLVER_RUNG_ID, JobConfig
from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync
from spark_examples_tpu.ops import gram
from spark_examples_tpu.ops.eigh import coords_from_eigpairs
from spark_examples_tpu.parallel.gram_sharded import GramPlan
from spark_examples_tpu.pipelines import runner as R
from spark_examples_tpu.solvers import sketch, solve

RUNG_ID = SOLVER_RUNG_ID  # re-exported; the numbers live with the ladder

_CKPT_LEAVES = sketch.STATE_LEAVES + ("passno",)


@dataclass
class SketchSolveResult:
    """What the pipeline needs back: host-resident eigenpairs/coords
    plus the provenance the model artifact and telemetry record."""

    sample_ids: list[str]
    eigenvalues: np.ndarray  # (k,) descending
    coords: np.ndarray  # (N, k)
    proportion: np.ndarray | None  # PCoA only (share of total inertia)
    n_variants: int
    rung: str
    rank: int
    passes: int


def sketch_plan(job: JobConfig) -> GramPlan:
    """The sketch's distribution plan: blocks variant-sharded over the
    mesh exactly like the gram path, state replicated. Never tile2d —
    there is no N x N accumulator to tile, so neither the acc-budget
    heuristic nor the sample-divisibility constraint applies."""
    meshes.maybe_init_distributed()
    mesh = meshes.make_mesh(shape=job.compute.mesh_shape)
    mode = "replicated" if mesh.devices.size == 1 else "variant"
    return GramPlan(mesh, mode)


def run_sketch_solve(job: JobConfig, source, timer: PhaseTimer,
                     kind: str) -> SketchSolveResult:
    """Run the full sketch/corrected solve for a pcoa or pca job."""
    cfg = job.compute
    metric = "shared-alt" if kind == "pca" else (cfg.metric or "ibs")
    sketch.check_sketchable(metric, cfg.solver)
    if cfg.backend == "cpu-reference":
        raise ValueError(
            "--solver sketch/corrected runs on the jax backend; the CPU "
            "oracle implements the dense reference route only"
        )
    if job.model_path:
        raise ValueError(
            "--save-model needs the dense distance/similarity matrix for "
            "the projection centering statistics, which the sketch route "
            "never materializes — fit the model with --solver exact"
        )
    plan = sketch_plan(job)
    if jax.process_count() > 1:
        raise ValueError(
            "--solver sketch/corrected is single-process for now (the "
            "state psums span the local mesh); run multi-host jobs with "
            "--solver exact"
        )
    n = source.n_samples
    rank = min(cfg.sketch_rank, n)
    passes = 1 + (cfg.sketch_iters if cfg.solver == "corrected" else 0)
    is_grm = metric == "grm"
    packed = cfg.pack_stream == "packed" or (
        cfg.pack_stream == "auto" and metric in gram.DOSAGE_METRICS
    )
    update = sketch.make_update(plan, metric, packed=packed,
                                grm_precise=cfg.grm_precise)

    # The memory story, in telemetry: what this run holds vs what the
    # dense route would have had to allocate for the same cohort.
    telemetry.gauge_set("solver.rung", RUNG_ID[cfg.solver])
    telemetry.gauge_set("solver.rank", float(rank))
    telemetry.gauge_set("solver.state_bytes",
                        float(sketch.state_bytes(n, rank)))
    telemetry.gauge_set("solver.nxn_bytes_avoided",
                        float(sketch.nxn_bytes(n, metric)))

    metric_tag = f"solver:{metric}"
    extra = {"solver": cfg.solver, "kind": kind, "rank": int(rank),
             "iters": int(cfg.sketch_iters), "seed": int(cfg.sketch_seed)}
    bv = job.ingest.block_variants

    def save_state(state: dict, cursor: int, pass_idx: int) -> None:
        acc = dict(state)
        acc["passno"] = np.int64(pass_idx)
        ckpt.save(cfg.checkpoint_dir, acc, cursor, metric_tag, bv,
                  source.sample_ids, extra=extra)

    state, start_pass, start_variant = None, 0, 0
    if cfg.checkpoint_dir:
        restored = ckpt.load(cfg.checkpoint_dir, metric_tag,
                             source.sample_ids, block_variants=bv,
                             leaves=list(_CKPT_LEAVES), expect_extra=extra)
        if restored is not None:
            acc, start_variant, _stats = restored
            start_pass = int(np.asarray(acc.pop("passno")))
            repl = meshes.replicated(plan.mesh)
            state = {k: jax.device_put(np.asarray(v), repl)
                     for k, v in acc.items()}
    if state is None:
        state = sketch.init_state(plan, n, rank, cfg.sketch_seed)

    checkpointing = bool(cfg.checkpoint_dir and cfg.checkpoint_every_blocks)
    n_variants = 0
    yb = tr = None
    for pass_idx in range(start_pass, passes):
        cb = None
        if checkpointing:
            def cb(st, cur, _p=pass_idx):
                save_state(st, cur, _p)
        with telemetry.span("solver.pass", cat="solver", index=pass_idx,
                            rung=cfg.solver):
            state, n_variants = R.run_sketch_pass(
                job, source, timer, plan, update, state,
                start_variant=start_variant if pass_idx == start_pass else 0,
                packed=packed,
                block_flops=lambda v: sketch.flops_per_block(n, v, rank),
                save_cb=cb,
            )
        telemetry.count("solver.passes")
        yb, tr = sketch.finalize_pass(state["y"], state["trace"],
                                      state["nvar"], is_grm=is_grm)
        if pass_idx + 1 < passes:
            # Subspace iteration: next pass tracks the orthonormalized
            # range of this one. The output of orthonormalize stays
            # column-centered (right multiplication), so it is already
            # the J q the update streams against.
            qc = solve.orthonormalize(yb, plan)
            state = sketch.reset_for_pass(plan, state, qc)
            if checkpointing:
                save_state(state, 0, pass_idx + 1)

    k = cfg.num_pc
    with timer.phase("eigh"):
        with telemetry.span("solver.solve", cat="solver", rung=cfg.solver):
            if cfg.solver == "sketch":
                vals, vecs = solve.nystrom_eigs(yb, state["qc"], k, plan)
            else:
                vals, vecs = solve.rayleigh_eigs(yb, state["qc"], k, plan)
            vals, vecs, tr = hard_sync((vals, vecs, tr))

    vals_np = np.asarray(vals)
    if kind == "pca":
        # The PCA driver's projection convention: coords = C v = lambda v
        # (B is PSD for every sketchable metric, so top == top-|lambda|).
        coords = np.asarray(vecs) * vals_np[None, :]
        prop = None
    else:
        coords = np.asarray(coords_from_eigpairs(vals, vecs))
        prop = np.maximum(vals_np, 0.0) / max(float(np.asarray(tr)), 1e-30)
    return SketchSolveResult(
        sample_ids=source.sample_ids,
        eigenvalues=vals_np,
        coords=coords,
        proportion=prop,
        n_variants=n_variants,
        rung=cfg.solver,
        rank=int(rank),
        passes=passes,
    )

"""The sketch-solver job driver: passes -> solve, under the ladder.

Orchestrates the pieces of the subsystem into the pipeline-facing call
(:func:`run_sketch_solve`, consumed by ``pipelines/jobs.py``):

- stream 1 + extra passes over the cohort through
  :func:`pipelines.runner.run_sketch_pass` (the same staged-ring feed,
  ``gram.block`` spans, and checkpoint cadence as the gram routes — a
  supervised sketch job is killed/resumed by exactly the machinery that
  supervises a gram job);
- between passes of the ``corrected`` rung, orthonormalize the sketch
  (shifted CholeskyQR2) and iterate — textbook subspace iteration where
  every B@Q product is a streamed pass, never a materialized matmul;
- terminal solve per rung: single-pass Nystrom (``sketch``) or Rayleigh
  Ritz pairs (``corrected``); ``exact`` never reaches this module.

Checkpoint/resume: the sketch state is an ordinary accumulator dict to
``core/checkpoint.py`` (leaves ``y``/``qc``/``trace``/``nvar`` plus the
``passno`` cursor), namespaced under ``solver:<metric>`` so a sketch
checkpoint can never be confused with a gram one, with the rung/rank/
seed recorded as the manifest's ``extra`` — a resume under different
probe settings is rejected, not silently mixed. Probes themselves are
re-derived from ``--sketch-seed``, so a killed job resumes
bit-identically (tests/test_kill_matrix.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np

from spark_examples_tpu import kernels
from spark_examples_tpu.core import checkpoint as ckpt
from spark_examples_tpu.core import meshes, telemetry
from spark_examples_tpu.core.config import SOLVER_RUNG_ID, JobConfig
from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync
from spark_examples_tpu.ops.eigh import coords_from_eigpairs
from spark_examples_tpu.parallel.gram_sharded import GramPlan
from spark_examples_tpu.pipelines import runner as R
from spark_examples_tpu.solvers import sketch, solve

RUNG_ID = SOLVER_RUNG_ID  # re-exported; the numbers live with the ladder

_CKPT_LEAVES = sketch.STATE_LEAVES + ("passno",)


@dataclass
class SketchSolveResult:
    """What the pipeline needs back: host-resident eigenpairs/coords
    plus the provenance the model artifact and telemetry record."""

    sample_ids: list[str]
    eigenvalues: np.ndarray  # (k,) descending
    coords: np.ndarray  # (N, k)
    proportion: np.ndarray | None  # PCoA only (share of total inertia)
    n_variants: int
    rung: str
    rank: int
    passes: int
    # Model-artifact payload (populated only when the job carries
    # --save-model and the rung/metric combination can persist one —
    # see kernels.check_factorized_savable): the RAW Ritz basis plus
    # the streamed centering statistics, and for dual metrics the
    # denominator scale diagonal with its floor.
    eigvecs: np.ndarray | None = None
    colmean: np.ndarray | None = None
    grand: float | None = None
    scale: np.ndarray | None = None
    scale_floor: float = 0.0
    seed: int = 0


def sketch_plan(job: JobConfig) -> GramPlan:
    """The sketch's distribution plan: blocks variant-sharded over the
    mesh exactly like the gram path, state replicated. Never tile2d —
    there is no N x N accumulator to tile, so neither the acc-budget
    heuristic nor the sample-divisibility constraint applies."""
    meshes.maybe_init_distributed()
    mesh = meshes.make_mesh(shape=job.compute.mesh_shape)
    mode = "replicated" if mesh.devices.size == 1 else "variant"
    return GramPlan(mesh, mode)


def run_sketch_solve(job: JobConfig, source, timer: PhaseTimer,
                     kind: str) -> SketchSolveResult:
    """Run the full sketch/corrected solve for a pcoa or pca job —
    dispatching on the kernel's declared streamability: a FactorSketch
    runs the PR-7 single-factor construction below; a DualSketch (ratio
    metrics: ibs, jaccard) runs :func:`_run_dual_solve`."""
    cfg = job.compute
    metric = "shared-alt" if kind == "pca" else (cfg.metric or "ibs")
    sketch.check_sketchable(metric, cfg.solver)
    if cfg.backend == "cpu-reference":
        raise ValueError(
            "--solver sketch/corrected runs on the jax backend; the CPU "
            "oracle implements the dense reference route only"
        )
    if job.model_path:
        # Config-time validation already ran (JobConfig.__post_init__);
        # this defense-in-depth call also knows the resolved kind, so a
        # hand-built config cannot sneak an unsavable combination in.
        kernels.check_factorized_savable(metric, cfg.solver, kind)
    plan = sketch_plan(job)
    if jax.process_count() > 1:
        raise ValueError(
            "--solver sketch/corrected is single-process for now (the "
            "state psums span the local mesh); run multi-host jobs with "
            "--solver exact"
        )
    kern = kernels.get(metric)
    spec = kern.sketch
    if isinstance(spec, kernels.DualSketch):
        return _run_dual_solve(job, source, timer, kind, metric, plan)
    n = source.n_samples
    rank = min(cfg.sketch_rank, n)
    passes = 1 + (cfg.sketch_iters if cfg.solver == "corrected" else 0)
    is_grm = spec.uses_nvar
    packed = cfg.pack_stream == "packed" or (
        cfg.pack_stream == "auto" and kern.pack_auto
    )
    update = sketch.make_update(plan, metric, packed=packed,
                                grm_precise=cfg.grm_precise)

    # The memory story, in telemetry: what this run holds vs what the
    # dense route would have had to allocate for the same cohort.
    telemetry.gauge_set("solver.rung", RUNG_ID[cfg.solver])
    telemetry.gauge_set("solver.rank", float(rank))
    telemetry.gauge_set("solver.dual", 0.0)
    telemetry.gauge_set("solver.dual_den_defect", 0.0)  # n/a here; unstale
    telemetry.gauge_set("solver.state_bytes",
                        float(sketch.state_bytes(n, rank)))
    telemetry.gauge_set("solver.nxn_bytes_avoided",
                        float(sketch.nxn_bytes(n, metric)))

    metric_tag = f"solver:{metric}"
    extra = {"solver": cfg.solver, "kind": kind, "rank": int(rank),
             "iters": int(cfg.sketch_iters), "seed": int(cfg.sketch_seed)}
    bv = job.ingest.block_variants

    def save_state(state: dict, cursor: int, pass_idx: int) -> None:
        acc = dict(state)
        acc["passno"] = np.int64(pass_idx)
        ckpt.save(cfg.checkpoint_dir, acc, cursor, metric_tag, bv,
                  source.sample_ids, extra=extra)

    state, start_pass, start_variant = None, 0, 0
    if cfg.checkpoint_dir:
        restored = ckpt.load(cfg.checkpoint_dir, metric_tag,
                             source.sample_ids, block_variants=bv,
                             leaves=list(_CKPT_LEAVES), expect_extra=extra)
        if restored is not None:
            acc, start_variant, _stats = restored
            start_pass = int(np.asarray(acc.pop("passno")))
            repl = meshes.replicated(plan.mesh)
            state = {k: jax.device_put(np.asarray(v), repl)
                     for k, v in acc.items()}
    if state is None:
        state = sketch.init_state(plan, n, rank, cfg.sketch_seed)

    checkpointing = bool(cfg.checkpoint_dir and cfg.checkpoint_every_blocks)
    n_variants = 0
    yb = tr = None
    for pass_idx in range(start_pass, passes):
        cb = None
        if checkpointing:
            def cb(st, cur, _p=pass_idx):
                save_state(st, cur, _p)
        with telemetry.span("solver.pass", cat="solver", index=pass_idx,
                            rung=cfg.solver):
            state, n_variants = R.run_sketch_pass(
                job, source, timer, plan, update, state,
                start_variant=start_variant if pass_idx == start_pass else 0,
                packed=packed,
                block_flops=lambda v: sketch.flops_per_block(n, v, rank),
                save_cb=cb,
            )
        telemetry.count("solver.passes")
        yb, tr = sketch.finalize_pass(state["y"], state["trace"],
                                      state["nvar"], is_grm=is_grm)
        if pass_idx + 1 < passes:
            # Subspace iteration: next pass tracks the orthonormalized
            # range of this one. The output of orthonormalize stays
            # column-centered (right multiplication), so it is already
            # the J q the update streams against.
            qc = solve.orthonormalize(yb, plan)
            state = sketch.reset_for_pass(plan, state, qc)
            if checkpointing:
                save_state(state, 0, pass_idx + 1)

    k = cfg.num_pc
    with timer.phase("eigh"):
        with telemetry.span("solver.solve", cat="solver", rung=cfg.solver):
            if cfg.solver == "sketch":
                vals, vecs = solve.nystrom_eigs(yb, state["qc"], k, plan)
            else:
                vals, vecs = solve.rayleigh_eigs(yb, state["qc"], k, plan)
            vals, vecs, tr = hard_sync((vals, vecs, tr))

    vals_np = np.asarray(vals)
    if kind == "pca":
        # The PCA driver's projection convention: coords = C v = lambda v
        # (B is PSD for every sketchable metric, so top == top-|lambda|).
        coords = np.asarray(vecs) * vals_np[None, :]
        prop = None
    else:
        coords = np.asarray(coords_from_eigpairs(vals, vecs))
        prop = np.maximum(vals_np, 0.0) / max(float(np.asarray(tr)), 1e-30)
    colmean = grand = None
    if job.model_path:
        # Finalize the streamed column mass into the centering
        # statistics the factorized artifact persists (jobs.py saves).
        colmean, grand = sketch.factor_centering(state)
    return SketchSolveResult(
        sample_ids=source.sample_ids,
        eigenvalues=vals_np,
        coords=coords,
        proportion=prop,
        n_variants=n_variants,
        rung=cfg.solver,
        rank=int(rank),
        passes=passes,
        eigvecs=np.asarray(vecs) if job.model_path else None,
        colmean=colmean,
        grand=grand,
        seed=int(cfg.sketch_seed),
    )


_DUAL_CKPT_LEAVES = sketch.DUAL_STATE_LEAVES + ("passno",)


def _run_dual_solve(job: JobConfig, source, timer: PhaseTimer, kind: str,
                    metric: str, plan: GramPlan) -> SketchSolveResult:
    """The dual-sketch solve for ratio metrics (similarity = NUM ⊘ DEN):
    pass 0 streams BOTH the numerator and the pair-count-denominator
    sketches in one variant pass (same staged-ring feed, ``gram.block``
    spans, cursors, and checkpoint cadence as every other streamed
    job); the denominator's dominant Perron rank-1 factor ``a a^T`` is
    then extracted from ITS sketch, and the solve targets the scaled
    operator ``B = J diag(1/a) NUM diag(1/a) J ~ J (NUM ⊘ DEN) J`` —
    exact when DEN is rank-1 (e.g. IBS pair counts with no missing
    calls). Corrected-rung passes are TRUE power steps of B (the scale
    folds into the streamed probes), ending in a Rayleigh solve; the
    single-pass rung (PSD numerators only — the registry's ``num_psd``
    gate) solves from the congruence-transformed Nystrom factorization.

    Geometry note: B embeds the **Gower geometry of the similarity**
    (squared distance ``s_ii + s_jj - 2 s_ij``). For kernels whose
    distance convention IS the Gower transform (jaccard), the rungs
    converge to the exact route's PCoA; for ibs — whose native distance
    is ``d1/2m`` directly — the sketch embeds the monotone-transformed
    ``sqrt(2 * dist)`` geometry instead (same ordering, same structure
    recovery; README 'Solvers & the accuracy ladder').

    Proportion-explained is None: the scaled operator's total inertia
    is not streamable before the scale exists, and a made-up
    denominator would be dishonest.
    """
    cfg = job.compute
    n = source.n_samples
    rank = min(cfg.sketch_rank, n)
    passes = 1 + (cfg.sketch_iters if cfg.solver == "corrected" else 0)
    kern = kernels.get(metric)
    packed = cfg.pack_stream == "packed" or (
        cfg.pack_stream == "auto" and kern.pack_auto
    )
    # Pass 0 streams num + den + exact diagonal; later passes are pure
    # power steps of the scaled operator and stream the numerator only.
    updates = {
        True: sketch.make_dual_update(plan, metric, packed=packed,
                                      with_den=True),
        False: sketch.make_dual_update(plan, metric, packed=packed,
                                       with_den=False),
    }

    telemetry.gauge_set("solver.rung", RUNG_ID[cfg.solver])
    telemetry.gauge_set("solver.rank", float(rank))
    telemetry.gauge_set("solver.dual", 1.0)
    telemetry.gauge_set("solver.dual_den_defect", 0.0)  # real value after pass 0
    telemetry.gauge_set("solver.state_bytes",
                        float(sketch.dual_state_bytes(n, rank)))
    telemetry.gauge_set("solver.nxn_bytes_avoided",
                        float(sketch.nxn_bytes(n, metric)))

    metric_tag = f"solver:{metric}"
    extra = {"solver": cfg.solver, "kind": kind, "rank": int(rank),
             "iters": int(cfg.sketch_iters), "seed": int(cfg.sketch_seed),
             "dual": True}
    bv = job.ingest.block_variants

    def save_state(state: dict, cursor: int, pass_idx: int) -> None:
        acc = dict(state)
        acc["passno"] = np.int64(pass_idx)
        ckpt.save(cfg.checkpoint_dir, acc, cursor, metric_tag, bv,
                  source.sample_ids, extra=extra)

    state, start_pass, start_variant = None, 0, 0
    if cfg.checkpoint_dir:
        restored = ckpt.load(cfg.checkpoint_dir, metric_tag,
                             source.sample_ids, block_variants=bv,
                             leaves=list(_DUAL_CKPT_LEAVES),
                             expect_extra=extra)
        if restored is not None:
            acc, start_variant, _stats = restored
            start_pass = int(np.asarray(acc.pop("passno")))
            repl = meshes.replicated(plan.mesh)
            state = {k: jax.device_put(np.asarray(v), repl)
                     for k, v in acc.items()}
    if state is None:
        state = sketch.init_dual_state(plan, n, rank, cfg.sketch_seed)

    checkpointing = bool(cfg.checkpoint_dir and cfg.checkpoint_every_blocks)
    n_variants = 0
    by = None
    for pass_idx in range(start_pass, passes):
        cb = None
        if checkpointing:
            def cb(st, cur, _p=pass_idx):
                save_state(st, cur, _p)
        with_den = pass_idx == 0
        with telemetry.span("solver.pass", cat="solver", index=pass_idx,
                            rung=cfg.solver, dual=True):
            state, n_variants = R.run_sketch_pass(
                job, source, timer, plan, updates[with_den], state,
                start_variant=start_variant if pass_idx == start_pass else 0,
                packed=packed,
                block_flops=lambda v, _wd=with_den: (
                    sketch.dual_flops_per_block(n, v, rank, metric,
                                                with_den=_wd)),
                save_cb=cb,
            )
        telemetry.count("solver.passes")
        if pass_idx == 0:
            # The denominator has now been seen once: its exact
            # streamed diagonal becomes the rank-1 scale, and the
            # denominator sketch prices the rank-1 residual the scaled
            # operator absorbs (solver.dual_den_defect — the honesty
            # gauge for the 'controlled approximation' claim).
            state = dict(state)
            state["scale"], defect = sketch.dual_scale(state, plan)
            telemetry.gauge_set("solver.dual_den_defect",
                                float(np.asarray(defect)))
        by = sketch.dual_apply(state)
        if pass_idx + 1 < passes:
            # Subspace iteration on B: orthonormalize the scaled,
            # centered range and fold the scale into the next pass's
            # streamed probes.
            qn = solve.orthonormalize(by, plan)
            state = sketch.reset_dual_pass(plan, state, qn)
            if checkpointing:
                save_state(state, 0, pass_idx + 1)

    k = cfg.num_pc
    with timer.phase("eigh"):
        with telemetry.span("solver.solve", cat="solver", rung=cfg.solver,
                            dual=True):
            if cfg.solver == "sketch":
                vals, vecs = solve.nystrom_eigs_scaled(
                    state["y"], state["qc"], by, k, plan)
            else:
                vals, vecs = solve.rayleigh_eigs(by, state["q"], k, plan)
            vals, vecs = hard_sync((vals, vecs))

    vals_np = np.asarray(vals)
    coords = np.asarray(coords_from_eigpairs(vals, vecs))
    colmean = scale_np = None
    grand = None
    floor = 0.0
    if job.model_path and cfg.solver == "corrected":
        # The dual column mass streams only on the scaled power passes
        # (the scale does not exist during pass 0), so only the
        # corrected rung can persist a factorized artifact — the
        # savable-combination gates upstream enforce exactly this; the
        # rung check here is defense-in-depth, not policy.
        colmean, grand, floor = sketch.dual_centering(state)
        scale_np = np.asarray(state["scale"], np.float64)
    return SketchSolveResult(
        sample_ids=source.sample_ids,
        eigenvalues=vals_np,
        coords=coords,
        proportion=None,
        n_variants=n_variants,
        rung=cfg.solver,
        rank=int(rank),
        passes=passes,
        eigvecs=(np.asarray(vecs)
                 if job.model_path and cfg.solver == "corrected" else None),
        colmean=colmean,
        grand=grand,
        scale=scale_np,
        scale_floor=floor,
        seed=int(cfg.sketch_seed),
    )

"""Streaming sketch solver — PCoA/PCA at 100k+ samples, no N x N.

The accuracy ladder (``--solver``, ``core.config.SOLVER_LADDER``):

- ``sketch``    — one streamed pass folds a low-rank range sketch
                  ``Y = B @ Omega`` into (N, rank) state; single-pass
                  Nystrom eigenpairs. O(N * rank) solver memory.
- ``corrected`` — ``sketch`` plus ``--sketch-iters`` extra streamed
                  passes (subspace-iteration power steps) and a
                  Rayleigh solve: each pass multiplies the residual
                  error by ~(lambda_{r+1}/lambda_k)^2.
- ``exact``     — the dense route (materialized Gram -> dense or
                  randomized eigh), unchanged from before this module.

Module map: :mod:`~spark_examples_tpu.solvers.sketch` (streamed
accumulator), :mod:`~spark_examples_tpu.solvers.solve` (sharded
CholeskyQR2 / Nystrom / Rayleigh solve stage),
:mod:`~spark_examples_tpu.solvers.driver` (pass orchestration,
checkpoint/resume, ladder dispatch — what ``pipelines/jobs.py`` calls).
"""

from spark_examples_tpu.core.config import SKETCH_METRICS, SOLVER_LADDER
from spark_examples_tpu.solvers.driver import (
    RUNG_ID,
    SketchSolveResult,
    run_sketch_solve,
)
from spark_examples_tpu.solvers.sketch import check_sketchable

__all__ = [
    "SKETCH_METRICS",
    "SOLVER_LADDER",
    "RUNG_ID",
    "SketchSolveResult",
    "run_sketch_solve",
    "check_sketchable",
]

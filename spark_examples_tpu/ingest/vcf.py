"""Streaming VCF ingest — the file-based stand-in for the reference's
Genomics-API ``searchVariants`` page loop (SURVEY.md §3.5).

A deliberately dependency-free text parser (plain or gzip VCF): header →
sample ids; records stream in genomic order and are packed column-by-
column into (N, v_blk) int8 dosage blocks. Any non-reference allele
counts toward dosage (multi-allelic sites collapse to alt-carrier
dosage), half-calls count the called allele, and ``.`` genotypes are
missing — the semantics the reference's alt-carrier pair counting implied
(SURVEY.md §3.1 "filter variants with >=1 non-ref call").

Region filtering mirrors the reference's ``--references chr:start:end``
flag: only records inside one of the ranges are yielded.
"""

from __future__ import annotations

import gzip
from dataclasses import dataclass, field
from typing import Iterator, Sequence

import numpy as np

from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest.source import BlockMeta


def _open(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "rt")


def _open_bytes(path: str):
    """Binary record stream — the parse loop stays on bytes so the native
    GT parser (native/codec.cpp vcf_parse_gt) sees the raw line with no
    per-line decode/encode round-trip."""
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _dosage(gt: str) -> int:
    """GT string -> dosage in {-1, 0, 1, 2}."""
    # strip trailing FORMAT subfields if caller passed the whole sample col
    alleles = gt.replace("|", "/").split("/")
    dose = 0
    seen = False
    for a in alleles:
        if a == "." or a == "":
            continue
        seen = True
        if a != "0":
            dose += 1
    if not seen:
        return -1
    return min(dose, 2)


def parse_record_lines(lines, n_samples: int, in_range, path: str,
                       ) -> Iterator[tuple[str, int, np.ndarray]]:
    """Yield (contig, pos, int8 dosage column) from raw VCF record lines.

    THE per-record parse — shared verbatim by the serial stream
    (``VcfSource._records``) and the byte-range shard workers of the
    parallel ingest engine (ingest/parallel.py), so an N-worker parse is
    bit-identical to the serial one by construction, not by parallel
    maintenance of two parsers. ``lines`` is any iterable of raw byte
    lines (a file object, a byte-range slice); header/short lines are
    skipped with the same semantics either way.

    Splits only the 9 fixed VCF columns in Python; the per-sample GT
    parse — the loop that runs N times per record — goes through the
    native parser when available (a C call that releases the GIL, which
    is what lets shard workers parse concurrently), with a GT-string-
    cached Python fallback carrying identical semantics (pinned by tests
    under SPARK_TPU_NO_NATIVE=1).
    """
    from spark_examples_tpu import native

    n = n_samples
    use_native = native.load() is not None
    gt_cache: dict[bytes, int] = {}
    short_records = 0
    for line in lines:
        if line.startswith(b"#"):
            continue
        # \r too: binary reads see CRLF files raw (text mode's
        # universal newlines used to hide this), and a trailing
        # \r would corrupt the last sample's GT.
        line = line.rstrip(b"\r\n")
        prefix = line.split(b"\t", 9)
        if len(prefix) < 10:
            continue
        contig, pos = prefix[0].decode(), int(prefix[1])
        if not in_range(contig, pos):
            continue
        fmt = prefix[8].split(b":")
        try:
            gt_idx = fmt.index(b"GT")
        except ValueError:
            continue  # no genotypes at this site
        col = np.empty(n, dtype=np.int8)
        if use_native and native.vcf_parse_gt(line, gt_idx, n, col):
            yield contig, pos, col
            continue
        gts = prefix[9].split(b"\t")
        if len(gts) < n:
            # Truncated/malformed record (interrupted download,
            # mid-line cut). Skipping silently would present a
            # clean job computed on reduced data — warn loudly,
            # once per stream.
            short_records += 1
            if short_records == 1:
                import warnings

                warnings.warn(
                    f"{path}: record at {contig}:{pos} has "
                    f"{len(gts)} sample columns, expected {n} — "
                    "skipping record(s); the file may be "
                    "truncated or malformed",
                    RuntimeWarning,
                    stacklevel=3,
                )
            continue
        for i in range(n):
            # VCF permits dropping trailing subfields, so a short
            # sample column means GT is absent -> missing (the
            # native parser's 'missing subfield' branch).
            sub = gts[i].split(b":")
            gt = sub[gt_idx] if gt_idx < len(sub) else b""
            d = gt_cache.get(gt)
            if d is None:
                d = _dosage(gt.decode())
                gt_cache[gt] = d
            col[i] = d
        yield contig, pos, col


@dataclass
class VcfSource:
    path: str
    references: Sequence[ReferenceRange] = ()
    _samples: list[str] | None = field(default=None, repr=False)
    _n_variants: int | None = field(default=None, repr=False)

    def _read_header(self) -> list[str]:
        with _open(self.path) as f:
            for line in f:
                if line.startswith("#CHROM"):
                    return line.rstrip("\n").split("\t")[9:]
                if not line.startswith("#"):
                    break
        raise ValueError(f"{self.path}: no #CHROM header line")

    @property
    def sample_ids(self) -> list[str]:
        if self._samples is None:
            self._samples = self._read_header()
        return self._samples

    @property
    def n_samples(self) -> int:
        return len(self.sample_ids)

    @property
    def n_variants(self) -> int:
        """Record count (single cheap pre-scan, cached).

        Counts with the exact yield conditions of ``_records`` — range,
        GT present in FORMAT, enough sample columns (a C-speed tab
        count) — but WITHOUT the per-sample GT parse, which is ~all of
        a full parse's cost at cohort widths. The ETL ``pack`` command
        calls this before its real pass; a full-parse count here would
        parse the file twice.
        """
        if self._n_variants is None:
            n = self.n_samples
            count = 0
            with _open_bytes(self.path) as f:
                for line in f:
                    if line.startswith(b"#"):
                        continue
                    line = line.rstrip(b"\r\n")
                    prefix = line.split(b"\t", 9)
                    if len(prefix) < 10:
                        continue
                    if not self._in_range(prefix[0].decode(),
                                          int(prefix[1])):
                        continue
                    if b"GT" not in prefix[8].split(b":"):
                        continue
                    if prefix[9].count(b"\t") + 1 < n:
                        continue  # short record (skipped by _records)
                    count += 1
            self._n_variants = count
        return self._n_variants

    def _in_range(self, contig: str, pos: int) -> bool:
        if not self.references:
            return True
        for r in self.references:
            if r.contig == contig and r.start <= pos < r.end:
                return True
        return False

    def _records(self) -> Iterator[tuple[str, int, np.ndarray]]:
        """Yield (contig, pos, int8 dosage column) for the whole file."""
        with _open_bytes(self.path) as f:
            yield from parse_record_lines(
                f, self.n_samples, self._in_range, self.path
            )

    def blocks(self, block_variants: int, start_variant: int = 0):
        """Stream (N, <=block_variants) blocks.

        Blocks never span a contig boundary (a boundary flushes the
        current partial block), so ``BlockMeta.contig`` is exact for
        every variant in the block. Consequently the resume cursor is a
        plain record ordinal — any ``start_variant`` a previous stream's
        ``meta.stop`` produced is valid, aligned or not.
        """
        cols: list[np.ndarray] = []
        positions: list[int] = []
        cur_contig: str | None = None
        idx = 0
        emitted_start = start_variant
        seen = 0

        def flush():
            nonlocal cols, positions, idx, emitted_start
            block = (
                np.stack(cols, axis=1),
                BlockMeta(
                    idx,
                    emitted_start,
                    emitted_start + len(cols),
                    cur_contig,
                    np.asarray(positions, np.int64),
                ),
            )
            emitted_start += len(cols)
            idx += 1
            cols, positions = [], []
            return block

        for contig, pos, col in self._records():
            if seen < start_variant:
                seen += 1
                continue
            seen += 1
            if cols and (len(cols) == block_variants or contig != cur_contig):
                yield flush()
            cur_contig = contig
            cols.append(col)
            positions.append(pos)
        if cols:
            yield flush()
        # A completed full pass has counted every record — cache it so a
        # later .n_variants doesn't re-parse the whole file.
        self._n_variants = seen


def write_vcf(
    path: str,
    genotypes: np.ndarray,
    sample_ids: list[str] | None = None,
    contig: str = "chr22",
    start_pos: int = 16_050_000,
) -> None:
    """Write an (N, V) dosage matrix as a minimal diploid VCF (testing and
    interchange; the inverse of VcfSource)."""
    n, v = genotypes.shape
    ids = sample_ids or [f"S{i:06d}" for i in range(n)]
    gt_of = {-1: "./.", 0: "0/0", 1: "0/1", 2: "1/1"}
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wt") as f:
        f.write("##fileformat=VCFv4.2\n")
        f.write(f"##contig=<ID={contig}>\n")
        f.write(
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
            + "\t".join(ids)
            + "\n"
        )
        for j in range(v):
            row = "\t".join(gt_of[int(g)] for g in genotypes[:, j])
            f.write(
                f"{contig}\t{start_pos + j}\trs{j}\tA\tC\t.\tPASS\t.\tGT\t{row}\n"
            )

"""Partitioned, concurrent host-side ingest — the reference's
genomic-range partitioner made operational.

The reference's *entire* sharding strategy was its partitioners: split
each contig into sub-ranges, one Genomics-API page-stream per RDD
partition, all streamed concurrently by executors (SURVEY.md §2.1
"Genomic-range partitioners", §3.5). Here the analogue is host-side:
:func:`~spark_examples_tpu.ingest.source.partition_ranges` decides the
split, and :class:`PartitionedSource` reads the resulting parts with a
bounded pool of reader threads while the consumer drains blocks in
strict part order — so the emitted stream (blocks, metadata, resume
cursors) is *bit-identical* to a sequential
:class:`~spark_examples_tpu.ingest.source.ChainSource` over the same
parts, and downstream accumulation order (hence int32 exactness and
checkpoint parity) is unchanged.

Read-ahead, not reordering: later parts parse while earlier parts are
being consumed and while the chip crunches (the pool's threads overlap
with device compute and with gzip/numpy work that releases the GIL;
pure-Python text parsing time-slices — the honest CPython bound, noted
here rather than hidden).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from dataclasses import dataclass, field

_END = object()


@dataclass
class PartitionedSource:
    """Order-preserving concurrent reader over per-range sources.

    ``parts`` — one GenotypeSource per genomic sub-range (the
    reference's ``VariantsPartition`` analogue), typically built with
    :func:`~spark_examples_tpu.ingest.source.partition_ranges` + one
    ``VcfSource``/``ArraySource`` each. ``max_workers`` parts read ahead
    at once; each buffers at most ``buffer_blocks`` blocks (memory
    bound: workers * buffer * block bytes).
    """

    parts: list
    max_workers: int = 4
    buffer_blocks: int = 4
    _counts: dict = field(default_factory=dict, repr=False)

    def __post_init__(self):
        if not self.parts:
            raise ValueError("PartitionedSource needs >= 1 part")
        ns = {p.n_samples for p in self.parts}
        if len(ns) != 1:
            raise ValueError(f"sources disagree on n_samples: {ns}")

    @property
    def n_samples(self) -> int:
        return self.parts[0].n_samples

    @property
    def n_variants(self) -> int:
        return sum(self._count(k) for k in range(len(self.parts)))

    @property
    def sample_ids(self) -> list[str]:
        return self.parts[0].sample_ids

    def _count(self, k: int) -> int:
        """Variant count of part k (cached; a VCF part pre-scans once)."""
        if k not in self._counts:
            self._counts[k] = self.parts[k].n_variants
        return self._counts[k]

    def blocks(self, block_variants: int, start_variant: int = 0):
        # Locate the resume point. Counting a part is only forced for
        # parts the cursor might lie in — a fresh stream (cursor 0)
        # starts immediately and learns counts from the stream itself.
        first_part, local_start, offset = 0, start_variant, 0
        while local_start > 0:
            if first_part >= len(self.parts):
                return  # cursor at/past the end
            pv = self._count(first_part)
            if local_start < pv:
                break
            local_start -= pv
            offset += pv
            first_part += 1
        if first_part >= len(self.parts):
            return

        active = list(range(first_part, len(self.parts)))
        queues = {k: queue.Queue(maxsize=self.buffer_blocks) for k in active}
        stop = threading.Event()
        sem = threading.BoundedSemaphore(max(1, self.max_workers))

        def put(k: int, item) -> bool:
            while not stop.is_set():
                try:
                    queues[k].put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def read_part(k: int, part_start: int):
            try:
                for item in self.parts[k].blocks(block_variants, part_start):
                    if not put(k, item):
                        return
                put(k, _END)
            except BaseException as e:  # propagate into the consumer
                put(k, e)
            finally:
                sem.release()

        threads: list[threading.Thread] = []

        def maybe_launch():
            # Launch parts in order while worker slots are free; the
            # semaphore caps concurrently-open parts. A finished reader
            # releases its slot, so drained parts make room for later
            # ones automatically.
            while len(threads) < len(active) and sem.acquire(blocking=False):
                k = active[len(threads)]
                t = threading.Thread(
                    target=read_part,
                    args=(k, local_start if k == first_part else 0),
                    name=f"partitioned-reader-{k}",
                    daemon=True,
                )
                threads.append(t)
                t.start()

        idx = 0
        try:
            maybe_launch()
            for k in active:
                last_local_stop = 0
                while True:
                    item = queues[k].get()
                    if item is _END:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    block, meta = item
                    yield block, dataclasses.replace(
                        meta,
                        index=idx,
                        start=meta.start + offset,
                        stop=meta.stop + offset,
                    )
                    idx += 1
                    last_local_stop = meta.stop
                    maybe_launch()
                # Advance the offset past part k. A drained part's final
                # block ends at the part's variant count (streams always
                # run to the part's end, whatever the start cursor), so
                # the stream itself supplies the count; only a part that
                # emitted nothing needs an explicit count.
                if last_local_stop > 0:
                    self._counts.setdefault(k, last_local_stop)
                    offset += last_local_stop
                else:
                    offset += self._count(k)
                maybe_launch()
        finally:
            stop.set()

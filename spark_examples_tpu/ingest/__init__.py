from spark_examples_tpu.ingest import packed, prefetch, source, synthetic, vcf  # noqa: F401
from spark_examples_tpu.ingest.packed import load_packed, save_packed  # noqa: F401
from spark_examples_tpu.ingest.source import (  # noqa: F401
    ArraySource,
    BlockMeta,
    ChainSource,
    GenotypeSource,
    partition_ranges,
)
from spark_examples_tpu.ingest.synthetic import SyntheticSource  # noqa: F401
from spark_examples_tpu.ingest.vcf import VcfSource, write_vcf  # noqa: F401

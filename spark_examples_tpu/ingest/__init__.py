from spark_examples_tpu.ingest import (  # noqa: F401
    bitpack,
    packed,
    parallel,
    parquet,
    plink,
    prefetch,
    source,
    synthetic,
    vcf,
)
from spark_examples_tpu.ingest.plink import (  # noqa: F401
    PlinkSource,
    write_plink,
)
from spark_examples_tpu.ingest.packed import (  # noqa: F401
    PACKED_SCHEMA_VERSION,
    Packed2BitSource,
    PackedFormatError,
    load_packed,
    save_packed,
)
from spark_examples_tpu.ingest.source import (  # noqa: F401
    ArraySource,
    BlockMeta,
    ChainSource,
    GenotypeSource,
    partition_ranges,
)
from spark_examples_tpu.ingest.parquet import (  # noqa: F401
    ParquetSource,
    write_parquet,
)
from spark_examples_tpu.ingest.resilient import (  # noqa: F401
    CorruptBlockError,
    IngestExhaustedError,
    RetryingSource,
    RetryPolicy,
)
from spark_examples_tpu.ingest.synthetic import SyntheticSource  # noqa: F401
from spark_examples_tpu.ingest.vcf import VcfSource, write_vcf  # noqa: F401

"""GenotypeSource — the kept-abstract replacement of the reference's
RDD/ingest layers (L2/L3).

The reference streamed variants through a custom ``VariantsRDD`` whose
partitions each paged a Genomics-API ``searchVariants`` range, with
genomic-range partitioners deciding the split (SURVEY.md §2.1 "Variants
RDD", "Genomic-range partitioners"; §3.5 ``VariantsRDD.compute``). This
framework keeps exactly that seam: anything that can yield dense int8
dosage blocks over a sample cohort is a source — synthetic cohorts, VCF
files, packed-array exports standing in for the BigQuery path. Compute
never sees anything but (N, v_blk) blocks + metadata.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Iterator, Protocol, Sequence, runtime_checkable

import numpy as np

from spark_examples_tpu.core.config import ReferenceRange


@dataclass(frozen=True)
class BlockMeta:
    """Metadata for one streamed genotype block.

    ``positions``/``contigs`` are optional per-variant annotations (the
    serializable remnant of the reference's ``Variant`` case class —
    SURVEY.md §2.1 "Serializable data model"); the cursor fields support
    deterministic resume (SURVEY.md §5 "Checkpoint / resume").
    """

    index: int  # block ordinal in the stream
    start: int  # first variant (global index, inclusive)
    stop: int  # past-the-end variant (global index)
    contig: str | None = None
    positions: np.ndarray | None = None  # (v_blk,) int64, optional


@runtime_checkable
class GenotypeSource(Protocol):
    """The ingest contract: sample axis fixed, variant axis streamed.

    Optional attribute ``exact_n_variants`` (absent == False): when
    True, the source guarantees that (a) ``n_variants`` is cheap and
    exact, and (b) ``blocks(bv, start)`` yields **exactly**
    ``ceil((n_variants - start) / bv)`` blocks for any block-aligned
    ``start``, on both transports — i.e. no early flushes at contig
    boundaries. The multi-host feeder uses this to precompute the
    global step count in one allgather (parallel/multihost.py); a
    source that flushes partial blocks mid-stream (multi-contig dense
    stores, ChainSource) must NOT claim it — the feeder trusts the
    claim and raises on a mismatch rather than silently dropping
    variants.
    """

    @property
    def n_samples(self) -> int: ...

    @property
    def n_variants(self) -> int: ...

    @property
    def sample_ids(self) -> list[str]: ...

    def blocks(
        self, block_variants: int, start_variant: int = 0
    ) -> Iterator[tuple[np.ndarray, BlockMeta]]:
        """Yield (int8 (n_samples, <=block_variants) dosage block, meta),
        starting at global variant index ``start_variant`` (resume)."""
        ...


def rechunk(items, width: int, start_variant: int = 0):
    """Re-chunk a stream of (cols, positions | None, contig) pieces into
    steady ``width``-wide (block, BlockMeta) outputs.

    The shared machinery of every stream transform that changes the
    variant count mid-stream (QC filtering, LD pruning, windowing):
    buffers pieces, splits off full-width heads, flushes partials at
    contig boundaries (the "blocks never span a contig" contract), and
    numbers ordinals over the OUTPUT stream. ``start_variant`` skips
    any block starting before it (ceil-align for mid-block cursors,
    exact for self-produced stops). Positions propagate when every
    contributing piece carries them, else None.
    """
    cols: list[np.ndarray] = []
    pos: list[np.ndarray | None] = []
    cur_contig: str | None = None
    idx = 0
    emitted = 0

    def assemble():
        block = cols[0] if len(cols) == 1 else np.concatenate(cols, axis=1)
        positions = (
            (pos[0] if len(pos) == 1 else np.concatenate(pos))
            if all(p is not None for p in pos) else None
        )
        return block, positions

    def emit(block, positions):
        nonlocal idx, emitted
        meta = BlockMeta(idx, emitted, emitted + block.shape[1],
                         cur_contig, positions)
        emitted += block.shape[1]
        idx += 1
        if meta.start >= start_variant:
            yield np.ascontiguousarray(block), meta

    for piece, p, contig in items:
        if cols and contig != cur_contig:
            yield from emit(*assemble())
            cols, pos = [], []
        cur_contig = contig
        if piece.shape[1] == 0:
            continue
        cols.append(piece)
        pos.append(np.asarray(p) if p is not None else None)
        while sum(c.shape[1] for c in cols) >= width:
            block, positions = assemble()
            head, tail = block[:, :width], block[:, width:]
            hp = tp = None
            if positions is not None:
                hp, tp = positions[:width], positions[width:]
            cols = [np.ascontiguousarray(tail)] if tail.shape[1] else []
            pos = (
                ([tp] if positions is not None else [None])
                if tail.shape[1] else []
            )
            yield from emit(head, hp)
    if cols:
        yield from emit(*assemble())


def partition_ranges(
    references: Sequence[ReferenceRange], splits_per_contig: int
) -> list[ReferenceRange]:
    """Split genomic ranges into ~equal sub-ranges.

    The TPU-native successor of the reference's ``VariantsPartitioner``
    ``FixedContigSplits(n)`` strategy (SURVEY.md §2.1): each sub-range is
    an independent ingest unit (the reference made one RDD partition /
    API page-stream per sub-range; here it is a unit of host-side read
    parallelism and the resume granularity).
    """
    out: list[ReferenceRange] = []
    for ref in references:
        span = ref.end - ref.start
        if span <= 0 or splits_per_contig <= 1:
            out.append(ref)
            continue
        step = -(-span // splits_per_contig)
        for s in range(ref.start, ref.end, step):
            out.append(ReferenceRange(ref.contig, s, min(s + step, ref.end)))
    return out


@dataclass
class ArraySource:
    """In-memory / memmapped (N, V) int8 matrix as a source.

    Wraps ``np.load(..., mmap_mode="r")`` arrays too, which makes it the
    packed-column-export stand-in for the reference fork's BigQuery
    ingestion path (SURVEY.md §2.1 "BigQuery ingestion path").
    """

    exact_n_variants = True  # the array's shape is the count

    genotypes: np.ndarray  # (N, V) int8
    ids: list[str] | None = None
    contig: str | None = None
    positions: np.ndarray | None = None

    @property
    def n_samples(self) -> int:
        return int(self.genotypes.shape[0])

    @property
    def n_variants(self) -> int:
        return int(self.genotypes.shape[1])

    @property
    def sample_ids(self) -> list[str]:
        if self.ids is not None:
            return self.ids
        return [f"S{i:06d}" for i in range(self.n_samples)]

    def blocks(self, block_variants: int, start_variant: int = 0):
        v = self.n_variants
        # ceil: a cursor inside/at-the-end-of a partial final block must
        # not re-emit it (cursors are block-aligned or == n_variants).
        first = -(-start_variant // block_variants)
        for idx in range(first, -(-v // block_variants)):
            lo = idx * block_variants
            hi = min(lo + block_variants, v)
            block = np.ascontiguousarray(self.genotypes[:, lo:hi], dtype=np.int8)
            pos = None
            if self.positions is not None:
                pos = self.positions[lo:hi]
            yield block, BlockMeta(idx, lo, hi, self.contig, pos)


@dataclass
class WindowSource:
    """Restrict a source to a contiguous variant window ``[start, stop)``.

    The per-process ingest partition of the multi-host job surface
    (parallel/multihost.py): every process wraps the same underlying
    source in its own window and *reads only that window* — the
    TPU-native successor of the reference's one-RDD-partition-per-
    executor split (SURVEY.md §2.1 "Genomic-range partitioners") for
    sources with cheap random access (synthetic generation, memmapped
    packed/array stores). ``start`` must be aligned to the block grid
    the stream will use; ``stop`` is either block-aligned or the end of
    the underlying source. Cursors (resume) and block ordinals are local
    to the window.
    """

    inner: GenotypeSource
    start: int
    stop: int

    def __post_init__(self):
        if not 0 <= self.start <= self.stop <= self.inner.n_variants:
            raise ValueError(
                f"window [{self.start}, {self.stop}) out of range for a "
                f"{self.inner.n_variants}-variant source"
            )
        # Only advertise packed transport when the inner source has it
        # (prefetch dispatches on hasattr).
        if hasattr(self.inner, "packed_blocks"):
            self.packed_blocks = self._packed_blocks
        # Same capability pattern for the column-window decode path
        # (store decode-straight-into-slab): a multi-host process whose
        # partition is a window over a store (directly or through the
        # retry boundary) then decodes ONLY its own variant slice into
        # its staging slab — no full-chunk materialize, no post-decode
        # slicing (parallel/multihost.py's shard-aware feed).
        if hasattr(self.inner, "decode_range_into") and hasattr(
                self.inner, "block_spans"):
            self.block_spans = self._block_spans
            self.decode_range_into = self._decode_range_into

    @property
    def n_samples(self) -> int:
        return self.inner.n_samples

    @property
    def n_variants(self) -> int:
        return self.stop - self.start

    @property
    def exact_n_variants(self) -> bool:
        # The window bounds are exact iff the inner count they were cut
        # from is (a filtered inner source could under-produce).
        return bool(getattr(self.inner, "exact_n_variants", False))

    @property
    def sample_ids(self) -> list[str]:
        return self.inner.sample_ids

    def _relocalize(self, it):
        idx = 0
        for block, meta in it:
            if meta.start >= self.stop:
                break
            take = min(meta.stop, self.stop) - meta.start
            if take < block.shape[1]:
                block = np.ascontiguousarray(block[:, :take])
            pos = meta.positions
            if pos is not None and take < len(pos):
                pos = pos[:take]
            yield block, dataclasses.replace(
                meta,
                index=idx,
                start=meta.start - self.start,
                stop=meta.start - self.start + take,
                positions=pos,
            )
            idx += 1

    def blocks(self, block_variants: int, start_variant: int = 0):
        if self.start % block_variants:
            raise ValueError(
                f"window start {self.start} not aligned to block grid "
                f"{block_variants} — inner cursors would ceil-align past "
                "the window's own variants"
            )
        yield from self._relocalize(
            self.inner.blocks(block_variants, self.start + start_variant)
        )

    def _block_spans(self, block_variants: int, start_variant: int = 0):
        """Window-relocalized spans of the inner source's block grid —
        (lo, hi, meta) in the WINDOW's coordinates, truncated at the
        window end. The decode-free twin of :meth:`blocks` for callers
        that drive :meth:`decode_range_into` into their own buffers."""
        if self.start % block_variants:
            raise ValueError(
                f"window start {self.start} not aligned to block grid "
                f"{block_variants} — inner cursors would ceil-align past "
                "the window's own variants"
            )
        idx = 0
        for lo, hi, meta in self.inner.block_spans(
                block_variants, self.start + start_variant):
            if lo >= self.stop:  # inner coordinates, like blocks()
                break
            hi = min(hi, self.stop)
            take = hi - lo
            pos = meta.positions
            if pos is not None and take < len(pos):
                pos = pos[:take]
            yield lo - self.start, hi - self.start, dataclasses.replace(
                meta,
                index=idx,
                start=lo - self.start,
                stop=hi - self.start,
                positions=pos,
            )
            idx += 1

    def _decode_range_into(self, lo: int, hi: int, out, col_off: int = 0):
        # Bounds-checked against the WINDOW, not just the inner source:
        # an over-long span would otherwise silently decode another
        # partition's variants (double-counted into the global
        # accumulation in a multi-host job) instead of erroring.
        if not 0 <= lo <= hi <= self.n_variants:
            raise ValueError(
                f"variant range [{lo}, {hi}) out of bounds for a "
                f"{self.n_variants}-variant window"
            )
        self.inner.decode_range_into(self.start + lo, self.start + hi,
                                     out, col_off)

    def _packed_blocks(self, block_variants: int, start_variant: int = 0):
        if self.start % block_variants:
            raise ValueError(
                f"window start {self.start} not aligned to block grid "
                f"{block_variants}"
            )
        it = self.inner.packed_blocks(
            block_variants, self.start + start_variant
        )
        # Packed blocks are (N, width/4) bytes; _relocalize's column
        # truncation must therefore work in bytes.
        idx = 0
        for pblock, meta in it:
            if meta.start >= self.stop:
                break
            from spark_examples_tpu.ingest import bitpack

            take = min(meta.stop, self.stop) - meta.start
            take_bytes = bitpack.packed_width(take)
            if take_bytes < pblock.shape[1]:
                pblock = np.ascontiguousarray(pblock[:, :take_bytes])
            yield pblock, dataclasses.replace(
                meta,
                index=idx,
                start=meta.start - self.start,
                stop=meta.start - self.start + take,
                positions=None,
            )
            idx += 1


@dataclass
class EmptyShare:
    """A zero-variant partition that still answers cohort metadata.

    Multi-host range partitioning can leave a process with no ranges at
    all (more processes than sub-ranges of a small contig). Building the
    underlying source with ``references=[]`` would mean "no filter" and
    silently re-read the WHOLE file into the global accumulation — so an
    empty share gets this instead: sample metadata from the inner source
    (consistency checks still hold), an empty stream, and the consensus
    feeder pads its steps with missing slabs.
    """

    inner: GenotypeSource

    exact_n_variants = True  # zero, exactly

    @property
    def n_samples(self) -> int:
        return self.inner.n_samples

    @property
    def n_variants(self) -> int:
        return 0

    @property
    def sample_ids(self) -> list[str]:
        return self.inner.sample_ids

    def blocks(self, block_variants: int, start_variant: int = 0):
        return iter(())


def window_for_process(
    n_variants: int, block_variants: int, process_index: int,
    process_count: int,
) -> tuple[int, int]:
    """Block-aligned contiguous [start, stop) window for one process.

    Splits ceil(V / bv) blocks into ``process_count`` contiguous runs of
    at most ceil(n_blocks / P) blocks each; trailing processes may get an
    empty window when blocks run out (their stream is empty and the
    multi-host feeder pads them with missing slabs).
    """
    n_blocks = -(-n_variants // block_variants)
    per = -(-n_blocks // max(1, process_count))
    start = min(process_index * per * block_variants, n_variants)
    stop = min((process_index + 1) * per * block_variants, n_variants)
    return start, stop


def concat_sources(sources: Sequence[GenotypeSource]) -> "ChainSource":
    return ChainSource(list(sources))


@dataclass
class ChainSource:
    """Concatenate sources along the variant axis (multi-contig cohorts:
    one source per reference range, mirroring partitioned ingest)."""

    parts: list

    def __post_init__(self):
        ns = {p.n_samples for p in self.parts}
        if len(ns) != 1:
            raise ValueError(f"sources disagree on n_samples: {ns}")

    @property
    def n_samples(self) -> int:
        return self.parts[0].n_samples

    @property
    def n_variants(self) -> int:
        return sum(p.n_variants for p in self.parts)

    # NOT exact_n_variants: blocks() restarts the grid at every part
    # boundary (a partial tail block per part), so the stream's block
    # count is not ceil(total / bv) unless every part happens to align.

    @property
    def sample_ids(self) -> list[str]:
        return self.parts[0].sample_ids

    def blocks(self, block_variants: int, start_variant: int = 0):
        offset = 0
        idx = 0
        for part in self.parts:
            pv = part.n_variants
            if start_variant >= offset + pv:
                offset += pv
                continue
            local_start = max(0, start_variant - offset)
            # local_start is passed through verbatim: parts ceil-align a
            # mid-block cursor to the next block boundary (ArraySource)
            # or treat it as an exact record ordinal (VcfSource) — both
            # are correct for cursors this same geometry produced, which
            # is the only kind checkpoint/resume ever feeds in.
            for block, meta in part.blocks(block_variants, local_start):
                yield block, dataclasses.replace(
                    meta,
                    index=idx,
                    start=meta.start + offset,
                    stop=meta.stop + offset,
                )
                idx += 1
            offset += pv

"""PLINK 1.x .bed/.bim/.fam ingest — the field-standard 2-bit container.

The reference ingested cohort genotypes from the Genomics API / BigQuery
exports (SURVEY.md §2.1); the on-disk equivalent every population-
genetics shop actually has is a PLINK fileset, so the rebuild reads it
natively. The .bed payload is *SNP-major*: 3 magic bytes, then per
variant ceil(N/4) bytes, each holding four samples at 2 bits (LSB
first). Code semantics differ from this framework's 2-bit codec
(ingest/bitpack.py) and the axes are transposed (samples-within-variant
vs variants-within-sample), so reading is a 256-entry LUT decode of the
memmapped byte rows plus one transpose per block — no per-genotype
Python. The dosage counts A1 alleles (PLINK's usual minor allele):

    0b00 A1/A1 -> 2      0b10 A1/A2 -> 1
    0b11 A2/A2 -> 0      0b01 missing -> -1

Blocks never span a chromosome boundary (same contract as VcfSource, so
``BlockMeta.contig`` is exact); resume cursors ceil-align to the block
grid like ArraySource — both geometries only ever see cursors they
produced. The streaming layer's ``pack=True`` transport re-packs blocks
into the framework codec in the producer thread (native codec when
available), so PLINK filesets ride the 4x-smaller host→device path with
no extra plumbing.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest.source import BlockMeta

_MAGIC = bytes([0x6C, 0x1B])
_SNP_MAJOR = 0x01

# byte -> 4 int8 dosages (LSB pair first).
_LUT = np.empty((256, 4), np.int8)
_CODE_DOSE = np.array([2, -1, 1, 0], np.int8)  # 00, 01, 10, 11
for _b in range(256):
    for _k in range(4):
        _LUT[_b, _k] = _CODE_DOSE[(_b >> (2 * _k)) & 3]


def _resolve_prefix(path: str) -> str:
    """Accept either the fileset prefix or the .bed path itself."""
    return path[:-4] if path.endswith(".bed") else path


@dataclass
class PlinkSource:
    """PLINK fileset as a GenotypeSource (``--source plink``).

    ``references``: optional genomic ranges (the reference's
    ``--references chr:start:end`` semantics, same as VcfSource) — only
    variants inside one of the ranges stream. Block/resume ordinals
    then index the *filtered* stream, exactly like VCF's record
    ordinals, so cursors stay valid for the geometry that made them.
    """

    path: str
    references: Sequence[ReferenceRange] = ()
    _ids: list[str] | None = field(default=None, repr=False)
    _chroms: np.ndarray | None = field(default=None, repr=False)
    _positions: np.ndarray | None = field(default=None, repr=False)
    _sel: np.ndarray | None = field(default=None, repr=False)

    def __post_init__(self):
        self.prefix = _resolve_prefix(self.path)
        bed = self.prefix + ".bed"
        with open(bed, "rb") as f:
            head = f.read(3)
        if len(head) < 3 or head[:2] != _MAGIC:
            raise ValueError(f"{bed}: not a PLINK .bed file (bad magic)")
        if head[2] != _SNP_MAJOR:
            raise ValueError(
                f"{bed}: sample-major .bed layout is not supported "
                "(re-export with modern PLINK, which writes SNP-major)"
            )

    def _read_fam(self) -> list[str]:
        if self._ids is None:
            ids = []
            with open(self.prefix + ".fam") as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        ids.append(parts[1])  # IID
            self._ids = ids
        return self._ids

    def _read_bim(self) -> tuple[np.ndarray, np.ndarray]:
        if self._chroms is None:
            chroms, pos = [], []
            with open(self.prefix + ".bim") as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        chroms.append(parts[0])
                        pos.append(int(parts[3]))
            self._chroms = np.asarray(chroms)
            self._positions = np.asarray(pos, np.int64)
        return self._chroms, self._positions

    @property
    def sample_ids(self) -> list[str]:
        return self._read_fam()

    @property
    def n_samples(self) -> int:
        return len(self._read_fam())

    def _selection(self) -> np.ndarray:
        """Indices of the variants that stream (all, or in-range);
        cached — the O(V x ranges) mask is rebuilt otherwise on every
        ``n_variants`` touch, and the runner touches it several times
        per job."""
        if self._sel is None:
            chroms, positions = self._read_bim()
            if not self.references:
                self._sel = np.arange(chroms.shape[0])
            else:
                mask = np.zeros(chroms.shape[0], bool)
                for r in self.references:
                    mask |= (
                        (chroms == r.contig)
                        & (positions >= r.start)
                        & (positions < r.end)
                    )
                self._sel = np.nonzero(mask)[0]
        return self._sel

    @property
    def n_variants(self) -> int:
        return int(self._selection().shape[0])

    def _bed_rows(self) -> np.ndarray:
        """(V_total, ceil(N/4)) uint8 memmap of the .bed payload — the
        FILE's variant count (every .bim row), not the filtered
        ``n_variants``: the selection indexes into these rows."""
        v_total = int(self._read_bim()[0].shape[0])
        bpr = -(-self.n_samples // 4)  # bytes per variant row
        return np.memmap(self.prefix + ".bed", np.uint8, mode="r",
                         offset=3, shape=(v_total, bpr))

    def blocks(self, block_variants: int, start_variant: int = 0):
        """(N, <=block_variants) int8 dosage blocks, chromosome-flush.

        Decode: LUT over the (w, ceil(N/4)) byte rows -> (w, 4*ceil(N/4))
        -> slice N -> transpose to the framework's sample-major layout.
        Block start/stop are ordinals of the (possibly range-filtered)
        stream; contiguous selections slice the memmap, filtered ones
        fancy-index it.
        """
        chroms, positions = self._read_bim()
        n = self.n_samples
        sel = self._selection()
        v = sel.shape[0]
        if v == 0:
            return
        rows = self._bed_rows()
        # Fixed grid over the selected stream, split at chromosome
        # boundaries (matching VCF's "blocks never span a contig"
        # contract).
        sel_chroms = chroms[sel]
        bounds = [0] + (
            np.nonzero(sel_chroms[1:] != sel_chroms[:-1])[0] + 1
        ).tolist() + [v]
        idx = 0
        for s in range(len(bounds) - 1):
            seg_lo, seg_hi = bounds[s], bounds[s + 1]
            for lo in range(seg_lo, seg_hi, block_variants):
                hi = min(lo + block_variants, seg_hi)
                # Resume by comparing against each block's actual stop:
                # chromosome flushes make the grid irregular, so a
                # ceil(start/bv) block-count (the ArraySource shortcut)
                # would recount flushed blocks and re-emit — double-
                # accumulating — already-checkpointed variants.
                if hi <= start_variant:
                    idx += 1
                    continue
                take = sel[lo:hi]
                if take[-1] - take[0] == hi - lo - 1:  # contiguous run
                    raw = rows[take[0] : take[-1] + 1]  # memmap view
                else:
                    raw = rows[take]  # gather (filtered selection)
                dense = _LUT[raw]  # (w, bpr, 4)
                block = np.ascontiguousarray(
                    dense.reshape(hi - lo, -1)[:, :n].T
                )
                yield block, BlockMeta(
                    idx, lo, hi, str(sel_chroms[lo]), positions[take]
                )
                idx += 1


def write_plink(
    prefix: str,
    genotypes: np.ndarray,
    sample_ids: list[str] | None = None,
    chroms: list[str] | None = None,
    positions: np.ndarray | None = None,
) -> None:
    """Write an (N, V) dosage matrix as a PLINK fileset (testing and
    interchange; the inverse of PlinkSource)."""
    g = np.asarray(genotypes, np.int8)
    n, v = g.shape
    ids = sample_ids or [f"S{i:06d}" for i in range(n)]
    chroms = chroms if chroms is not None else ["1"] * v
    positions = (np.asarray(positions, np.int64) if positions is not None
                 else np.arange(1, v + 1, dtype=np.int64))
    # dosage -> PLINK code (inverse of _CODE_DOSE)
    code_of = np.zeros(4, np.uint8)
    code_of[2], code_of[1], code_of[0] = 0b00, 0b10, 0b11
    codes = np.where(g < 0, 0b01, code_of[np.clip(g, 0, 2)]).astype(np.uint8)
    pad = -n % 4
    if pad:
        codes = np.concatenate(
            [codes, np.full((pad, v), 0b11, np.uint8)], axis=0
        )  # pad samples encode as hom A2 (dosage 0) and are never read
    c = codes.T.reshape(v, -1, 4)  # SNP-major
    rows = (c[..., 0] | (c[..., 1] << 2) | (c[..., 2] << 4)
            | (c[..., 3] << 6))
    with open(prefix + ".bed", "wb") as f:
        f.write(_MAGIC + bytes([_SNP_MAJOR]))
        f.write(np.ascontiguousarray(rows).tobytes())
    with open(prefix + ".fam", "w") as f:
        for i, s in enumerate(ids):
            f.write(f"FAM{i} {s} 0 0 0 -9\n")
    with open(prefix + ".bim", "w") as f:
        for j in range(v):
            f.write(f"{chroms[j]}\trs{j}\t0\t{positions[j]}\tA\tC\n")

"""Parquet variant-table ingest — the literal BigQuery-export stand-in.

The reference fork's BigQuery path pulled 1000-Genomes variant tables
into RDDs (SURVEY.md §2.1 "BigQuery ingestion path"); BigQuery's native
bulk-export interchange format is parquet, so a ``GenotypeSource`` over
a parquet variant table completes that stand-in literally (SURVEY.md §7
step 2). The supported schema is the wide variant-by-sample export:

- one row per variant;
- optional ``contig`` (string) and ``position`` (int64) columns;
- every other column is one sample's int8/integer dosage
  ({0, 1, 2}, negative = missing), column name = sample id.

Reading is row-group granular so parquet's own metadata does the heavy
lifting: under ``--references chr:start:end`` filtering, row groups
whose contig/position column *statistics* cannot overlap any range are
skipped without touching their bytes, and candidate groups decode their
two metadata columns first — the N sample columns are only decoded when
the range mask actually selects rows. Blocks then stream through the
shared :func:`~spark_examples_tpu.ingest.source.rechunk` machinery
(steady widths, contig-boundary flushes, resume cursors), so the
parquet path behaves exactly like every other file source.

pyarrow is the only reader dependency; it is present in this image, but
the import is deferred and failure-gated so environments without it
lose only this source, not the package.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from spark_examples_tpu.core.config import ReferenceRange
from spark_examples_tpu.ingest.source import rechunk

_META_COLUMNS = ("contig", "position")


def _pyarrow():
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - image has pyarrow
        raise ImportError(
            "the parquet source needs pyarrow, which is not installed "
            "in this environment — re-export the table as VCF or a "
            "packed store, or install pyarrow"
        ) from e
    return pq


def _column_np(table, name: str, dtype=None, null_fill=None) -> np.ndarray:
    """A (possibly chunked) table column as one numpy array.

    Arrow NULLs do NOT survive ``np.asarray`` on integer columns — the
    cast backfills them with arbitrary values (observed: 0), which for a
    dosage column silently recodes every uncalled genotype as
    homozygous-reference. So nulls are handled explicitly: filled with
    ``null_fill`` when given (sample columns pass -1, the documented
    missing code), otherwise a hard error naming the column (metadata
    columns, where a null has no meaningful encoding).
    """
    col = table.column(name)
    if col.null_count:
        if null_fill is None:
            raise ValueError(
                f"column {name!r} has {col.null_count} NULL value(s); "
                "NULLs cannot be cast losslessly — re-export the table "
                "without nulls in this column"
            )
        import pyarrow.compute as pc

        col = pc.fill_null(col, null_fill)
    chunks = col.chunks
    arrs = [np.asarray(c) if dtype is None else np.asarray(c, dtype)
            for c in chunks]
    return arrs[0] if len(arrs) == 1 else np.concatenate(arrs)


@dataclass
class ParquetSource:
    path: str
    references: Sequence[ReferenceRange] = ()
    _samples: list[str] | None = field(default=None, repr=False)
    _n_variants: int | None = field(default=None, repr=False)
    _single_contig: bool | None = field(default=None, repr=False)

    def _file(self):
        return _pyarrow().ParquetFile(self.path)

    @property
    def sample_ids(self) -> list[str]:
        if self._samples is None:
            names = [
                c for c in self._file().schema_arrow.names
                if c not in _META_COLUMNS
            ]
            if not names:
                raise ValueError(
                    f"{self.path}: no sample columns (only "
                    f"{_META_COLUMNS}) — not a variant-by-sample table"
                )
            self._samples = names
        return self._samples

    @property
    def n_samples(self) -> int:
        return len(self.sample_ids)

    @property
    def exact_n_variants(self) -> bool:
        """True only when the stream provably satisfies the steady
        ceil(v/bv) block-count contract (GenotypeSource docstring):
        unfiltered AND single-contig — multi-contig tables flush
        partial blocks at contig changes. Single-contig is decided from
        row-group column statistics alone (no data read); inconclusive
        statistics decline conservatively. Min/max statistics IGNORE
        nulls, so a contig column containing any NULL (which ``_pieces``
        treats as its own contig=None run, with boundary flushes) must
        also decline — the statistics must prove ``null_count == 0``
        before one (min == max) value means one contig."""
        if self.references:
            return False
        if self._single_contig is None:
            f = self._file()
            if "contig" not in f.schema_arrow.names:
                self._single_contig = True
            else:
                md = f.metadata
                seen: set = set()
                ok = True
                for rg in range(md.num_row_groups):
                    rg_meta = md.row_group(rg)
                    st = self._rg_stats(rg_meta, "contig")
                    nulls = self._rg_null_count(rg_meta, "contig")
                    if st is None or nulls != 0:
                        ok = False  # inconclusive or null-bearing
                        break
                    seen.update((st[0], st[1]))
                self._single_contig = ok and len(seen) == 1
        return self._single_contig

    @property
    def n_variants(self) -> int:
        if self._n_variants is None:
            f = self._file()
            if not self.references:
                self._n_variants = f.metadata.num_rows
            else:
                # Counting scan over pruned row groups' metadata
                # columns only — no sample data is read.
                count = 0
                for _rg, meta_tbl in self._candidate_groups(f):
                    count += int(self._range_mask(meta_tbl).sum())
                self._n_variants = count
        return self._n_variants

    @staticmethod
    def _rg_stats(rg_meta, name: str):
        """(min, max) statistics of one column in one row group, or
        None when the writer recorded none."""
        for i in range(rg_meta.num_columns):
            col = rg_meta.column(i)
            if col.path_in_schema == name:
                st = col.statistics
                if st is None or not st.has_min_max:
                    return None
                return st.min, st.max
        return None

    @staticmethod
    def _rg_null_count(rg_meta, name: str):
        """Recorded null count of one column in one row group, or None
        when the writer recorded no statistics (conservatively
        inconclusive — NOT zero)."""
        for i in range(rg_meta.num_columns):
            col = rg_meta.column(i)
            if col.path_in_schema == name:
                st = col.statistics
                if st is None or not st.has_null_count:
                    return None
                return int(st.null_count)
        return None

    def _rg_may_overlap(self, rg_meta, names) -> bool:
        """Can this row group contain any row inside the ranges? False
        only on a provable miss (missing statistics keep the group)."""
        cstat = self._rg_stats(rg_meta, "contig") if "contig" in names else None
        pstat = self._rg_stats(rg_meta, "position") if "position" in names else None
        for r in self.references:
            if cstat is not None and not (cstat[0] <= r.contig <= cstat[1]):
                continue
            if pstat is not None and (pstat[1] < r.start or pstat[0] >= r.end):
                continue
            return True
        return False

    def _candidate_groups(self, f):
        """Yield (row-group index, metadata-columns table) for groups
        that may intersect the ranges — the stats-pruned scan both
        counting and streaming share."""
        names = f.schema_arrow.names
        meta_cols = [c for c in _META_COLUMNS if c in names]
        if not meta_cols:
            raise ValueError(
                f"{self.path}: --references filtering needs 'contig' "
                "and 'position' columns in the table"
            )
        for rg in range(f.metadata.num_row_groups):
            if not self._rg_may_overlap(f.metadata.row_group(rg), names):
                continue
            yield rg, f.read_row_group(rg, columns=meta_cols)

    def _range_mask(self, meta_tbl) -> np.ndarray:
        names = meta_tbl.schema.names
        if "contig" not in names or "position" not in names:
            raise ValueError(
                f"{self.path}: --references filtering needs 'contig' "
                "and 'position' columns in the table"
            )
        contigs = np.asarray(meta_tbl.column("contig").to_pylist())
        pos = _column_np(meta_tbl, "position", np.int64)
        mask = np.zeros(meta_tbl.num_rows, bool)
        for r in self.references:
            mask |= (contigs == r.contig) & (pos >= r.start) & (pos < r.end)
        return mask

    def _pieces(self):
        """Yield (int8 (N, v) piece, positions | None, contig | None) per
        row group, split on contig changes (the rechunk contract)."""
        f = self._file()
        names = f.schema_arrow.names
        samples = self.sample_ids
        has_contig = "contig" in names
        has_pos = "position" in names
        meta_cols = [c for c in _META_COLUMNS if c in names]

        if self.references:
            groups = self._candidate_groups(f)
        else:
            groups = (
                (rg, f.read_row_group(rg, columns=meta_cols)
                 if meta_cols else None)
                for rg in range(f.metadata.num_row_groups)
            )
        for rg, meta_tbl in groups:
            if self.references:
                mask = self._range_mask(meta_tbl)
                if not mask.any():
                    continue  # sample columns never decoded
            else:
                mask = None
            data = f.read_row_group(rg, columns=samples)
            # (v_rows, N) → (N, v): one astype per sample column, then a
            # stack — columnar decode, no per-record Python loop. NULL
            # dosages (routine in BigQuery exports for uncalled
            # genotypes) become -1, the documented missing code — NOT
            # the silent NULL->0 (homozygous-reference) an unchecked
            # arrow->numpy cast produces.
            cols = np.stack(
                [_column_np(data, s, np.int8, null_fill=-1)
                 for s in samples]
            )
            pos = (
                _column_np(meta_tbl, "position", np.int64)
                if has_pos else None
            )
            contigs = (
                np.asarray(meta_tbl.column("contig").to_pylist())
                if has_contig else None
            )
            if mask is not None:
                cols = cols[:, mask]
                pos = pos[mask] if pos is not None else None
                contigs = contigs[mask] if contigs is not None else None
            if contigs is None:
                yield cols, pos, None
                continue
            # Split the group at contig changes so no piece spans one.
            # NULL contigs (None entries from to_pylist) form their own
            # contig=None runs — boundaries against named contigs still
            # flush, and the label is a real None, not the str(None)
            # "None" pseudo-contig an unchecked str() would mint.
            edges = np.flatnonzero(contigs[1:] != contigs[:-1]) + 1
            for lo, hi in zip(
                np.concatenate(([0], edges)),
                np.concatenate((edges, [len(contigs)])),
            ):
                label = contigs[lo]
                yield (
                    cols[:, lo:hi],
                    pos[lo:hi] if pos is not None else None,
                    None if label is None else str(label),
                )

    def blocks(self, block_variants: int, start_variant: int = 0):
        seen = 0
        for block, meta in rechunk(
            self._pieces(), block_variants, start_variant
        ):
            seen = meta.stop
            yield block, meta
        if self._n_variants is None and start_variant == 0:
            self._n_variants = seen  # full pass counted the stream


def write_parquet(
    path: str,
    genotypes: np.ndarray,
    sample_ids: list[str] | None = None,
    contig: str | None = "chr22",
    positions: np.ndarray | None = None,
    start_pos: int = 16_050_000,
    row_group_rows: int = 8192,
) -> None:
    """Write an (N, V) dosage matrix as a wide parquet variant table
    (testing and interchange; the inverse of :class:`ParquetSource`)."""
    pq = _pyarrow()
    import pyarrow as pa

    n, v = genotypes.shape
    ids = sample_ids or [f"S{i:06d}" for i in range(n)]
    cols: dict = {}
    if contig is not None:
        cols["contig"] = pa.array([contig] * v)
        if positions is None:
            positions = np.arange(start_pos, start_pos + v, dtype=np.int64)
        cols["position"] = pa.array(np.asarray(positions, np.int64))
    for i, s in enumerate(ids):
        cols[s] = pa.array(np.asarray(genotypes[i], np.int8))
    pq.write_table(
        pa.table(cols), path, row_group_size=row_group_rows
    )

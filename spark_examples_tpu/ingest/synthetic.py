"""Seeded synthetic cohorts with planted population structure.

The reference validated its PCA against the known continental-ancestry
clusters of 1000 Genomes (SURVEY.md §4 "Golden values"). The synthetic
source plants the same kind of structure on demand — a Balding-Nichols
model: ancestral allele frequency per variant, population-specific
frequencies drawn Beta-distributed around it with drift F_ST, genotypes
Binomial(2, p_pop) — so recovery of the planted clusters is an assertable
property at any scale, not an eyeballed one.

Generation is chunk-deterministic: variants are produced on a fixed
internal 1024-wide grid, each chunk from its own ``SeedSequence([seed,
chunk])`` stream, so the data for variant ``i`` is identical regardless
of the caller's ``block_variants`` or resume point.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from spark_examples_tpu.ingest.source import BlockMeta

_CHUNK = 1024


@dataclass
class SyntheticSource:
    # Deterministic generation: the variant count is exact and free to
    # read (multi-host feeder precomputes step counts from it).
    exact_n_variants = True

    n_samples: int = 2504
    n_variants: int = 100_000
    n_populations: int = 5
    fst: float = 0.1  # drift between populations
    missing_rate: float = 0.01
    maf_low: float = 0.05
    seed: int = 0
    _pops: np.ndarray = field(init=False, repr=False)

    def __post_init__(self):
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, 0xC0]))
        self._pops = rng.integers(0, self.n_populations, self.n_samples)

    @property
    def populations(self) -> np.ndarray:
        """Planted population label per sample (for validation)."""
        return self._pops

    @property
    def sample_ids(self) -> list[str]:
        return [
            f"P{self._pops[i]}_S{i:06d}" for i in range(self.n_samples)
        ]

    def _chunk(self, c: int) -> np.ndarray:
        """Generate the int8 (n_samples, <=_CHUNK) chunk ``c``."""
        lo = c * _CHUNK
        width = min(_CHUNK, self.n_variants - lo)
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, 1, c]))
        p_anc = rng.uniform(self.maf_low, 1.0 - self.maf_low, width)
        if self.fst > 0:
            a = p_anc * (1.0 - self.fst) / self.fst
            b = (1.0 - p_anc) * (1.0 - self.fst) / self.fst
            # (n_pops, width) population-specific frequencies
            p_pop = rng.beta(np.maximum(a, 1e-3), np.maximum(b, 1e-3),
                             (self.n_populations, width))
        else:
            p_pop = np.broadcast_to(p_anc, (self.n_populations, width))
        p = p_pop[self._pops]  # (n_samples, width)
        # Binomial(2, p) drawn as two Bernoulli trials — ~4x faster than
        # rng.binomial for large blocks and identical in distribution.
        g = (
            (rng.random((self.n_samples, width)) < p).astype(np.int8)
            + (rng.random((self.n_samples, width)) < p).astype(np.int8)
        )
        if self.missing_rate > 0:
            miss = rng.random((self.n_samples, width)) < self.missing_rate
            g[miss] = -1
        return g

    def blocks(self, block_variants: int, start_variant: int = 0):
        v = self.n_variants
        first = -(-start_variant // block_variants)  # ceil, see ArraySource
        for idx in range(first, -(-v // block_variants)):
            lo = idx * block_variants
            hi = min(lo + block_variants, v)
            c0, c1 = lo // _CHUNK, (hi - 1) // _CHUNK
            chunks = [self._chunk(c) for c in range(c0, c1 + 1)]
            wide = np.concatenate(chunks, axis=1)
            block = np.ascontiguousarray(
                wide[:, lo - c0 * _CHUNK : hi - c0 * _CHUNK]
            )
            yield block, BlockMeta(idx, lo, hi, contig="synthetic")

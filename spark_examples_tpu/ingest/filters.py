"""Variant QC filtering as a stream transform (``--maf``,
``--max-missing``).

The reference filtered variants in its RDD map stage ("filter variants
with >=1 non-ref call", SURVEY.md §3.1); real pipelines additionally
drop rare variants and high-missingness sites before kinship/PCA. Here
that is a source wrapper: per incoming block the allele frequency and
missing rate are computed vectorized on the host (producer side — the
chip never sees dropped columns, so filtering also shrinks transport),
surviving columns are re-chunked into steady ``block_variants``-wide
blocks, and contig boundaries still flush (the wrapped stream keeps the
"blocks never span a contig" contract).

Ordinals index the FILTERED stream — deterministic for a fixed
source+thresholds, so resume cursors stay valid (the filter re-derives
the same kept set on every pass). ``n_variants`` requires counting the
kept set, i.e. a full pass over the inner source; it is computed lazily
and cached, and the streaming jobs never call it (they count from
``meta.stop`` precisely to avoid such scans).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from spark_examples_tpu.ingest.source import rechunk


def qc_mask(block: np.ndarray, maf: float, max_missing: float) -> np.ndarray:
    """Boolean keep-mask over the block's variant axis.

    MAF = min(p, 1-p) with p the alt-allele frequency over called
    genotypes (dosage mean / 2); variants with zero calls have
    missing rate 1.0 and undefined MAF -> dropped whenever either
    threshold is active.
    """
    valid = block >= 0
    n_called = valid.sum(axis=0)
    miss = 1.0 - n_called / block.shape[0]
    keep = miss <= max_missing
    if maf > 0.0:
        with np.errstate(invalid="ignore"):
            p = np.where(
                n_called > 0,
                np.where(valid, block, 0).sum(axis=0) / (2.0 * n_called),
                np.nan,
            )
        keep &= np.minimum(p, 1.0 - p) >= maf
    return keep


@dataclass
class FilteredSource:
    """QC-filtered view of any GenotypeSource."""

    inner: object
    maf: float = 0.0
    max_missing: float = 1.0
    _n_variants: int | None = field(default=None, repr=False)

    @property
    def sample_ids(self) -> list[str]:
        return self.inner.sample_ids

    @property
    def n_samples(self) -> int:
        return self.inner.n_samples

    @property
    def n_variants(self) -> int:
        """Kept-variant count — a full pass over the inner source
        (lazy; also cached as a side effect of any completed streaming
        pass, so jobs that already streamed don't pay a second one)."""
        if self._n_variants is None:
            count = 0
            for block, _ in self.inner.blocks(16384):
                count += int(qc_mask(block, self.maf, self.max_missing).sum())
            self._n_variants = count
        return self._n_variants

    def _filtered(self):
        for block, meta in self.inner.blocks(16384):
            keep = qc_mask(block, self.maf, self.max_missing)
            yield (
                np.ascontiguousarray(block[:, keep]),
                (np.asarray(meta.positions)[keep]
                 if meta.positions is not None else None),
                meta.contig,
            )

    def blocks(self, block_variants: int, start_variant: int = 0):
        emitted = 0
        for block, meta in rechunk(self._filtered(), block_variants,
                                   start_variant):
            emitted = meta.stop
            yield block, meta
        if start_variant == 0:
            # A completed full pass has counted the kept set — cache it
            # so a later .n_variants doesn't re-stream (VcfSource makes
            # the same promise).
            self._n_variants = emitted

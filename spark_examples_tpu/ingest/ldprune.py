"""LD pruning as a stream transform (``--ld-prune-r2``) — the
``--indep-pairwise`` step of PLINK-family workflows.

Nearby variants are correlated (linkage disequilibrium); PCA/kinship
over unpruned data overweights long LD blocks, so the standard pipeline
prunes until no within-window pair exceeds an r² threshold. The
TPU-native shape: window the stream (the shared ``rechunk`` machinery,
window-sized blocks, chromosome-flush), compute the squared correlation
on device — ONE (W, N) x (N, W) matmul of per-variant standardized
dosages (missing mean-imputed, the field's usual approximation to
pairwise-complete r²) at a FIXED padded shape of ``carry + window``
columns, so XLA compiles exactly once regardless of ragged windows —
and run the greedy keep-scan on the host (an O(W) loop over a W²
matrix already in hand). Kept columns re-chunk into steady blocks.

Window handling: non-overlapping windows with the last ``carry`` KEPT
variants carried into the next window's comparison set, so pairs
spanning a boundary within ``carry`` variants are still checked —
pairs further apart than a window are not (same spirit as PLINK's
sliding step; documented approximation). LD context resets at
chromosome boundaries (LD does not span them). Ordinals index the
pruned stream; the prune is deterministic for a fixed
source+parameters, so resume cursors stay valid.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_examples_tpu.ingest.source import rechunk


@partial(jax.jit, static_argnames=("w",))
def _window_r2(x: jnp.ndarray, w: int) -> jnp.ndarray:
    """(N, w) int8 dosages -> (w, w) squared correlation of variants.

    Missing calls are mean-imputed per variant (contributing zero after
    centering); zero-variance variants — including zero PAD columns the
    caller appends to keep this shape static — get r = 0 against
    everything, which the greedy scan treats as "no LD" (pad columns
    are sliced away before any decision).
    """
    valid = (x >= 0)
    v = valid.astype(jnp.float32)
    y = jnp.where(valid, x, 0).astype(jnp.float32)
    cnt = jnp.maximum(v.sum(axis=0), 1.0)
    mean = y.sum(axis=0) / cnt
    z = jnp.where(valid, y - mean[None, :], 0.0)
    cov = z.T @ z
    var = jnp.diagonal(cov)
    denom = jnp.sqrt(jnp.outer(var, var))
    r = jnp.where(denom > 1e-12, cov / denom, 0.0)
    return r * r


def _greedy_keep(r2: np.ndarray, base: int, thresh: float) -> np.ndarray:
    """Greedy scan: keep variant j iff its r² with every PREVIOUSLY
    KEPT variant (including the ``base`` carried-in columns, which are
    immutable) stays <= thresh. Returns the keep mask for columns
    base..W (the carried columns are not re-decided)."""
    w = r2.shape[0]
    kept = list(range(base))
    keep = np.zeros(w - base, bool)
    for j in range(base, w):
        if not kept or (r2[j, kept] <= thresh).all():
            keep[j - base] = True
            kept.append(j)
    return keep


@dataclass
class LdPruneSource:
    """LD-pruned view of any GenotypeSource."""

    inner: object
    r2: float = 0.2
    window: int = 256
    carry: int = 64
    _n_variants: int | None = field(default=None, repr=False)

    def __post_init__(self):
        if not 0.0 < self.r2 <= 1.0:
            raise ValueError(f"r2 threshold must be in (0, 1], got {self.r2}")
        if not 1 <= self.carry < self.window:
            # carry=0 would make the negative tail-slice below grab the
            # WHOLE history; negatives likewise — reject both loudly.
            raise ValueError(
                f"carry must be in [1, window), got carry={self.carry} "
                f"window={self.window}"
            )

    @property
    def sample_ids(self) -> list[str]:
        return self.inner.sample_ids

    @property
    def n_samples(self) -> int:
        return self.inner.n_samples

    @property
    def n_variants(self) -> int:
        """Kept count — a full pruning pass (lazy; also cached by any
        completed streaming pass, so jobs that already streamed don't
        prune the cohort a second time)."""
        if self._n_variants is None:
            self._n_variants = sum(
                b.shape[1] for b, _ in self.blocks(16384)
            )
        return self._n_variants

    def _pruned_windows(self):
        """Yield (kept_block, positions, contig) per window, carrying
        kept-variant context within each contig. Every device call pads
        to (N, carry + window) so XLA compiles the r² matmul once."""
        n = self.inner.n_samples
        wpad = self.carry + self.window
        ctx: np.ndarray | None = None  # (N, <=carry) kept tail
        ctx_contig: str | None = None

        def pieces():
            for block, meta in self.inner.blocks(self.window):
                yield (
                    block,
                    (np.asarray(meta.positions)
                     if meta.positions is not None else None),
                    meta.contig,
                )

        for cols, meta in rechunk(pieces(), self.window):
            if ctx_contig != meta.contig:
                ctx = None  # LD does not span chromosomes
            base = 0 if ctx is None else ctx.shape[1]
            w = cols.shape[1]
            x = np.full((n, wpad), -1, np.int8)  # pad = all-missing:
            if base:                             # zero variance, r = 0
                x[:, :base] = ctx
            x[:, base : base + w] = cols
            r2m = np.asarray(_window_r2(x, wpad))[: base + w, : base + w]
            keep = _greedy_keep(r2m, base, self.r2)[:w]
            kept = np.ascontiguousarray(cols[:, keep])
            all_kept = (
                kept if ctx is None
                else np.concatenate([ctx, kept], axis=1)
            )
            ctx = np.ascontiguousarray(all_kept[:, -self.carry:])
            ctx_contig = meta.contig
            kp = (
                meta.positions[keep]
                if meta.positions is not None else None
            )
            yield kept, kp, meta.contig

    def blocks(self, block_variants: int, start_variant: int = 0):
        """Re-chunk pruned windows into (N, <=block_variants) blocks,
        contig-flush, pruned-stream ordinals."""
        emitted = 0
        for block, meta in rechunk(self._pruned_windows(), block_variants,
                                   start_variant):
            emitted = meta.stop
            yield block, meta
        if start_variant == 0:
            self._n_variants = emitted  # completed pass counted the set
"""Parallel ingest engine: multi-worker parse/pack with ordered reassembly.

The reference hid ingest latency behind many concurrent executor tasks
(one Genomics-API page stream per RDD partition, SURVEY.md §3.5); the
rebuild's cold paths — VCF text parse, `ingest` compaction packing —
ran on one core while the chip idled. This module restores the
reference's task-level parallelism host-side without giving up the one
property Spark never had to promise: **bit-identical, deterministically
ordered output**. Work is sharded (by byte range for VCF text, by block
ordinal for random-access sources), executed by a bounded worker pool,
and reassembled in submission order, so the emitted stream — blocks,
metadata, positions, resume cursors — is indistinguishable from the
serial one.

Three layers:

- :func:`parallel_map_ordered` — the shared primitive: a bounded
  ThreadPoolExecutor whose results are yielded strictly in input order
  (the ordered reassembly buffer). Worker exceptions surface at the
  consumer on their turn, never out of order and never silently.
- :func:`parallel_blocks` — ``source.blocks(bv)`` parallelized where a
  capability allows it: plain (seekable, non-gzip) VCF files shard by
  byte range through the SAME record parser the serial path runs
  (``vcf.parse_record_lines``); sources claiming ``exact_n_variants``
  (synthetic, memmapped packed/array stores, single-contig dataset
  stores) shard by block ordinal via their own O(1) resume cursors.
  Everything else degrades to the serial stream — correctness never
  depends on the fast path being available.
- the compaction wiring lives in ``store/writer.py`` (``compact(...,
  workers=N)``): stage A is this module's parallel parse, stage B packs
  + hashes + writes each chunk in a second ordered pool, so parse,
  2-bit packing, sha256, and file IO all overlap.

Fault story: shard workers honor the retry contract. A worker crossing
the ``ingest.block_read`` site (or raising a real transient ``IOError``)
retries its shard from scratch under the wrapping
:class:`~spark_examples_tpu.ingest.resilient.RetryPolicy` — a shard
parse is idempotent, so the re-read is bit-identical — and an exhausted
budget surfaces as :class:`~spark_examples_tpu.ingest.resilient.
IngestExhaustedError` carrying the **in-order resume cursor** (the
variants already delivered downstream), stamped at the reassembly point
where that cursor is known. Fail-fast errors (``StoreCorruptError``,
``CorruptBlockError``) propagate unchanged with their own cursors.
"""

from __future__ import annotations

import os
import random
import time
import warnings
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from spark_examples_tpu.core import faults, telemetry

# Byte-range shards target this much raw VCF text each: small enough
# that inflight shards bound host RAM (a shard's dense columns are
# ~text/4 bytes), large enough that per-shard overhead (thread dispatch,
# file open/seek) is noise against the parse.
VCF_SHARD_BYTES = 32 << 20

MAX_WORKERS = 256  # sanity ceiling, mirrored by the config validation


def parallel_map_ordered(items, fn, workers: int, inflight: int | None = None,
                         name: str = "ingest-worker"):
    """Yield ``fn(item)`` for every item, in input order, computed by a
    bounded worker pool.

    The ordered reassembly buffer of the parallel ingest engine: up to
    ``inflight`` tasks run/wait at once (bounding memory for streams of
    large blocks), results are yielded strictly in submission order, and
    a worker exception re-raises at the consumer on that item's turn —
    after every in-order predecessor was delivered, so downstream resume
    cursors are exact. Items are pulled from ``items`` lazily in the
    consumer thread (keep item production cheap; put the work in ``fn``).
    ``workers <= 1`` degrades to a plain in-thread map.
    """
    workers = max(1, int(workers))
    if workers == 1:
        for item in items:
            yield fn(item)
        return
    inflight = max(workers + 2, int(inflight or 0))
    pending: deque = deque()
    ex = ThreadPoolExecutor(max_workers=workers, thread_name_prefix=name)
    try:
        it = iter(items)
        exhausted = False
        while True:
            while not exhausted and len(pending) < inflight:
                try:
                    item = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(ex.submit(fn, item))
            if not pending:
                return
            fut = pending.popleft()
            t0 = time.perf_counter()
            value = fut.result()  # re-raises the worker's exception
            telemetry.observe("ingest.reassembly_wait_s",
                              time.perf_counter() - t0)
            yield value
    finally:
        for fut in pending:
            fut.cancel()
        ex.shutdown(wait=False, cancel_futures=True)


# ---------------------------------------------------------------------------
# VCF byte-range sharding.


def vcf_byte_shards(path: str, target_bytes: int | None = None,
                    max_shards: int | None = None) -> list[tuple[int, int]]:
    """Split a plain (non-gzip) VCF into record-aligned byte ranges.

    The first range starts at the first data line (header skipped);
    every boundary is advanced to the next line start, so each record
    line belongs to exactly one shard and concatenating shard parses in
    range order reproduces the file's record order exactly.
    ``target_bytes`` defaults to the module's :data:`VCF_SHARD_BYTES`
    (read at call time, so tests and tuning can adjust it).
    """
    if target_bytes is None:
        target_bytes = VCF_SHARD_BYTES
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        data_start = 0
        for line in f:
            if not line.startswith(b"#"):
                break
            data_start += len(line)
        span = size - data_start
        if span <= 0:
            return []
        n = max(1, -(-span // max(1, int(target_bytes))))
        if max_shards:
            n = min(n, int(max_shards))
        if n == 1:
            return [(data_start, size)]
        step = -(-span // n)
        bounds = [data_start]
        for k in range(1, n):
            target = min(data_start + k * step, size)
            if target <= bounds[-1]:
                continue
            f.seek(target)
            f.readline()  # discard the partial line; next one starts clean
            b = f.tell()
            if bounds[-1] < b < size:
                bounds.append(b)
        bounds.append(size)
    return list(zip(bounds[:-1], bounds[1:]))


def _parse_vcf_shard(path, lo, hi, n_samples, in_range, policy, seed):
    """One shard's records, grouped into per-contig-run pieces.

    Runs in a pool worker. Crosses the ``ingest.block_read`` fault site
    once per attempt and retries the WHOLE shard under ``policy`` on
    transient IO errors — a shard parse has no side effects, so the
    retry is bit-identical to an unfailed read. Returns
    ``[(cols, positions, contig), ...]`` pieces ready for ``rechunk``.

    Deliberately NOT RetryingSource._stream: that loop's extra
    machinery — reopen factories (for object-held memmaps; a shard
    opens its path fresh every attempt), per-block budget resets (a
    shard is one idempotent unit with no partial progress), cursor
    tracking (stamped at the reassembly point instead, where the
    in-order cursor exists) — has no referent here. The two share the
    RetryPolicy (budget/backoff/jitter) and the retry telemetry names,
    which is the contract that must stay in sync.
    """
    from spark_examples_tpu.ingest.resilient import IngestExhaustedError

    rng = random.Random(seed)
    retries_left = policy.max_retries if policy is not None else 0
    retry_on = policy.retry_on if policy is not None else ()
    while True:
        try:
            faults.fire("ingest.block_read")
            return _parse_vcf_range(path, lo, hi, n_samples, in_range)
        except retry_on as e:
            if retries_left <= 0:
                telemetry.count("ingest.exhausted")
                # cursor -1: the reassembly layer stamps the in-order
                # variant cursor (unknowable inside one shard).
                raise IngestExhaustedError(
                    f"parallel ingest shard (bytes [{lo}, {hi}) of "
                    f"{path}) failed after {policy.max_retries} retries: "
                    f"{e!r}", -1,
                ) from e
            attempt = policy.max_retries - retries_left
            retries_left -= 1
            delay = policy.sleep_s(attempt, rng)
            telemetry.count("ingest.retries")
            telemetry.count("ingest.backoff_s", delay)
            warnings.warn(
                f"transient ingest error in parallel shard "
                f"[{lo}, {hi}) of {path} ({e!r}); retrying in "
                f"{delay * 1e3:.0f} ms ({retries_left} retries left)",
                RuntimeWarning, stacklevel=2,
            )
            time.sleep(delay)


def _parse_vcf_range(path, lo, hi, n_samples, in_range):
    """The record-aligned range [lo, hi) as per-contig-run pieces.

    The hot loop is the native batch parser (one GIL-released C call
    over the whole shard buffer — what lets shard worker THREADS scale
    on cores); the Python record parser is the byte-identical fallback
    and the handler for input the C parser punts on.
    """
    from spark_examples_tpu import native

    with open(path, "rb") as f:
        f.seek(lo)
        buf = f.read(hi - lo)

    parsed = native.vcf_parse_block(buf, n_samples)
    if parsed is not None:
        rows, positions, contigs, n_short = parsed
        if n_short:
            warnings.warn(
                f"{path}: {n_short} record(s) in bytes [{lo}, {hi}) have "
                f"fewer than {n_samples} sample columns — skipping; the "
                "file may be truncated or malformed",
                RuntimeWarning, stacklevel=3,
            )
        if in_range is not None:
            keep = np.fromiter(
                (in_range(c, int(p))
                 for c, p in zip(contigs, positions.tolist())),
                dtype=bool, count=len(contigs),
            )
            if not keep.all():
                rows = rows[keep]
                positions = positions[keep]
                contigs = [c for c, k in zip(contigs, keep.tolist()) if k]
        pieces = []
        a = 0
        for b in range(1, len(contigs) + 1):
            if b == len(contigs) or contigs[b] != contigs[a]:
                pieces.append((
                    np.ascontiguousarray(rows[a:b].T),
                    np.ascontiguousarray(positions[a:b]),
                    contigs[a],
                ))
                a = b
        return pieces

    return _parse_vcf_range_py(buf, path, n_samples, in_range)


def _parse_vcf_range_py(buf, path, n_samples, in_range):
    """Pure-Python shard parse through the SAME record parser the
    serial stream runs — the semantic reference the batch C path is
    pinned against."""
    import io

    from spark_examples_tpu.ingest.vcf import parse_record_lines

    pieces = []
    cols: list[np.ndarray] = []
    positions: list[int] = []
    contig: str | None = None

    def flush():
        if cols:
            pieces.append((
                np.stack(cols, axis=1),
                np.asarray(positions, np.int64),
                contig,
            ))
        cols.clear()
        positions.clear()

    rng_check = in_range if in_range is not None else (lambda c, p: True)
    for c, pos, col in parse_record_lines(
        io.BytesIO(buf), n_samples, rng_check, path
    ):
        if cols and c != contig:
            flush()
        contig = c
        cols.append(col)
        positions.append(pos)
    flush()
    return pieces


# ---------------------------------------------------------------------------
# Capability dispatch.


def _unwrap_retrying(source):
    """(inner, policy, seed) — see through a RetryingSource so the
    parallel path can honor the SAME retry contract inside workers."""
    from spark_examples_tpu.ingest.resilient import RetryingSource

    if isinstance(source, RetryingSource):
        return source.inner, source.policy, source.seed
    return source, None, 0


def _vcf_shardable(source):
    """The VcfSource (possibly retry-wrapped) iff byte-range sharding
    applies: a plain seekable file (gzip streams cannot seek)."""
    from spark_examples_tpu.ingest.vcf import VcfSource

    inner, policy, seed = _unwrap_retrying(source)
    if isinstance(inner, VcfSource) and not inner.path.endswith(".gz"):
        return inner, policy, seed
    return None


def parallel_blocks(source, block_variants: int, workers: int,
                    start_variant: int = 0) -> Iterator:
    """``source.blocks(block_variants)`` with the parse fanned out over
    ``workers`` threads — bit-identical stream, parallel production.

    Dispatch (first capability wins):

    - **VCF byte-range** — plain-file VcfSource (retry-wrapped or not):
      record-aligned byte shards through the shared record parser, then
      ``rechunk`` reassembles the in-order pieces into exactly the
      serial block grid (contig flushes included).
    - **block stripes** — sources claiming ``exact_n_variants`` (O(1)
      block-aligned resume, no mid-stream flushes, concurrency-safe
      reads): one pool task per block ordinal via ``blocks(bv, k*bv)``.
    - **serial fallback** — everything else (gzip VCF, chained/filtered
      streams, multi-contig stores): the source's own stream, unchanged.

    Resume (``start_variant > 0``) always takes the serial path: resume
    streams the tail of an interrupted job, where cursor semantics are
    source-specific and the win from parallelism is marginal.
    """
    workers = max(1, int(workers))
    if workers == 1 or start_variant > 0:
        yield from source.blocks(block_variants, start_variant)
        return

    vcf = _vcf_shardable(source)
    if vcf is not None:
        inner, policy, seed = vcf
        shards = vcf_byte_shards(inner.path)
        if len(shards) > 1:
            yield from _parallel_vcf_blocks(
                inner, shards, block_variants, workers, policy, seed
            )
            return
        # One shard = nothing to fan out; stream through the ORIGINAL
        # (possibly retry-wrapped) source, not the unwrapped inner.
        yield from source.blocks(block_variants, 0)
        return

    if getattr(source, "exact_n_variants", False):
        yield from _striped_blocks(source, block_variants, workers)
        return

    yield from source.blocks(block_variants, 0)


def _parallel_vcf_blocks(src, shards, block_variants, workers, policy, seed):
    from spark_examples_tpu.ingest.source import rechunk

    n = src.n_samples  # header read once, in the consumer thread
    # None = no region filter (the common case) — the shard parser then
    # skips the per-record Python range check entirely.
    in_range = src._in_range if src.references else None

    def parse(shard_k):
        k, (lo, hi) = shard_k
        telemetry.count("ingest.parallel_shards")
        return _parse_vcf_shard(
            src.path, lo, hi, n, in_range, policy, seed + k
        )

    delivered = 0
    try:
        def pieces():
            for shard_pieces in parallel_map_ordered(
                enumerate(shards), parse, workers, name="vcf-parse"
            ):
                yield from shard_pieces

        for block, meta in rechunk(pieces(), block_variants):
            yield block, meta
            delivered = meta.stop
        # A full parse counted every record — cache it like the serial
        # stream does, so a later .n_variants needs no re-parse.
        src._n_variants = delivered
    except BaseException as e:
        if getattr(e, "cursor", None) == -1:
            e.cursor = delivered
            e.args = (f"{e.args[0]} — {delivered} variants were already "
                      f"delivered in order; resume from "
                      f"start_variant={delivered} (or the last "
                      "--checkpoint-dir checkpoint)",) + e.args[2:]
        raise


def _striped_blocks(source, block_variants, workers):
    """One pool task per block ordinal over an exact-length source —
    the stripe shard mode (random-access resume makes ``blocks(bv,
    k*bv)`` O(1), and exactness guarantees the grid is plain ceil
    division with no mid-stream flushes)."""
    v = source.n_variants
    n_blocks = -(-v // block_variants)
    if n_blocks <= 1:
        yield from source.blocks(block_variants, 0)
        return

    import dataclasses

    def read(k):
        telemetry.count("ingest.parallel_shards")
        it = source.blocks(block_variants, k * block_variants)
        try:
            block, meta = next(iter(it))
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
        # Re-index over the OUTPUT grid: an exact source's k-th block IS
        # ordinal k, and a retry wrapper's per-call re-indexing (every
        # stripe call starts a fresh stream at index 0) must not leak
        # into the reassembled metadata.
        return block, dataclasses.replace(meta, index=k)

    yield from parallel_map_ordered(
        range(n_blocks), read, workers, name="block-stripe"
    )


__all__ = [
    "parallel_blocks",
    "parallel_map_ordered",
    "vcf_byte_shards",
    "VCF_SHARD_BYTES",
    "MAX_WORKERS",
]

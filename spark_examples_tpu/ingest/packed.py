"""Packed columnar genotype store — the BigQuery-export stand-in.

The Stanford fork added a BigQuery → RDD ingestion path for
1000-Genomes-style variant tables (SURVEY.md §2.1 "BigQuery ingestion
path"). Its spirit — bulk columnar export consumed by the compute tier,
bypassing the paged API — maps here to a directory holding a memmappable
genotype matrix plus a JSON sidecar of sample ids / positions. Reading is
zero-copy block slicing of the memmap.

Two on-disk layouts:

- ``bits=8`` (legacy): ``genotypes.npy``, (N, V) int8 dosages.
- ``bits=2`` (default): ``genotypes.2bit.npy``, (N, ceil(V/4)) uint8 —
  four dosages per byte (ingest/bitpack.py). Quarter the disk footprint
  and, crucially, quarter the host→device traffic: the streaming layer
  slices these bytes zero-copy (``packed_blocks``) and the gram update
  unpacks on device.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.source import ArraySource, BlockMeta

# Sidecar schema version, mirroring the saved-model treatment
# (pipelines/project.py SCHEMA_VERSION): bump when a field is added/
# renamed/re-semanticized; load_packed refuses files it cannot
# interpret with a friendly error instead of a raw KeyError. Version 2
# = the first versioned schema (version 1, retroactively, is the
# unversioned pre-versioning format).
PACKED_SCHEMA_VERSION = 2

_REQUIRED_META = ("n_samples", "n_variants", "bits")


class PackedFormatError(ValueError):
    """A packed-store sidecar that cannot be safely interpreted:
    missing/truncated meta.json, a pre-versioning store, a store from a
    newer build, a missing required field, or a missing genotype file —
    always with the offending cause named."""


def _write_sidecar(
    path: str,
    n_samples: int,
    n_variants: int,
    bits: int,
    sample_ids: list[str] | None,
    contig: str | None,
    positions: np.ndarray | None,
    contig_runs: list[tuple[str | None, int]] | None = None,
) -> None:
    """The store's meta.json + positions.npy, shared by every writer so
    the schema can't drift between save_packed and pack_source.

    ``contig_runs``: [(name, start_index), ...] for multi-chromosome
    cohorts — run i spans [start_i, start_{i+1}).
    """
    meta = {
        "schema_version": PACKED_SCHEMA_VERSION,
        "n_samples": int(n_samples),
        "n_variants": int(n_variants),
        "bits": bits,
        "sample_ids": sample_ids,
        "contig": contig,
    }
    if contig_runs is not None:
        meta["contig_runs"] = [[c, int(s)] for c, s in contig_runs]
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    if positions is not None:
        np.save(os.path.join(path, "positions.npy"),
                np.asarray(positions, np.int64))


def save_packed(
    path: str,
    genotypes: np.ndarray,
    sample_ids: list[str] | None = None,
    contig: str | None = None,
    positions: np.ndarray | None = None,
    bits: int = 2,
) -> None:
    if bits not in (2, 8):
        raise ValueError(f"bits must be 2 or 8, got {bits}")
    os.makedirs(path, exist_ok=True)
    if bits == 2:
        np.save(os.path.join(path, "genotypes.2bit.npy"),
                bitpack.pack_dosages(np.asarray(genotypes)))
    else:
        np.save(os.path.join(path, "genotypes.npy"),
                np.ascontiguousarray(genotypes, dtype=GENOTYPE_DTYPE))
    _write_sidecar(path, genotypes.shape[0], genotypes.shape[1], bits,
                   sample_ids, contig, positions)


@dataclass
class Packed2BitSource:
    """2-bit columnar store as a GenotypeSource.

    ``blocks()`` unpacks host-side (protocol compatibility, CPU oracle
    path); ``packed_blocks()`` yields zero-copy byte slices for the
    packed streaming path (ingest/prefetch.stream_to_device(pack=True)).
    """

    packed: np.ndarray  # (N, ceil(V/4)) uint8, possibly memmapped
    v: int  # true variant count (last byte may hold pad codes)
    ids: list[str] | None = None
    contig: str | None = None
    positions: np.ndarray | None = None
    # Multi-chromosome stores: [(name, start_index), ...] — run i spans
    # [start_i, start_{i+1}). None = single-contig store (``contig``).
    contig_runs: list | None = None

    @property
    def n_samples(self) -> int:
        return int(self.packed.shape[0])

    @property
    def n_variants(self) -> int:
        return self.v

    @property
    def sample_ids(self) -> list[str]:
        if self.ids is not None:
            return self.ids
        return [f"S{i:06d}" for i in range(self.n_samples)]

    @property
    def exact_n_variants(self) -> bool:
        """Single-run stores stream exactly ceil(v/bv) blocks on both
        transports; multi-contig stores' DENSE blocks flush at each
        chromosome run (packed_blocks would be exact, but the claim
        must hold for whichever transport the consumer picks — see the
        GenotypeSource contract), so they conservatively decline."""
        return self.contig_runs is None or len(self.contig_runs) <= 1

    def _contig_of(self, lo: int, hi: int) -> str | None:
        """Contig of the variant range [lo, hi) — None when the range
        spans a run boundary (multi-contig stores pack continuously, so
        a byte-aligned packed block can straddle chromosomes)."""
        if self.contig_runs is None:
            return self.contig
        name = None
        for c, s in self.contig_runs:
            if s <= lo:
                name = c
            elif s < hi:
                return None  # a later run starts inside the range
        return name

    def _bounds(self) -> list[int]:
        """Segment boundaries dense blocks must not cross."""
        if not self.contig_runs:
            return [0, self.v]
        starts = [int(s) for _, s in self.contig_runs]
        return starts + [self.v]

    def packed_blocks(self, block_variants: int, start_variant: int = 0):
        """Yield ((N, <=block_variants/4) uint8, meta) zero-copy byte
        slices. Requires ``block_variants`` divisible by 4 so blocks fall
        on byte boundaries (``blocks()`` has no such restriction). The
        fixed byte grid can straddle chromosome runs; such blocks carry
        ``contig=None`` (positions stay exact)."""
        if block_variants % bitpack.VARIANTS_PER_BYTE:
            raise ValueError(
                f"packed_blocks needs block_variants divisible by "
                f"{bitpack.VARIANTS_PER_BYTE}, got {block_variants}"
            )
        bw = block_variants // bitpack.VARIANTS_PER_BYTE
        total_w = self.packed.shape[1]
        first = -(-start_variant // block_variants)
        for idx in range(first, -(-self.v // block_variants)):
            lo_b, hi_b = idx * bw, min((idx + 1) * bw, total_w)
            block = np.ascontiguousarray(self.packed[:, lo_b:hi_b])
            lo, hi = idx * block_variants, min(
                (idx + 1) * block_variants, self.v
            )
            pos = None
            if self.positions is not None:
                pos = self.positions[lo:hi]
            yield block, BlockMeta(idx, lo, hi, self._contig_of(lo, hi),
                                   pos)

    def blocks(self, block_variants: int, start_variant: int = 0):
        """Dense int8 blocks: unpack the covering byte range and slice
        off the sub-byte offset. Blocks never span a chromosome run
        (VCF/PLINK parity), so ``meta.contig`` is exact; resume skips
        any block starting before the cursor (ceil-align for mid-block
        cursors, exact for self-produced stops — both geometries only
        ever see cursors they made)."""
        vpb = bitpack.VARIANTS_PER_BYTE
        bounds = self._bounds()
        idx = 0
        for s in range(len(bounds) - 1):
            for lo in range(bounds[s], bounds[s + 1], block_variants):
                hi = min(lo + block_variants, bounds[s + 1])
                if lo < start_variant:
                    idx += 1
                    continue
                dense = bitpack.unpack_dosages_np(
                    self.packed[:, lo // vpb : -(-hi // vpb)]
                )
                block = dense[:, lo % vpb : lo % vpb + (hi - lo)]
                pos = None
                if self.positions is not None:
                    pos = self.positions[lo:hi]
                yield block, BlockMeta(idx, lo, hi,
                                       self._contig_of(lo, hi), pos)
                idx += 1


def pack_source(
    path: str,
    source,
    block_variants: int = 16384,
) -> int:
    """Stream any GenotypeSource into a 2-bit store in one pass — the
    ETL tier (the reference's BigQuery-export job shape): parse once,
    then every later job reads zero-copy packed bytes.

    The (N, ceil(V/4)) uint8 matrix is preallocated as a memmapped .npy
    (variant count comes from the source) and filled block-by-block at
    byte offsets, so the cohort never materializes dense in host RAM.
    Returns the number of variants written.
    """
    vpb = bitpack.VARIANTS_PER_BYTE
    n, v = source.n_samples, source.n_variants
    os.makedirs(path, exist_ok=True)
    out = np.lib.format.open_memmap(
        os.path.join(path, "genotypes.2bit.npy"), mode="w+",
        dtype=np.uint8, shape=(n, bitpack.packed_width(v)),
    )
    positions = np.full(v, -1, np.int64)
    runs: list[tuple[str | None, int]] = []  # (contig, start) per run
    written = 0  # variants consumed from the stream
    flushed = 0  # variants whose bytes have landed (always % 4 == 0)
    carry = np.empty((n, 0), np.int8)  # sub-byte tail awaiting alignment

    def flush(cols: np.ndarray, final: bool = False) -> np.ndarray:
        """Write the byte-aligned prefix of ``cols``; return the rest.
        Contig-flush blocks make arbitrary widths — a sub-byte tail must
        wait for the next block (packing it early would misalign every
        later variant by the pad codes)."""
        nonlocal flushed
        aligned = cols.shape[1] if final else cols.shape[1] // vpb * vpb
        if aligned:
            pb = bitpack.pack_dosages(np.ascontiguousarray(
                cols[:, :aligned]
            ))
            out[:, flushed // vpb : flushed // vpb + pb.shape[1]] = pb
            flushed += aligned
        return cols[:, aligned:]

    for block, meta in source.blocks(block_variants):
        if meta.start != written:
            raise ValueError(
                f"non-contiguous block stream: expected start {written}, "
                f"got {meta.start}"
            )
        if meta.positions is not None:
            positions[meta.start : meta.stop] = meta.positions
        if not runs or runs[-1][0] != meta.contig:
            runs.append((meta.contig, meta.start))
        written = meta.stop
        carry = flush(
            np.concatenate([carry, block], axis=1) if carry.size else block
        )
    flush(carry, final=True)
    if written != v:
        raise ValueError(
            f"source stream ended at {written} of {v} declared variants"
        )
    out.flush()
    single = runs[0][0] if len(runs) == 1 else None
    _write_sidecar(
        path, n, v, 2, source.sample_ids,
        contig=single,
        positions=positions if (positions >= 0).all() else None,
        contig_runs=runs if len(runs) > 1 else None,
    )
    return written


def _load_meta(path: str) -> dict:
    """The sidecar, validated with load_model()-grade friendliness —
    every way a long-lived job can trip over a bad store directory gets
    a :class:`PackedFormatError` naming the cause, never a raw
    ``KeyError``/``JSONDecodeError``/``FileNotFoundError``. The ladder
    itself is shared with the dataset-store manifest
    (core/sidecar.py)."""
    from spark_examples_tpu.core.sidecar import load_versioned_sidecar

    meta_path = os.path.join(path, "meta.json")
    return load_versioned_sidecar(
        meta_path,
        current_version=PACKED_SCHEMA_VERSION,
        required=_REQUIRED_META,
        error_cls=PackedFormatError,
        noun="packed-store sidecar",
        missing_msg=(
            f"{path!r} is not a packed store: no meta.json (create one "
            "with the `pack` command or save_packed)"
        ),
        repair="re-pack the store",
    )


def load_packed(path: str, mmap: bool = True):
    meta = _load_meta(path)
    positions = None
    pos_path = os.path.join(path, "positions.npy")
    if os.path.exists(pos_path):
        positions = np.load(pos_path)
    mode = "r" if mmap else None
    bits = meta["bits"]
    fname = "genotypes.2bit.npy" if bits == 2 else "genotypes.npy"
    try:
        g = np.load(os.path.join(path, fname), mmap_mode=mode)
    except FileNotFoundError:
        raise PackedFormatError(
            f"packed store {path!r}: sidecar says bits={bits} but "
            f"{fname} is missing — interrupted pack? re-pack the store"
        ) from None
    except ValueError as e:
        raise PackedFormatError(
            f"packed store {path!r}: {fname} is not a readable .npy "
            f"({e}) — truncated or corrupt? re-pack the store"
        ) from None
    if bits == 2:
        runs = meta.get("contig_runs")
        return Packed2BitSource(
            packed=g,
            v=meta["n_variants"],
            ids=meta.get("sample_ids"),
            contig=meta.get("contig"),
            positions=positions,
            contig_runs=[(c, int(s)) for c, s in runs] if runs else None,
        )
    return ArraySource(
        genotypes=g,
        ids=meta.get("sample_ids"),
        contig=meta.get("contig"),
        positions=positions,
    )

"""Packed columnar genotype store — the BigQuery-export stand-in.

The Stanford fork added a BigQuery → RDD ingestion path for
1000-Genomes-style variant tables (SURVEY.md §2.1 "BigQuery ingestion
path"). Its spirit — bulk columnar export consumed by the compute tier,
bypassing the paged API — maps here to a directory holding a memmappable
``genotypes.npy`` (N, V) int8 matrix plus a JSON sidecar of sample ids /
positions. Reading is zero-copy block slicing of the memmap.
"""

from __future__ import annotations

import json
import os

import numpy as np

from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE
from spark_examples_tpu.ingest.source import ArraySource


def save_packed(
    path: str,
    genotypes: np.ndarray,
    sample_ids: list[str] | None = None,
    contig: str | None = None,
    positions: np.ndarray | None = None,
) -> None:
    os.makedirs(path, exist_ok=True)
    np.save(os.path.join(path, "genotypes.npy"),
            np.ascontiguousarray(genotypes, dtype=GENOTYPE_DTYPE))
    meta = {
        "n_samples": int(genotypes.shape[0]),
        "n_variants": int(genotypes.shape[1]),
        "sample_ids": sample_ids,
        "contig": contig,
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    if positions is not None:
        np.save(os.path.join(path, "positions.npy"),
                np.asarray(positions, np.int64))


def load_packed(path: str, mmap: bool = True) -> ArraySource:
    g = np.load(os.path.join(path, "genotypes.npy"),
                mmap_mode="r" if mmap else None)
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    positions = None
    pos_path = os.path.join(path, "positions.npy")
    if os.path.exists(pos_path):
        positions = np.load(pos_path)
    return ArraySource(
        genotypes=g,
        ids=meta.get("sample_ids"),
        contig=meta.get("contig"),
        positions=positions,
    )

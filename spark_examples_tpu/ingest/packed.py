"""Packed columnar genotype store — the BigQuery-export stand-in.

The Stanford fork added a BigQuery → RDD ingestion path for
1000-Genomes-style variant tables (SURVEY.md §2.1 "BigQuery ingestion
path"). Its spirit — bulk columnar export consumed by the compute tier,
bypassing the paged API — maps here to a directory holding a memmappable
genotype matrix plus a JSON sidecar of sample ids / positions. Reading is
zero-copy block slicing of the memmap.

Two on-disk layouts:

- ``bits=8`` (legacy): ``genotypes.npy``, (N, V) int8 dosages.
- ``bits=2`` (default): ``genotypes.2bit.npy``, (N, ceil(V/4)) uint8 —
  four dosages per byte (ingest/bitpack.py). Quarter the disk footprint
  and, crucially, quarter the host→device traffic: the streaming layer
  slices these bytes zero-copy (``packed_blocks``) and the gram update
  unpacks on device.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.source import ArraySource, BlockMeta


def save_packed(
    path: str,
    genotypes: np.ndarray,
    sample_ids: list[str] | None = None,
    contig: str | None = None,
    positions: np.ndarray | None = None,
    bits: int = 2,
) -> None:
    if bits not in (2, 8):
        raise ValueError(f"bits must be 2 or 8, got {bits}")
    os.makedirs(path, exist_ok=True)
    if bits == 2:
        np.save(os.path.join(path, "genotypes.2bit.npy"),
                bitpack.pack_dosages(np.asarray(genotypes)))
    else:
        np.save(os.path.join(path, "genotypes.npy"),
                np.ascontiguousarray(genotypes, dtype=GENOTYPE_DTYPE))
    meta = {
        "n_samples": int(genotypes.shape[0]),
        "n_variants": int(genotypes.shape[1]),
        "bits": bits,
        "sample_ids": sample_ids,
        "contig": contig,
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    if positions is not None:
        np.save(os.path.join(path, "positions.npy"),
                np.asarray(positions, np.int64))


@dataclass
class Packed2BitSource:
    """2-bit columnar store as a GenotypeSource.

    ``blocks()`` unpacks host-side (protocol compatibility, CPU oracle
    path); ``packed_blocks()`` yields zero-copy byte slices for the
    packed streaming path (ingest/prefetch.stream_to_device(pack=True)).
    """

    packed: np.ndarray  # (N, ceil(V/4)) uint8, possibly memmapped
    v: int  # true variant count (last byte may hold pad codes)
    ids: list[str] | None = None
    contig: str | None = None
    positions: np.ndarray | None = None

    @property
    def n_samples(self) -> int:
        return int(self.packed.shape[0])

    @property
    def n_variants(self) -> int:
        return self.v

    @property
    def sample_ids(self) -> list[str]:
        if self.ids is not None:
            return self.ids
        return [f"S{i:06d}" for i in range(self.n_samples)]

    def packed_blocks(self, block_variants: int, start_variant: int = 0):
        """Yield ((N, <=block_variants/4) uint8, meta) zero-copy byte
        slices. Requires ``block_variants`` divisible by 4 so blocks fall
        on byte boundaries (``blocks()`` has no such restriction)."""
        if block_variants % bitpack.VARIANTS_PER_BYTE:
            raise ValueError(
                f"packed_blocks needs block_variants divisible by "
                f"{bitpack.VARIANTS_PER_BYTE}, got {block_variants}"
            )
        bw = block_variants // bitpack.VARIANTS_PER_BYTE
        total_w = self.packed.shape[1]
        first = -(-start_variant // block_variants)
        for idx in range(first, -(-self.v // block_variants)):
            lo_b, hi_b = idx * bw, min((idx + 1) * bw, total_w)
            block = np.ascontiguousarray(self.packed[:, lo_b:hi_b])
            lo, hi = idx * block_variants, min(
                (idx + 1) * block_variants, self.v
            )
            pos = None
            if self.positions is not None:
                pos = self.positions[lo:hi]
            yield block, BlockMeta(idx, lo, hi, self.contig, pos)

    def blocks(self, block_variants: int, start_variant: int = 0):
        """Dense int8 blocks of any width: unpack the covering byte range
        and slice off the sub-byte offset."""
        vpb = bitpack.VARIANTS_PER_BYTE
        first = -(-start_variant // block_variants)
        for idx in range(first, -(-self.v // block_variants)):
            lo = idx * block_variants
            hi = min(lo + block_variants, self.v)
            dense = bitpack.unpack_dosages_np(
                self.packed[:, lo // vpb : -(-hi // vpb)]
            )
            block = dense[:, lo % vpb : lo % vpb + (hi - lo)]
            pos = None
            if self.positions is not None:
                pos = self.positions[lo:hi]
            yield block, BlockMeta(idx, lo, hi, self.contig, pos)


def load_packed(path: str, mmap: bool = True):
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    positions = None
    pos_path = os.path.join(path, "positions.npy")
    if os.path.exists(pos_path):
        positions = np.load(pos_path)
    mode = "r" if mmap else None
    if meta.get("bits", 8) == 2:
        p = np.load(os.path.join(path, "genotypes.2bit.npy"), mmap_mode=mode)
        return Packed2BitSource(
            packed=p,
            v=meta["n_variants"],
            ids=meta.get("sample_ids"),
            contig=meta.get("contig"),
            positions=positions,
        )
    g = np.load(os.path.join(path, "genotypes.npy"), mmap_mode=mode)
    return ArraySource(
        genotypes=g,
        ids=meta.get("sample_ids"),
        contig=meta.get("contig"),
        positions=positions,
    )

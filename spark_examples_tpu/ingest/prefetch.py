"""Double-buffered host→device feeding.

The reference overlapped nothing: executors paged the Genomics API inside
``compute`` and Spark hid latency only via many concurrent tasks
(SURVEY.md §3.5). On TPU the equivalent overlap is explicit: a background
thread produces host blocks while the chip crunches the previous one, and
``jax.device_put`` of block k+1 overlaps the accumulation FMA of block k
(dispatch is async). Ragged final blocks are padded to the full block
width with MISSING (-1), which is semantically free — a missing call
contributes zero to every gram piece — and keeps a single compiled shape
for the whole stream (SURVEY.md §7 step 2 "double-buffered feed").
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

import jax
import numpy as np

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE, MISSING
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.source import BlockMeta, GenotypeSource

_END = object()

# A byte of four missing codes (0b11_11_11_11) — the packed twin of
# MISSING, shared with the multi-host feeder's padding slabs.
PACKED_MISSING = 0xFF


def pad_block(block: np.ndarray, block_variants: int) -> np.ndarray:
    """Right-pad a ragged block to ``block_variants`` with MISSING."""
    n, v = block.shape
    if v == block_variants:
        return block
    out = np.full((n, block_variants), MISSING, dtype=GENOTYPE_DTYPE)
    out[:, :v] = block
    return out


def pad_packed(packed: np.ndarray, width_bytes: int) -> np.ndarray:
    """Right-pad a ragged 2-bit packed block to ``width_bytes`` columns."""
    n, w = packed.shape
    if w == width_bytes:
        return packed
    out = np.full((n, width_bytes), PACKED_MISSING, dtype=np.uint8)
    out[:, :w] = packed
    return out


def padded_width(block_variants: int, pad_multiple: int = 1,
                 pack: bool = False) -> int:
    """The (host-side) column width every streamed block is padded to —
    bytes when ``pack``, variants otherwise. Exposed so the multi-host
    feeder (parallel/multihost.py) can agree on a global block shape
    across processes without consulting any data."""
    grid = pad_multiple * (bitpack.VARIANTS_PER_BYTE if pack else 1)
    width = -(-block_variants // grid) * grid
    return width // bitpack.VARIANTS_PER_BYTE if pack else width


def stream_host_blocks(
    source: GenotypeSource,
    block_variants: int,
    start_variant: int = 0,
    prefetch: int = 2,
    pad_multiple: int = 1,
    pack: bool = False,
    stats: dict | None = None,
) -> Iterator[tuple[np.ndarray, BlockMeta]]:
    """Yield shape-stable padded HOST blocks from a producer thread.

    The host half of :func:`stream_to_device` — same producer thread,
    bounded queue, padding, packing, and stats contract, but the blocks
    stay host-resident. The multi-host feeder consumes this directly
    (each process assembles its slab into a global array itself).
    """
    yield from _produce_host_blocks(
        source, block_variants, start_variant, prefetch, pad_multiple,
        pack, stats,
    )


def stream_to_device(
    source: GenotypeSource,
    block_variants: int,
    start_variant: int = 0,
    device=None,
    sharding=None,
    prefetch: int = 2,
    pad_multiple: int = 1,
    pack: bool = False,
    stats: dict | None = None,
) -> Iterator[tuple[jax.Array, BlockMeta]]:
    """Yield device-resident, shape-stable (N, padded_width) blocks.

    A daemon thread runs the (possibly slow, pure-Python/IO) source
    iterator and fills a bounded queue; the consumer side transfers to
    ``device`` (or places with ``sharding`` for the multi-chip path) and
    yields. Errors in the producer propagate to the consumer; abandoning
    the generator early (caller raises / breaks) stops the producer
    instead of leaving it blocked on the full queue with the source open.

    ``pad_multiple``: additionally round the padded width up to this
    multiple — variant-sharded placement needs the variant axis divisible
    by the mesh size.

    ``pack``: ship 2-bit packed uint8 blocks (N, width/4) instead of
    dense int8 — 4x less host→device traffic, unpacked on device inside
    the gram update (ops/gram.update_packed). Packing happens in the
    producer thread, overlapping the chip's FMA on the previous block. A
    source exposing ``packed_blocks`` (the 2-bit columnar store) is
    sliced zero-copy instead of being unpacked and re-packed.

    ``stats``: optional dict the producer thread updates in place —
    currently ``max_value`` (largest entry seen, dense transport only;
    the packed codec's domain is bounded at 2 by construction). Feeds
    the runner's int32-accumulator exactness guard for arbitrary int8
    tables; computed off the critical path.
    """
    for host_block, meta in _produce_host_blocks(
        source, block_variants, start_variant, prefetch, pad_multiple,
        pack, stats,
    ):
        # Chaos site: a "delay" here is a stalled host->device link (the
        # prefetch queue must absorb it); an "io_error" is a failed
        # transfer (not retryable — the stream's cursor semantics make
        # the job resumable from its checkpoint instead).
        faults.fire("device.put")
        if sharding is not None:
            dev_block = jax.device_put(host_block, sharding)
        elif device is not None:
            dev_block = jax.device_put(host_block, device)
        else:
            dev_block = jax.device_put(host_block)
        yield dev_block, meta


def _produce_host_blocks(
    source, block_variants, start_variant, prefetch, pad_multiple, pack,
    stats,
):
    q: queue.Queue = queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()
    grid = pad_multiple * (bitpack.VARIANTS_PER_BYTE if pack else 1)
    width = -(-block_variants // grid) * grid

    def _put(item, measure: bool = True) -> bool:
        # Producer-side backpressure metric: time this block waited for
        # queue space. Consistently large put-waits mean the CONSUMER
        # (device transfer/update) is the bottleneck and deeper prefetch
        # buys nothing; ~zero means ingest is the bottleneck (see the
        # get-wait twin below). Sentinel puts (_END, exceptions) are
        # NOT measured: the terminal _END put blocks until the consumer
        # drains the whole queue, and that one non-block sample would
        # dominate a short stream's p95/max and fake a consumer
        # bottleneck.
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                if measure:
                    telemetry.observe("prefetch.put_wait_s",
                                      time.perf_counter() - t0)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            if (
                pack
                and hasattr(source, "packed_blocks")
                and block_variants % bitpack.VARIANTS_PER_BYTE == 0
            ):
                w_bytes = width // bitpack.VARIANTS_PER_BYTE
                for pblock, meta in source.packed_blocks(
                    block_variants, start_variant
                ):
                    if not _put((pad_packed(pblock, w_bytes), meta)):
                        return
            elif pack:
                for block, meta in source.blocks(block_variants, start_variant):
                    host = bitpack.pack_dosages(pad_block(block, width))
                    if not _put((host, meta)):
                        return
            else:
                for block, meta in source.blocks(block_variants, start_variant):
                    if stats is not None and block.size:
                        stats["max_value"] = max(
                            stats.get("max_value", 0), int(block.max())
                        )
                    if not _put((pad_block(block, width), meta)):
                        return
            _put(_END, measure=False)
        except BaseException as e:  # propagate into consumer
            _put(e, measure=False)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            # Depth sampled before each get: max == configured depth
            # means the producer runs ahead (healthy); persistent 0
            # means the chip is starved by ingest. The get-wait is the
            # stall the consumer actually paid — its sum over the gram
            # phase is the bench digest's "prefetch stall fraction".
            telemetry.gauge_set("prefetch.queue_depth", q.qsize())
            t0 = time.perf_counter()
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            # Observed only for real blocks (the sentinel's wait is not
            # a per-block stall, and its sum feeds the digest's
            # prefetch_stall_frac).
            telemetry.observe("prefetch.get_wait_s",
                              time.perf_counter() - t0)
            yield item
    finally:
        stop.set()

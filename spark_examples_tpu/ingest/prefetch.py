"""K-deep pipelined host→device feeding on a donated staging ring.

The reference overlapped nothing: executors paged the Genomics API inside
``compute`` and Spark hid latency only via many concurrent tasks
(SURVEY.md §3.5). On TPU the equivalent overlap is explicit and now runs
three stages deep: a background producer thread parses/packs host blocks
into a **rotating ring of reusable host staging buffers** (the pinned-
slab analogue — each slab is written once per rotation and handed to
``jax.device_put``, then recycled only after its transfer completed, so
the allocator never churns a fresh 10–40 MB block per step); slab
recycling lags :data:`TRANSFER_DEPTH` blocks behind the yield point, so
transfers always have a full pipeline period to drain before their slab
rotates. Net: block k accumulates on the chip while k+1's transfer
drains and k+2 stages into a recycled slab — at exactly the block
cadence (cursors, checkpoints, error positions) a depth-1 feed had
(SURVEY.md §7 step 2, deepened).

Ragged final blocks are padded to the full block width with MISSING
(-1), which is semantically free — a missing call contributes zero to
every gram piece — and keeps a single compiled shape for the whole
stream. Zero-copy packed sources (the 2-bit stores) bypass staging
entirely: their blocks are read-only views of an mmap, already stable
host memory with nothing to recycle.

Per-stage telemetry: ``prefetch.stage_wait_s`` (producer waits for a
free slab — the transfer/compute side is the bottleneck),
``prefetch.put_wait_s`` / ``prefetch.get_wait_s`` (queue backpressure /
consumer starvation, as before), ``prefetch.transfer_wait_s`` (residual
wait for a transfer at retire time — ~0 when the pipeline is deep
enough) and the ``prefetch.queue_depth`` / ``prefetch.transfers_in_
flight`` gauges.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Iterator

import jax
import numpy as np

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE, MISSING
from spark_examples_tpu.ingest import bitpack
from spark_examples_tpu.ingest.source import BlockMeta, GenotypeSource

_END = object()

# A byte of four missing codes (0b11_11_11_11) — the packed twin of
# MISSING, shared with the multi-host feeder's padding slabs.
PACKED_MISSING = 0xFF

# How many blocks a staged slab's recycling lags behind its yield: the
# slab of block k rotates back when block k+TRANSFER_DEPTH is yielded,
# by which time k's transfer has had a full pipeline period to complete
# (the residual wait is prefetch.transfer_wait_s). 2 keeps at most 3
# slabs transfer-bound beyond the queue.
TRANSFER_DEPTH = 2


def _can_stage(device, sharding) -> bool:
    """Whether the reusable staging ring is SAFE for this placement.

    On accelerator targets ``jax.device_put`` of a NumPy array is a real
    host->device copy (immutable only until the transfer completes —
    which the retire-time ready wait guarantees before a slab rotates).
    On the CPU backend it is **zero-copy**: the returned array aliases
    the host buffer for its whole life, so recycling the slab would
    rewrite blocks the consumer still holds. There is also no transfer
    to overlap there — staging buys nothing — so CPU placements run
    unstaged (fresh buffer per block, the pre-ring behavior).
    """
    try:
        if sharding is not None:
            return all(d.platform != "cpu" for d in sharding.device_set)
        if device is not None:
            return device.platform != "cpu"
        return jax.default_backend() != "cpu"
    except Exception:
        return False


class _Slot:
    """One staging slab plus its way home."""

    __slots__ = ("buf", "_ring")

    def __init__(self, buf, ring):
        self.buf = buf
        self._ring = ring

    def release(self):
        self._ring.release(self)


class _StagingRing:
    """Rotating pool of reusable host staging buffers.

    Slabs are allocated lazily up to ``n_slots`` (a short stream never
    pays for the full ring) and recycled through a queue: the producer
    blocks in :meth:`acquire` when every slab is in flight — which is
    exactly the backpressure the bounded block queue used to provide,
    now extended over the transfer stage too.
    """

    def __init__(self, n_slots: int, shape, dtype, fill):
        self._shape, self._dtype, self._fill = shape, dtype, fill
        self._free: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._allocated = 0
        self._n_slots = max(1, int(n_slots))

    def acquire(self, stop: threading.Event) -> "_Slot | None":
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                slot = self._free.get_nowait()
            except queue.Empty:
                slot = None
                with self._lock:
                    if self._allocated < self._n_slots:
                        self._allocated += 1
                        slot = _Slot(
                            np.full(self._shape, self._fill, self._dtype),
                            self,
                        )
                if slot is None:
                    try:
                        slot = self._free.get(timeout=0.1)
                    except queue.Empty:
                        continue
            # Re-check AFTER winning a slot: on abandonment the consumer
            # stops the producer and then releases in-flight slabs — a
            # get racing that release could otherwise hand the producer
            # a slab whose transfer is still live.
            if stop.is_set():
                return None
            telemetry.observe("prefetch.stage_wait_s",
                              time.perf_counter() - t0)
            return slot
        return None

    def release(self, slot: "_Slot") -> None:
        self._free.put(slot)


def pad_block(block: np.ndarray, block_variants: int) -> np.ndarray:
    """Right-pad a ragged block to ``block_variants`` with MISSING."""
    n, v = block.shape
    if v == block_variants:
        return block
    out = np.full((n, block_variants), MISSING, dtype=GENOTYPE_DTYPE)
    out[:, :v] = block
    return out


def pad_packed(packed: np.ndarray, width_bytes: int) -> np.ndarray:
    """Right-pad a ragged 2-bit packed block to ``width_bytes`` columns."""
    n, w = packed.shape
    if w == width_bytes:
        return packed
    out = np.full((n, width_bytes), PACKED_MISSING, dtype=np.uint8)
    out[:, :w] = packed
    return out


def padded_width(block_variants: int, pad_multiple: int = 1,
                 pack: bool = False) -> int:
    """The (host-side) column width every streamed block is padded to —
    bytes when ``pack``, variants otherwise. Exposed so the multi-host
    feeder (parallel/multihost.py) can agree on a global block shape
    across processes without consulting any data."""
    grid = pad_multiple * (bitpack.VARIANTS_PER_BYTE if pack else 1)
    width = -(-block_variants // grid) * grid
    return width // bitpack.VARIANTS_PER_BYTE if pack else width


def stream_host_blocks(
    source: GenotypeSource,
    block_variants: int,
    start_variant: int = 0,
    prefetch: int = 2,
    pad_multiple: int = 1,
    pack: bool = False,
    stats: dict | None = None,
) -> Iterator[tuple[np.ndarray, BlockMeta]]:
    """Yield shape-stable padded HOST blocks from a producer thread.

    The host half of :func:`stream_to_device` — same producer thread,
    bounded queue, padding, packing, and stats contract, but the blocks
    stay host-resident (and unstaged: the consumer owns each block
    indefinitely, so the reusable ring cannot apply). The multi-host
    feeder consumes this directly (each process assembles its slab into
    a global array itself); dense store-backed partitions take the
    decode-straight-into-buffer drive (``direct=True``) so each
    process's slab is decoded in one native call from exactly its
    window's variants — the shard-aware feed.
    """
    for host, _slot, meta in _produce_host_blocks(
        source, block_variants, start_variant, prefetch, pad_multiple,
        pack, stats, staging=False, direct=True,
    ):
        yield host, meta


def stream_to_device(
    source: GenotypeSource,
    block_variants: int,
    start_variant: int = 0,
    device=None,
    sharding=None,
    prefetch: int = 2,
    pad_multiple: int = 1,
    pack: bool = False,
    stats: dict | None = None,
) -> Iterator[tuple[jax.Array, BlockMeta]]:
    """Yield device-resident, shape-stable (N, padded_width) blocks.

    A daemon thread runs the (possibly slow, pure-Python/IO) source
    iterator and fills a bounded queue; the consumer side transfers to
    ``device`` (or places with ``sharding`` for the multi-chip path) and
    yields. Errors in the producer propagate to the consumer; abandoning
    the generator early (caller raises / breaks) stops the producer
    instead of leaving it blocked on the full queue with the source open.

    ``pad_multiple``: additionally round the padded width up to this
    multiple — variant-sharded placement needs the variant axis divisible
    by the mesh size.

    ``pack``: ship 2-bit packed uint8 blocks (N, width/4) instead of
    dense int8 — 4x less host→device traffic, unpacked on device inside
    the gram update (ops/gram.update_packed). Packing happens in the
    producer thread, overlapping the chip's FMA on the previous block. A
    source exposing ``packed_blocks`` (the 2-bit columnar store) is
    sliced zero-copy instead of being unpacked and re-packed.

    ``stats``: optional dict the producer thread updates in place —
    currently ``max_value`` (largest entry seen, dense transport only;
    the packed codec's domain is bounded at 2 by construction). Feeds
    the runner's int32-accumulator exactness guard for arbitrary int8
    tables; computed off the critical path.
    """

    def place(host):
        if sharding is not None:
            return jax.device_put(host, sharding)
        if device is not None:
            return jax.device_put(host, device)
        return jax.device_put(host)

    # Slabs whose transfers may still be in flight: a staged slab only
    # rotates back once ITS device_put completed (mutating host memory
    # under an in-flight H2D copy is the one bug this ring must never
    # have). Recycling lags TRANSFER_DEPTH blocks behind the yield
    # point, so by the time a slab is reclaimed its transfer started
    # TRANSFER_DEPTH blocks ago — the ready-wait is the residual, ~0 in
    # a healthy pipeline, and measured when it is not. Yields themselves
    # are NEVER delayed: the consumer sees exactly the block cadence a
    # depth-1 feed had (checkpoint cursors, producer skew, and error
    # positions are unchanged by the ring).
    pending: deque = deque()

    def recycle_oldest():
        dev, slot = pending.popleft()
        # Chaos site at slab-retire time: a "delay" is a host->device
        # link that stalls exactly when the ring needs its slab back
        # (the stage-wait backpressure path must absorb it); an
        # "io_error" is a transfer that never completes (not retryable
        # — the job resumes from its checkpoint, like device.put).
        faults.fire("prefetch.transfer_wait")
        t0 = time.perf_counter()
        dev.block_until_ready()
        telemetry.observe("prefetch.transfer_wait_s",
                          time.perf_counter() - t0)
        slot.release()

    producer = _produce_host_blocks(
        source, block_variants, start_variant, prefetch, pad_multiple,
        pack, stats, staging=_can_stage(device, sharding),
    )
    try:
        for host_block, slot, meta in producer:
            # Chaos site: a "delay" here is a stalled host->device link
            # (the prefetch queue must absorb it); an "io_error" is a
            # failed transfer (not retryable — the stream's cursor
            # semantics make the job resumable from its checkpoint
            # instead).
            faults.fire("device.put")
            dev_block = place(host_block)
            if slot is not None:
                pending.append((dev_block, slot))
                telemetry.gauge_set("prefetch.transfers_in_flight",
                                    float(len(pending)))
                if len(pending) > TRANSFER_DEPTH:
                    recycle_oldest()
            yield dev_block, meta
    finally:
        # Stop the producer FIRST (its generator's finally sets the stop
        # event), THEN release the in-flight slabs: released in the
        # other order, a producer blocked on the ring could win a slab
        # whose transfer is still live and overwrite it under the copy —
        # the aliasing bug the ring exists to prevent. acquire()'s
        # post-get stop check closes the remaining race.
        producer.close()
        while pending:
            _dev, slot = pending.popleft()
            slot.release()


def _produce_host_blocks(
    source, block_variants, start_variant, prefetch, pad_multiple, pack,
    stats, staging=False, direct=False,
):
    """The producer thread: yields ``(host_array, slot | None, meta)``.

    ``staging`` arms the reusable-slab ring for paths that materialize a
    fresh host buffer per block (dense padding, host-side 2-bit
    packing); the zero-copy packed-source path stays unstaged — its
    blocks are read-only mmap views, already stable host memory.
    ``direct`` opts an UNSTAGED dense stream into the store's
    decode-straight-into-buffer drive (fresh consumer-owned buffer per
    block) — the multi-host per-process feed's path, where each host
    decodes only its window's variants with zero intermediate copies;
    single-host unstaged streams keep the ordinary blocks() path (and
    its decode-cache population) unchanged.
    """
    q: queue.Queue = queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()
    grid = pad_multiple * (bitpack.VARIANTS_PER_BYTE if pack else 1)
    width = -(-block_variants // grid) * grid

    zero_copy = (
        pack
        and hasattr(source, "packed_blocks")
        and block_variants % bitpack.VARIANTS_PER_BYTE == 0
    )
    # Dense store streams skip the source's own block materialization
    # entirely: the producer drives the store's decode_range_into
    # against the destination buffer, so a cold chunk inflates +
    # unpacks STRAIGHT into it in one native call (store/codec.py) —
    # no per-block dense intermediate, no decode-then-slice-then-pad
    # copy chain. Staged placements decode into the reusable ring
    # slab; unstaged ones (CPU targets, host-block consumers like the
    # multi-host per-process feed) decode into a fresh MISSING-filled
    # buffer the consumer owns outright — either way the per-block
    # copies collapse to zero. Capability-detected: StoreSource,
    # its range/window shares, and the retry boundary (the DEFAULT
    # wrapper) all advertise it (ingest/resilient.py, ingest/source.py,
    # store/reader.py); other wrappers (filters) take the ordinary
    # path below, bit-identically.
    decode_direct = (
        (staging or direct)
        and not pack
        and hasattr(source, "decode_range_into")
        and hasattr(source, "block_spans")
    )
    ring = None
    if staging and not zero_copy:
        n_slots = max(1, prefetch) + TRANSFER_DEPTH + 2
        if pack:
            ring = _StagingRing(
                n_slots,
                (source.n_samples, width // bitpack.VARIANTS_PER_BYTE),
                np.uint8, PACKED_MISSING,
            )
        else:
            ring = _StagingRing(
                n_slots, (source.n_samples, width), GENOTYPE_DTYPE, MISSING,
            )

    def _stage(host) -> "tuple | None":
        """Copy a freshly-built block into a recycled slab; None means
        the stream was abandoned while waiting for a free slot."""
        slot = ring.acquire(stop)
        if slot is None:
            return None
        v = host.shape[1]
        np.copyto(slot.buf[:, :v], host)
        if v < slot.buf.shape[1]:
            slot.buf[:, v:] = PACKED_MISSING if pack else MISSING
        return slot.buf, slot

    def _put(item, measure: bool = True) -> bool:
        # Producer-side backpressure metric: time this block waited for
        # queue space. Consistently large put-waits mean the CONSUMER
        # (device transfer/update) is the bottleneck and deeper prefetch
        # buys nothing; ~zero means ingest is the bottleneck (see the
        # get-wait twin below). Sentinel puts (_END, exceptions) are
        # NOT measured: the terminal _END put blocks until the consumer
        # drains the whole queue, and that one non-block sample would
        # dominate a short stream's p95/max and fake a consumer
        # bottleneck.
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                if measure:
                    telemetry.observe("prefetch.put_wait_s",
                                      time.perf_counter() - t0)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            if decode_direct:
                if stats is not None:
                    # Store payloads are 2-bit dosages by construction:
                    # the dense-transport max-value guard's answer is
                    # known without scanning a single block.
                    stats["max_value"] = 2
                for lo, hi, meta in source.block_spans(
                    block_variants, start_variant
                ):
                    if ring is not None:
                        slot = ring.acquire(stop)
                        if slot is None:
                            return
                        buf = slot.buf
                    else:
                        # Unstaged (CPU placement / host-block
                        # consumer): the consumer owns each block
                        # indefinitely, so decode into a fresh buffer —
                        # pre-filled MISSING, which doubles as the
                        # ragged-tail pad.
                        slot = None
                        buf = np.full((source.n_samples, width),
                                      MISSING, GENOTYPE_DTYPE)
                    w = hi - lo
                    source.decode_range_into(lo, hi, buf)
                    if slot is not None and w < buf.shape[1]:
                        buf[:, w:] = MISSING
                    if not _put((buf, slot, meta)):
                        return
            elif zero_copy:
                w_bytes = width // bitpack.VARIANTS_PER_BYTE
                for pblock, meta in source.packed_blocks(
                    block_variants, start_variant
                ):
                    if not _put((pad_packed(pblock, w_bytes), None, meta)):
                        return
            elif pack:
                for block, meta in source.blocks(block_variants, start_variant):
                    host = bitpack.pack_dosages(pad_block(block, width))
                    if ring is not None:
                        staged = _stage(host)
                        if staged is None:
                            return
                        host, slot = staged
                    else:
                        slot = None
                    if not _put((host, slot, meta)):
                        return
            else:
                for block, meta in source.blocks(block_variants, start_variant):
                    if stats is not None and block.size:
                        stats["max_value"] = max(
                            stats.get("max_value", 0), int(block.max())
                        )
                    if ring is not None:
                        staged = _stage(block)
                        if staged is None:
                            return
                        host, slot = staged
                    else:
                        host, slot = pad_block(block, width), None
                    if not _put((host, slot, meta)):
                        return
            _put(_END, measure=False)
        except BaseException as e:  # propagate into consumer
            _put(e, measure=False)

    t = threading.Thread(target=produce, name="prefetch-producer",
                         daemon=True)
    t.start()
    try:
        while True:
            # Depth sampled before each get: max == configured depth
            # means the producer runs ahead (healthy); persistent 0
            # means the chip is starved by ingest. The get-wait is the
            # stall the consumer actually paid — its sum over the gram
            # phase is the bench digest's "prefetch stall fraction".
            telemetry.gauge_set("prefetch.queue_depth", q.qsize())
            t0 = time.perf_counter()
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            # Observed only for real blocks (the sentinel's wait is not
            # a per-block stall, and its sum feeds the digest's
            # prefetch_stall_frac).
            telemetry.observe("prefetch.get_wait_s",
                              time.perf_counter() - t0)
            yield item
    finally:
        stop.set()

"""2-bit genotype codec: four dosages per byte, unpacked on device.

Dosages occupy {0, 1, 2, missing} — two bits of information stored in an
eight-bit lane. The reference never faced this (its variants travelled as
JSON/protobuf over HTTPS, SURVEY.md §3.5); on TPU the host→device link is
the bottleneck for the 40M-variant north star (400 GB at int8, 100 GB at
2 bits), so the framework ships *packed* blocks and unpacks with
shift/mask on device, where the elementwise work is free next to the
matmuls. Same idea as PLINK's .bed format (the field's standard 2-bit
genotype container), with a simpler encoding:

    code 0 -> dosage 0      code 2 -> dosage 2
    code 1 -> dosage 1      code 3 -> missing (-1)

Variant ``v`` lives in byte ``v // 4`` at bit offset ``2 * (v % 4)``.
Ragged widths are padded with code 3 (missing), which contributes zero to
every gram piece — the same semantically-free padding the streaming layer
already uses (ingest/prefetch.py).
"""

from __future__ import annotations

import numpy as np

CODE_MISSING = 3
VARIANTS_PER_BYTE = 4


def packed_width(n_variants: int) -> int:
    """Bytes per sample row needed to hold ``n_variants`` dosages."""
    return -(-n_variants // VARIANTS_PER_BYTE)


def pack_dosages(g: np.ndarray) -> np.ndarray:
    """(N, V) int8 dosages in {-1, 0, 1, 2} -> (N, ceil(V/4)) uint8.

    Values outside the dosage domain would be silently corrupted by the
    2-bit truncation, so they are rejected loudly — the packed path is for
    genotype dosages (core/dtypes.py policy), not arbitrary count tables
    (those take the dense Bray-Curtis route).

    Runs in the prefetch producer thread, so it takes the single-pass
    native loop (native/codec.cpp) when available; the NumPy path below
    is the byte-identical fallback and test oracle.
    """
    g = np.asarray(g)
    if g.ndim != 2:
        raise ValueError(f"expected (N, V) matrix, got shape {g.shape}")
    from spark_examples_tpu import native

    out = native.pack_dosages(g)
    if out is not None:
        return out
    lo, hi = int(g.min(initial=0)), int(g.max(initial=0))
    if lo < -1 or hi > 2:
        raise ValueError(
            f"dosage values out of 2-bit range [-1, 2]: min={lo} max={hi} "
            "(pack_dosages is for genotype dosages only)"
        )
    n, v = g.shape
    codes = np.where(g < 0, CODE_MISSING, g).astype(np.uint8)
    pad = -v % VARIANTS_PER_BYTE
    if pad:
        codes = np.concatenate(
            [codes, np.full((n, pad), CODE_MISSING, np.uint8)], axis=1
        )
    c = codes.reshape(n, -1, VARIANTS_PER_BYTE)
    return (
        c[..., 0] | (c[..., 1] << 2) | (c[..., 2] << 4) | (c[..., 3] << 6)
    )


def unpack_dosages_np(packed: np.ndarray) -> np.ndarray:
    """Host-side inverse of :func:`pack_dosages` (test oracle / CPU path).

    Returns the full (N, 4 * W) int8 matrix — any pad columns come back as
    missing (-1), which downstream accumulation treats as absent.
    """
    packed = np.asarray(packed, np.uint8)
    from spark_examples_tpu import native

    out = native.unpack_dosages(packed)
    if out is not None:
        return out
    shifts = np.array([0, 2, 4, 6], np.uint8)
    codes = (packed[:, :, None] >> shifts) & np.uint8(3)
    codes = codes.reshape(packed.shape[0], -1)
    return np.where(codes == CODE_MISSING, -1, codes).astype(np.int8)


def unpack_dosages(packed):
    """Device-side unpack: (N, W) uint8 -> (N, 4 * W) int8 dosages.

    Pure elementwise shift/mask — under jit, XLA fuses it with the
    indicator thresholds feeding the gram matmuls, so the int8 block never
    round-trips through HBM at full width on its own.
    """
    import jax.numpy as jnp

    shifts = jnp.arange(0, 8, 2, dtype=jnp.uint8)
    codes = (packed[:, :, None] >> shifts) & jnp.uint8(3)
    codes = codes.reshape(packed.shape[0], -1)
    return jnp.where(
        codes == CODE_MISSING, jnp.int8(-1), codes.astype(jnp.int8)
    )

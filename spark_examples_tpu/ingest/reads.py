"""Reads tier — the reference's ``ReadsRDD`` surface (SURVEY.md §2.1).

The reference mirrored its variants machinery for aligned reads: a
``ReadsRDD : RDD[(ReadKey, Read)]`` paging ``searchReads`` per genomic
range, consumed by ``SearchReadsExample*`` coverage/count demos
(SURVEY.md §3.4 — smoke-test tier, no linear-algebra tail). Here the
same shape: a ``Read`` record, sources that stream reads in genomic
order per range, and a vectorised coverage pipeline
(:mod:`spark_examples_tpu.pipelines.coverage`).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Iterator, Sequence

import numpy as np

from spark_examples_tpu.core.config import ReferenceRange


@dataclass(frozen=True)
class Read:
    """Serializable mirror of an aligned read (reference: the ``Read``
    case class, SURVEY.md §2.1 'Serializable data model')."""

    name: str
    contig: str
    start: int  # 0-based alignment start
    length: int  # aligned span on the reference
    mapq: int = 60

    @property
    def end(self) -> int:
        return self.start + self.length


class ReadsSource:
    """Protocol: stream (starts, lengths) int64 array batches per range."""

    def ranges(self) -> Sequence[ReferenceRange]: ...

    def read_batches(
        self, ref: ReferenceRange, batch: int = 65536
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]: ...


@dataclass
class SyntheticReadsSource(ReadsSource):
    """Seeded synthetic aligned reads over given ranges: uniform starts,
    fixed-ish lengths — enough to validate coverage math at any scale."""

    references: Sequence[ReferenceRange]
    reads_per_range: int = 100_000
    read_length: int = 150
    length_jitter: int = 10
    seed: int = 0

    def ranges(self) -> Sequence[ReferenceRange]:
        return list(self.references)

    def read_batches(self, ref: ReferenceRange, batch: int = 65536):
        # Two independent streams so the generated reads are identical
        # regardless of the caller's batch size (prefix-stable draws).
        # zlib.crc32, not hash(): str hashes are salted per process and
        # would break cross-run reproducibility of --seed.
        contig_key = zlib.crc32(ref.contig.encode()) & 0xFFFF
        key = [self.seed, contig_key, ref.start]
        rng_s = np.random.default_rng(np.random.SeedSequence(key + [1]))
        rng_l = np.random.default_rng(np.random.SeedSequence(key + [2]))
        remaining = self.reads_per_range
        while remaining > 0:
            m = min(batch, remaining)
            starts = rng_s.integers(
                ref.start, max(ref.end - 1, ref.start + 1), m
            )
            lengths = self.read_length + rng_l.integers(
                -self.length_jitter, self.length_jitter + 1, m
            )
            yield starts.astype(np.int64), np.maximum(lengths, 1).astype(np.int64)
            remaining -= m


@dataclass
class SamSource(ReadsSource):
    """Minimal SAM text reader (dependency-free): name, contig, 1-based
    pos, and CIGAR-less length from the SEQ field. Good enough for the
    coverage example tier; BAM needs htslib and is out of scope."""

    path: str
    references: Sequence[ReferenceRange] = field(default_factory=list)

    def ranges(self) -> Sequence[ReferenceRange]:
        if self.references:
            return list(self.references)
        # default: one open-ended range per contig seen in the header
        contigs = []
        with open(self.path) as f:
            for line in f:
                if line.startswith("@SQ"):
                    fields = dict(
                        kv.split(":", 1) for kv in line.rstrip().split("\t")[1:]
                    )
                    contigs.append(
                        ReferenceRange(fields["SN"], 0, int(fields["LN"]))
                    )
                elif not line.startswith("@"):
                    break
        return contigs

    _by_contig: dict | None = field(default=None, repr=False)

    def _load(self) -> dict:
        """Single-pass parse, bucketed per contig — avoids re-reading the
        file once per queried range."""
        if self._by_contig is None:
            buckets: dict[str, tuple[list[int], list[int]]] = {}
            with open(self.path) as f:
                for line in f:
                    if line.startswith("@"):
                        continue
                    fields = line.rstrip("\n").split("\t")
                    contig, pos, seq = fields[2], int(fields[3]) - 1, fields[9]
                    s, l = buckets.setdefault(contig, ([], []))
                    s.append(pos)
                    l.append(len(seq))
            self._by_contig = {
                c: (np.asarray(s, np.int64), np.asarray(l, np.int64))
                for c, (s, l) in buckets.items()
            }
        return self._by_contig

    def read_batches(self, ref: ReferenceRange, batch: int = 65536):
        data = self._load().get(ref.contig)
        if data is None:
            return
        starts, lengths = data
        keep = (starts >= ref.start) & (starts < ref.end)
        starts, lengths = starts[keep], lengths[keep]
        for i in range(0, len(starts), batch):
            yield starts[i : i + batch], lengths[i : i + batch]

"""Retrying ingest — transient IO failures must not kill a 40M-variant job.

The reference got this for free: a failed Spark task re-read its RDD
partition through lineage (SURVEY.md §5 "Failure detection"). The
TPU-native stream has no lineage, so the equivalent is explicit:
:class:`RetryingSource` wraps any file-backed
:class:`~spark_examples_tpu.ingest.source.GenotypeSource` and, when a
block read raises a transient IO error, **re-opens the source and seeks
back to the cursor** — every source's ``blocks(bv, start)`` already
implements deterministic resume for checkpointing (SURVEY.md §5), and
retry rides exactly that contract: the re-opened iterator restarts at
the last successfully yielded block's ``meta.stop``, so the downstream
stream is byte-identical to an unfailed read.

What is deliberately NOT retried:

- **Corrupt blocks fail fast.** A block with the wrong sample count,
  rank, or dtype means the file (or a transform above it) is damaged,
  not flaky — retrying would re-yield the same garbage into the
  accumulation. The error names the resume cursor so an operator can
  fix the input and resume from a checkpoint instead of restarting.
  A "skip the bad block" policy is intentionally not offered: silently
  dropping variants shifts every later cursor and corrupts
  checkpoint/resume alignment.
- **Non-IO exceptions.** ValueError/contract violations propagate
  unchanged (they are bugs or bad configs, not weather).

Fault-injection site ``ingest.block_read`` (core/faults.py) fires
*inside* the retry boundary, so the chaos tests exercise precisely the
path a flaky NFS mount would.
"""

from __future__ import annotations

import dataclasses
import random
import time
import warnings
from dataclasses import dataclass

import numpy as np

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE
from spark_examples_tpu.ingest.source import GenotypeSource


class IngestExhaustedError(IOError):
    """Bounded retries ran out. Carries the resume cursor in the message
    (and as ``.cursor``) so the job can be restarted from a checkpoint
    or an explicit ``start_variant`` without re-reading good data."""

    def __init__(self, msg: str, cursor: int):
        super().__init__(msg)
        self.cursor = cursor


class CorruptBlockError(ValueError):
    """A block that cannot be valid (wrong cohort width / rank / dtype).
    Never retried — fail fast with the cursor named."""

    def __init__(self, msg: str, cursor: int):
        super().__init__(msg)
        self.cursor = cursor


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter (decorrelated restarts
    when many hosts share one flaky filesystem)."""

    max_retries: int = 3  # per-incident: consecutive failures without progress
    backoff_s: float = 0.05
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 5.0
    jitter: float = 0.25  # +- fraction of the computed backoff
    retry_on: tuple = (IOError, OSError)

    def sleep_s(self, attempt: int, rng: random.Random) -> float:
        base = min(
            self.backoff_s * self.backoff_multiplier ** attempt,
            self.max_backoff_s,
        )
        return base * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


@dataclass
class RetryingSource:
    """Transparent retry wrapper over a file-backed source.

    Metadata properties and the ``exact_n_variants`` claim pass through
    (a retried stream yields the identical block sequence, so the inner
    source's contracts survive wrapping). The packed transport
    (``packed_blocks``) is forwarded under the same retry loop when the
    inner source has one.

    ``reopen``: factory returning a FRESH inner source, invoked before
    each retry. Sources that open file handles inside ``blocks()``
    (VCF/plink/parquet) re-open naturally and don't need it; memmap-
    backed sources (the packed store) hold their mapping on the object,
    so without a rebuilder every "retry" would re-slice the same stale
    mapping and the budget would exhaust without one real re-open.
    (A fatal mmap fault the kernel reports as SIGBUS is outside any
    userspace retry's reach — this covers errors surfaced as OSError.)
    The retry budget is per-incident: a successfully yielded block
    resets it, so independent recoverable hiccups hours apart never
    accumulate into a job kill.
    """

    inner: GenotypeSource
    policy: RetryPolicy = RetryPolicy()
    seed: int = 0
    reopen: object = None  # () -> GenotypeSource, or None

    def __post_init__(self):
        if hasattr(self.inner, "packed_blocks"):
            self.packed_blocks = self._packed_blocks
        # The staged dense feed detects the store's decode-straight-
        # into-slab drive by capability (ingest/prefetch.py
        # decode_direct); the DEFAULT config wraps every store in this
        # boundary (io_retries=3), so without forwarding, production
        # store-fed jobs would silently demote to the materialize-then-
        # copy path — and choosing compression would mean losing IO-
        # retry protection.
        if hasattr(self.inner, "decode_range_into") and hasattr(
                self.inner, "block_spans"):
            self.block_spans = self._block_spans
            self.decode_range_into = self._decode_range_into

    @property
    def n_samples(self) -> int:
        return self.inner.n_samples

    @property
    def n_variants(self) -> int:
        return self.inner.n_variants

    @property
    def sample_ids(self) -> list[str]:
        return self.inner.sample_ids

    @property
    def exact_n_variants(self) -> bool:
        return bool(getattr(self.inner, "exact_n_variants", False))

    def _validate(self, block: np.ndarray, cursor: int) -> None:
        n = self.inner.n_samples
        if (
            getattr(block, "ndim", 0) != 2
            or block.shape[0] != n
            or block.dtype != GENOTYPE_DTYPE
        ):
            telemetry.count("ingest.corrupt_blocks")
            raise CorruptBlockError(
                f"corrupt block at variant cursor {cursor}: got "
                f"shape {getattr(block, 'shape', None)} dtype "
                f"{getattr(block, 'dtype', None)}, expected ({n}, v) "
                f"{np.dtype(GENOTYPE_DTYPE).name} — the input is damaged "
                "(not a transient failure, so it is not retried); fix the "
                f"file and resume from start_variant={cursor} (or the "
                "last --checkpoint-dir checkpoint)",
                cursor,
            )

    def _stream(self, opener, block_variants: int, start_variant: int,
                validate):
        """The shared retry loop: ``opener(cursor)`` re-opens the inner
        iterator at a cursor; blocks re-index over the OUTPUT stream so
        downstream ordinals don't jump backwards across a re-open."""
        cursor = start_variant
        idx = 0
        rng = random.Random(self.seed)
        retries_left = self.policy.max_retries
        need_reopen = False
        while True:
            it = None
            try:
                # The rebuild and the open live INSIDE the boundary: on
                # a still-flaky mount reopen()/opener() fail exactly
                # like a block read, and must consume the same budget
                # and produce the same cursor-naming exhaustion error —
                # not escape as a raw OSError.
                if need_reopen and self.reopen is not None:
                    telemetry.count("ingest.reopens")
                    self.inner = self.reopen()
                need_reopen = False
                it = opener(cursor)
                for block, meta in it:
                    faults.fire("ingest.block_read")
                    if validate:
                        self._validate(block, meta.start)
                    yield block, dataclasses.replace(meta, index=idx)
                    idx += 1
                    cursor = meta.stop
                    # Progress restores the budget: the bound is on
                    # CONSECUTIVE failures (one incident), not on the
                    # lifetime of a stream — otherwise job-death
                    # probability would grow with stream length and a
                    # 40M-variant run would die on its 4th independent,
                    # individually-recoverable hiccup.
                    retries_left = self.policy.max_retries
                return
            except self.policy.retry_on as e:
                if retries_left <= 0:
                    telemetry.count("ingest.exhausted")
                    raise IngestExhaustedError(
                        f"ingest failed at variant cursor {cursor} after "
                        f"{self.policy.max_retries} retries: {e!r} — "
                        "resume from the last --checkpoint-dir checkpoint "
                        f"or restart this stream at start_variant={cursor}",
                        cursor,
                    ) from e
                attempt = self.policy.max_retries - retries_left
                retries_left -= 1
                delay = self.policy.sleep_s(attempt, rng)
                # Counted process-wide (this source has no timer handle);
                # PhaseTimer.report() surfaces nonzero retry counters so
                # a silently-retrying run is distinguishable from a
                # clean one in the same output that reports throughput.
                telemetry.count("ingest.retries")
                telemetry.count("ingest.backoff_s", delay)
                warnings.warn(
                    f"transient ingest error at variant cursor {cursor} "
                    f"({e!r}); retrying in {delay * 1e3:.0f} ms "
                    f"({retries_left} retries left)",
                    RuntimeWarning,
                    stacklevel=2,
                )
                time.sleep(delay)
                need_reopen = True
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()

    def blocks(self, block_variants: int, start_variant: int = 0):
        yield from self._stream(
            lambda cur: self.inner.blocks(block_variants, cur),
            block_variants, start_variant, validate=True,
        )

    def _packed_blocks(self, block_variants: int, start_variant: int = 0):
        # Packed blocks are (N, width/4) uint8 — shape/dtype validation
        # lives in the dense contract, not here; the codec's unpack is
        # bounds-safe by construction.
        yield from self._stream(
            lambda cur: self.inner.packed_blocks(block_variants, cur),
            block_variants, start_variant, validate=False,
        )

    def _block_spans(self, block_variants: int, start_variant: int = 0):
        # Pure manifest arithmetic, no chunk IO — nothing to retry.
        yield from self.inner.block_spans(block_variants, start_variant)

    def _decode_range_into(self, lo: int, hi: int, out: np.ndarray,
                           col_off: int = 0) -> None:
        """One bounded decode under the retry boundary. A transient
        error may leave ``out`` partially written; a successful retry
        re-decodes the whole [lo, hi) span over it, so the slab leaves
        here bit-identical to an unwrapped decode. StoreCorruptError is
        a ValueError — quarantine semantics pass through untouched."""
        rng = random.Random(self.seed)
        retries_left = self.policy.max_retries
        need_reopen = False
        while True:
            try:
                # The rebuild lives INSIDE the boundary (same contract
                # as _stream): on a still-flaky mount reopen() fails
                # like a block read and consumes the same budget.
                if need_reopen and self.reopen is not None:
                    telemetry.count("ingest.reopens")
                    self.inner = self.reopen()
                need_reopen = False
                # Same per-block site the streamed path fires inside
                # its boundary: an armed kill/io_error spec hits the
                # staged drive at the same cadence.
                faults.fire("ingest.block_read")
                self.inner.decode_range_into(lo, hi, out, col_off)
                return
            except self.policy.retry_on as e:
                if retries_left <= 0:
                    telemetry.count("ingest.exhausted")
                    raise IngestExhaustedError(
                        f"ingest failed at variant cursor {lo} after "
                        f"{self.policy.max_retries} retries: {e!r} — "
                        "resume from the last --checkpoint-dir "
                        "checkpoint or restart this stream at "
                        f"start_variant={lo}",
                        lo,
                    ) from e
                attempt = self.policy.max_retries - retries_left
                retries_left -= 1
                delay = self.policy.sleep_s(attempt, rng)
                telemetry.count("ingest.retries")
                telemetry.count("ingest.backoff_s", delay)
                warnings.warn(
                    f"transient ingest error at variant cursor {lo} "
                    f"({e!r}); retrying in {delay * 1e3:.0f} ms "
                    f"({retries_left} retries left)",
                    RuntimeWarning,
                    stacklevel=2,
                )
                time.sleep(delay)
                need_reopen = True

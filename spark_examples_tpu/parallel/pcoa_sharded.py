"""Tile2d-sharded finalize → center → randomized eigh → coordinates.

The 76k-exome regime (BASELINE.md config 4) can *accumulate* its Gram
tiles across the mesh (parallel/gram_sharded tile2d mode), but a 76k^2
f32 matrix is ~23 GB — materialising it on one chip (or the host) for
the downstream finalize/centering/eigensolve would undo the whole point
of tiling. This module keeps every N x N intermediate tile2d-sharded
(rows over mesh axis ``i``, cols over ``j``) from the raw accumulators
all the way to the eigensolve, whose only large operations are
``b @ q`` products — (N, N) x (N, k+p) matmuls that contract the
column axis locally and psum over ``j`` (XLA SPMD inserts the
collectives from the sharding annotations; no hand-written comms).

Per-device residency is therefore O(N^2 / n_devices) for the matrix
tiles plus O(N (k+p)) for the probe block — the (N, k+p) subspace is
deliberately replicated (at 76k x 26 f32 it is ~8 MB, noise next to a
2.9 GB tile).

The combination algebra (transposes like ``yc + yc^T``) resolves to a
mesh transpose of the tile grid — P(i, j) -> P(j, i) — which XLA lowers
to an all-to-all over ICI, still never widening any single device's
footprint beyond its tile.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from spark_examples_tpu.core import meshes
from spark_examples_tpu.core.config import (
    EIGH_ITERS_DEFAULT,
    EIGH_OVERSAMPLE_DEFAULT,
)
from spark_examples_tpu.models.pca import PCAResult
from spark_examples_tpu.models.pcoa import PCoAResult
from spark_examples_tpu.ops import distances
from spark_examples_tpu.ops.centering import center_matrix, gower_center
from spark_examples_tpu.ops.eigh import coords_from_eigpairs, randomized_eigh
from spark_examples_tpu.parallel.gram_sharded import GramPlan, _acc_shardings


@lru_cache(maxsize=32)
def _finalize_field_jit(plan: GramPlan, metric: str, field: str):
    """acc (tile2d leaves) -> one finalized matrix ("distance" for the
    PCoA route, "similarity" for PCA), kept tile2d.

    Donation is restricted to leaves the executable can actually alias
    into the f32 output tile: XLA input/output aliasing is by
    (dtype, shape, layout), so donating the count family's int32 pieces
    (or grm's scalar nvar) only earns the "Some donated buffers were
    not usable" warning — every MULTICHIP dryrun printed it — without
    freeing anything the post-call invalidation doesn't already free.
    Only float-family N x N leaves (grm's zz) qualify; everything else
    rides the non-donated argument and is dropped by the caller's
    ``del`` as before. tests/test_parallel.py asserts the whole sharded
    route now compiles warning-free."""
    from spark_examples_tpu import kernels

    kern = kernels.get(metric)
    acc_sh = _acc_shardings(plan, metric)
    donatable = tuple(
        k for k in kern.acc_leaves
        if kern.family == "float" and k not in kern.scalar_leaves
    )
    rest = tuple(k for k in kern.acc_leaves if k not in donatable)
    jitted = jax.jit(
        lambda don, keep: distances.finalize({**don, **keep}, metric)[field],
        in_shardings=(
            {k: acc_sh[k] for k in donatable},
            {k: acc_sh[k] for k in rest},
        ),
        out_shardings=plan.acc_sharding,
        donate_argnums=(0,),
    )

    def call(acc):
        return jitted({k: acc[k] for k in donatable},
                      {k: acc[k] for k in rest})

    return call


def _center_sym(s):
    """PCA centering: symmetrized J A J (models/pca._fit's form)."""
    c = center_matrix.__wrapped__(s)
    return 0.5 * (c + c.T)


_CENTER_FN = {"gower": gower_center, "pca": _center_sym}


@lru_cache(maxsize=32)
def _center_jit(plan: GramPlan, kind: str = "gower"):
    """N x N matrix (tile2d) -> centered matrix, kept tile2d. Row/col
    mean subtraction is two sharded reductions (psum over one mesh axis
    each); the PCA variant's symmetry-guard transpose is a mesh
    transpose of the tile grid (all-to-all over ICI). Nothing widens."""
    return jax.jit(
        _CENTER_FN[kind],
        in_shardings=(plan.acc_sharding,),
        out_shardings=plan.acc_sharding,
        donate_argnums=(0,),
    )


@lru_cache(maxsize=32)
def _eigh_jit(plan: GramPlan, k: int, oversample: int, iters: int,
              select: str = "top", with_trace: bool = True):
    """B (tile2d) -> (vals, vecs[, trace]) replicated.

    The algorithm is exactly ops.eigh.randomized_eigh — the only
    difference is the sharding contract: B stays tiled, the (N, k+p)
    subspace iterates replicated, and every B @ Q is a sharded matmul
    (local contraction + psum over mesh axis j). QR/eigh of the skinny
    (N, p)/(p, p) blocks run replicated — at 76k x 26 that is ~100
    MFLOP, irrelevant next to the 2 N^2 p matmuls. ``select="abs"`` is
    the PCA ordering; ``with_trace`` adds total inertia (computed inside
    so ``b`` can be donated and freed).
    """
    repl = meshes.replicated(plan.mesh)

    def solve(b, key):
        vals, vecs = randomized_eigh.__wrapped__(
            b, k, key, oversample=oversample, iters=iters, select=select
        )
        if with_trace:
            return vals, vecs, jnp.trace(b)
        return vals, vecs

    # No donation: every output is a small replicated (k,)/(N, k) block
    # — a tiled N x N input can never alias one, so donating b only
    # produced the unusable-donation warning. b is freed by the caller's
    # scope exit exactly as before.
    return jax.jit(
        solve,
        in_shardings=(plan.acc_sharding, repl),
        out_shardings=(repl, repl, repl) if with_trace else (repl, repl),
    )


def _solve_sharded(plan, acc, metric, field, center_kind, k, key,
                   oversample, iters, select, with_trace,
                   check_shardings, timer):
    """Shared stage choreography of both sharded routes: finalize ->
    center -> randomized eig, every alias-able N x N input donated
    stage to stage and the rest dropped eagerly (per-device peak ~one
    tile per live stage) and tile-asserted at each boundary. The two
    public entry points differ only in parameters."""
    from spark_examples_tpu.core.profiling import PhaseTimer, hard_sync

    if key is None:
        key = jax.random.key(0)
    if timer is None:
        timer = PhaseTimer()
    with timer.phase("finalize"):
        mat = _finalize_field_jit(plan, metric, field)(acc)
        if check_shardings:
            assert_tiled(mat, plan, f"finalize {field}")
        b = hard_sync(_center_jit(plan, center_kind)(mat))
        del mat  # donated into b
    if check_shardings:
        assert_tiled(b, plan, f"{center_kind}-centered matrix")
    with timer.phase("eigh"):
        out = hard_sync(
            _eigh_jit(plan, k, oversample, iters, select, with_trace)(
                b, key
            )
        )
    return out


def pca_coords_sharded(
    plan: GramPlan,
    acc: dict,
    metric: str = "shared-alt",
    k: int = 10,
    key: jax.Array | None = None,
    oversample: int = EIGH_OVERSAMPLE_DEFAULT,
    iters: int = EIGH_ITERS_DEFAULT,
    check_shardings: bool = True,
    timer=None,
) -> PCAResult:
    """Raw tile2d accumulators -> PCA coordinates with no full N x N
    leaf on any device — the flagship ``VariantsPcaDriver`` at the 76k
    regime, where the host fallback (materialize N x N, dense eigh)
    stops being possible. Mirrors models/pca.fit_pca stage for stage
    (finalize similarity -> center+symmetrize -> top-|lambda| eig ->
    coords = v * lambda); small-N parity with the dense route is pinned
    by tests/test_parallel.py. ``acc`` is donated stage to stage, as in
    :func:`pcoa_coords_sharded`.
    """
    vals, vecs = _solve_sharded(
        plan, acc, metric, "similarity", "pca", k, key, oversample,
        iters, select="abs", with_trace=False,
        check_shardings=check_shardings, timer=timer,
    )
    coords = vecs * vals[None, :]  # projection C v = lambda v
    # The tile2d randomized route is the "exact" rung of the accuracy
    # ladder: it materializes the (tiled) N x N and solves it — the
    # sketch rungs (spark_examples_tpu/solvers) never build it at all.
    return PCAResult(coords, vals, solver="exact")


def assert_tiled(x: jax.Array, plan: GramPlan, what: str) -> None:
    """Assert an N x N stage output is genuinely tile2d-sharded: every
    addressable shard holds a proper tile, never the full matrix."""
    n_i, n_j = plan.mesh.devices.shape
    if n_i * n_j == 1:
        return  # single device: tiling is vacuous
    n, m = x.shape
    want = (n // n_i, m // n_j)
    for sh in x.addressable_shards:
        if sh.data.shape != want:
            raise AssertionError(
                f"{what}: shard on {sh.device} has shape {sh.data.shape}, "
                f"want tile {want} — a full-size leaf landed on one device"
            )


def pcoa_coords_sharded(
    plan: GramPlan,
    acc: dict,
    metric: str,
    k: int = 10,
    key: jax.Array | None = None,
    oversample: int = EIGH_OVERSAMPLE_DEFAULT,
    iters: int = EIGH_ITERS_DEFAULT,
    check_shardings: bool = True,
    timer=None,
) -> PCoAResult:
    """Raw tile2d accumulators -> PCoA coordinates, no full N x N leaf
    on any single device at any stage boundary.

    Mirrors the dense route (finalize -> gower_center -> eigh -> coords,
    SURVEY.md §3.3) stage for stage; small-N parity with that route is
    pinned by tests/test_parallel.py. ``check_shardings`` verifies the
    tile contract on every N x N stage output (cheap: metadata only).
    ``timer``: optional PhaseTimer recording finalize/eigh phases (adds
    a hard sync per phase boundary for honest wall-clock).

    Every stage donates the N x N inputs its executable can alias
    (dist -> B; grm's float acc -> dist) and drops the rest eagerly, so
    per-device peak stays ~one tile per live stage instead of
    accumulating all of them; ``acc`` is consumed — callers must not
    reuse it afterwards.
    """
    vals, vecs, trace = _solve_sharded(
        plan, acc, metric, "distance", "gower", k, key, oversample,
        iters, select="top", with_trace=True,
        check_shardings=check_shardings, timer=timer,
    )
    coords = coords_from_eigpairs(vals, vecs)
    prop = jnp.maximum(vals, 0.0) / jnp.maximum(trace, 1e-30)
    return PCoAResult(coords, vals, prop, solver="exact")

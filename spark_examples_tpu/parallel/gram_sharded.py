"""Mesh-sharded Gram accumulation: the Spark shuffle, as XLA collectives.

The reference's only distribution strategy was data parallelism over the
variant axis — RDD partitions by genomic range, pair counts merged by a
netty-shuffle ``reduceByKey`` (SURVEY.md §2.2). Its TPU-native successor
is sharding annotations on the *same* jitted computation
(:func:`spark_examples_tpu.ops.gram.update`):

- **variant mode** (N x N fits per chip): the genotype block is sharded
  along the variant axis over every chip in the mesh, the accumulator is
  replicated. XLA's SPMD partitioner turns the indicator matmuls into
  local dots over each chip's variant shard plus one ``psum`` over ICI —
  exactly the "jax.distributed all-gather/all-reduce assembling the full
  N x N Gram on-device" the north star prescribes (BASELINE.json:5).
- **tile2d mode** (the 76k-exome regime, BASELINE.md config 4): the
  accumulator is tiled (rows over mesh axis i, cols over j) so each chip
  holds an (N/p_i, N/p_j) tile. Two block transports exist, chosen by
  ``make_update(block_layout=...)``:

  * ``"sharded"`` (default — host-streamed blocks): blocks arrive
    variant-sharded (each chip is fed 1/n_dev of the block over the
    host link) and the block is reassembled over ICI before each chip
    contracts its row-slice against its col-slice — host→device traffic
    per chip drops by n_dev, and the reassembly rides ICI, orders of
    magnitude faster than the host link. This is also exactly the
    transport the multi-host path needs: each process feeds only its
    own variant slice (parallel/multihost.py). HOW the shards reach
    every chip is the ``transport`` choice (``make_update(transport=
    ...)``, ``--tile2d-transport``):

    - ``"gather"`` — one bulk ``all_gather`` of the (packed) block in
      front of the contraction: the hot loop's only collective, but it
      runs SERIALLY before every block's matmuls. At 76k x 4096 int8
      it moves ~0.3 GB/block over ICI (~3 ms at v5e ICI rates) against
      ~10^13 FLOPs of tile matmuls — <2 % of the update (BASELINE.md
      config 4) — but the fraction grows as tiles shrink.
    - ``"ring"`` — a ``ppermute`` ring schedule (arXiv:2112.09017's
      gather-behind-the-MXU structure): each device contracts the
      variant shard it currently holds against its row/col tile slices
      while the next shard rotates in from its ring neighbor, so after
      D - 1 hops every device has contracted the full block and every
      hop overlapped a contraction. Shards stay 2-bit packed on the
      wire exactly as the gather path gathers them packed. Summation
      order is per-shard partial products added in ring order — int32
      accumulation is exact under reordering, so every count-family
      kernel is BIT-identical to the gather transport (pinned by
      tests/test_parallel.py); grm's f32 accumulation agrees to
      float tolerance.
    - ``"auto"`` (the config default) — ring when the plan's FLOPs
      model says one ring step's contraction outweighs a shard hop
      (:func:`resolve_transport`), gather otherwise (tiny tiles, where
      D small hops cost more latency than one bulk collective).
  * ``"replicated"`` (staged/on-device blocks): the block is already
    fully present on every chip (generated on device, or staged once),
    each chip slices its row/col operands locally, and the hot loop
    runs with NO collectives at all — chips are independent between
    checkpoints. ``tests/test_parallel.py`` compile-checks this claim
    (no all-gather/all-to-all in the lowered update). This is the
    layout the config-4 per-chip projection assumes.
- **replicated mode**: single-chip degenerate case (mesh (1,1)).

Mode choice is automatic from accumulator-memory footprint unless forced.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import lru_cache

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu import kernels
from spark_examples_tpu.core import meshes, telemetry
from spark_examples_tpu.core.config import (
    GRAM_PLAN_MODES,
    TILE2D_TRANSPORTS,
)
from spark_examples_tpu.ops import gram as gram_ops

# Rough per-chip HBM budget for resident accumulators (bytes).
_ACC_BUDGET = 8 * 2**30


@dataclass(frozen=True)
class GramPlan:
    mesh: Mesh
    mode: str  # replicated | variant | tile2d

    @property
    def acc_sharding(self) -> NamedSharding:
        if self.mode == "tile2d":
            return meshes.tile2d(self.mesh)
        return meshes.replicated(self.mesh)

    @property
    def scalar_sharding(self) -> NamedSharding:
        return meshes.replicated(self.mesh)

    @property
    def block_sharding(self) -> NamedSharding:
        # Both multi-device modes transport blocks variant-sharded: in
        # variant mode that IS the compute layout (local dot + psum); in
        # tile2d mode XLA all-gathers the shards over ICI inside the
        # update — either way each chip's host link carries 1/n_dev of
        # every block, and each *process* can feed only its own slice.
        # Blocks already resident on-device take the "replicated" layout
        # instead (make_update(block_layout="replicated")) and skip the
        # gather entirely.
        if self.mode != "replicated":  # variant and tile2d both shard
            return meshes.variants_flat(self.mesh)
        return meshes.replicated(self.mesh)

    @property
    def block_shards(self) -> int:
        """How many ways the variant axis of a block is split."""
        return self.mesh.devices.size if self.mode != "replicated" else 1


def check_tile_divisible(n_samples: int, mesh: Mesh) -> None:
    """tile2d requires the SAMPLE axis divisible by both mesh axes — and
    unlike the variant axis, it cannot be padded for free (a padded
    sample row would join the distance matrix as a phantom individual).
    Caught up front with the fixes named, instead of deep inside
    shard_map as a raw sharding error naming no framework concept
    (VERDICT r5 weak #4)."""
    n_i, n_j = mesh.devices.shape
    if n_samples % n_i or n_samples % n_j:
        # Largest valid cohort = largest multiple of lcm(n_i, n_j); a
        # multiple of n_i * n_j would over-trim (or suggest 0 when a
        # valid trim exists — lcm(2, 4) = 4, not 8).
        lcm = math.lcm(n_i, n_j)
        trim = (n_samples // lcm) * lcm
        trim_fix = (
            f", or trim the cohort to {trim} samples" if trim else ""
        )
        raise ValueError(
            f"tile2d cannot tile N={n_samples} samples over the "
            f"({n_i}, {n_j}) mesh: N must be divisible by both mesh "
            f"axes (N % {n_i} = {n_samples % n_i}, N % {n_j} = "
            f"{n_samples % n_j}). Fix: pick --mesh-shape with axes "
            f"dividing {n_samples}{trim_fix} "
            "(the sample axis cannot be padded — a padding row would "
            "appear in the output matrix as a phantom sample)."
        )


def plan_for(
    mesh: Mesh, n_samples: int, metric: str, mode: str = "auto"
) -> GramPlan:
    """Pick a distribution mode (or validate a forced one)."""
    if mode == "auto":
        n_dev = mesh.devices.size
        kern = kernels.maybe_get(metric)
        # N x N leaves only — scalar leaves (grm's nvar) are noise.
        n_acc = (max(len(kern.acc_leaves) - len(kern.scalar_leaves), 1)
                 if kern is not None else 1)
        acc_bytes = 4 * n_samples * n_samples * n_acc
        if n_dev == 1:
            mode = "replicated"
        elif acc_bytes <= _ACC_BUDGET:
            mode = "variant"
        else:
            mode = "tile2d"
    if mode not in GRAM_PLAN_MODES:
        raise ValueError(f"unknown gram mode {mode!r}")
    if mode == "tile2d":
        check_tile_divisible(n_samples, mesh)
    return GramPlan(mesh, mode)


def _acc_shardings(plan: GramPlan, metric: str):
    """Per-leaf shardings for the accumulator pytree — N x N leaves take
    the plan's accumulator layout, the kernel's declared scalar leaves
    (e.g. the GRM's nvar) stay replicated."""
    kern = kernels.get(metric)
    return {
        k: (plan.scalar_sharding if k in kern.scalar_leaves
            else plan.acc_sharding)
        for k in kern.acc_leaves
    }


def init_sharded(plan: GramPlan, n: int, metric: str):
    """Zero accumulators laid out per the plan."""
    if plan.mode == "tile2d":
        # Plans built directly (bypassing plan_for) still fail up front
        # with the actionable message, not a raw shard_map error.
        check_tile_divisible(n, plan.mesh)
    shardings = _acc_shardings(plan, metric)
    acc = gram_ops.init(n, metric)
    return {k: jax.device_put(v, shardings[k]) for k, v in acc.items()}


def _tile2d_shard_map_impl(plan: GramPlan, metric: str, packed: bool,
                           grm_precise: bool, transport: str,
                           lowering: str = "reference"):
    """The tile2d update as an explicit shard_map, for all transports.

    Relying on jit + sharding annotations here lets XLA's SPMD
    partitioner pick pathological lowerings (observed on the CPU mesh):
    for the replicated layout it re-shards the indicator intermediates
    and all-gathers them back; for the variant-sharded layout it
    computes PARTIAL tiles per variant shard and all-REDUCES them —
    tile_area x 4 B x n_pieces of ICI traffic per block (11.6 GB at the
    76k config-4 shape) instead of the one (N, v) block gather (~80 MB
    packed) the design intends. shard_map makes the choreography
    explicit:

    - ``transport="gather"`` (variant-sharded blocks, bulk reassembly):
      one ``all_gather`` of the (packed) block over the flattened mesh
      — the hot loop's ONLY collective, gathered in the 2-bit domain
      when the stream is packed so it costs n*v/4 bytes, but run
      serially in front of every contraction;
    - ``transport="ring"`` (variant-sharded blocks, overlapped
      reassembly): D - 1 ``ppermute`` hops around the flattened device
      ring (meshes.ring_perm), each device contracting the shard it
      holds while the next one rotates in — the hop is issued BEFORE
      the contraction so XLA's scheduler hides it behind the matmuls;
      shards stay packed on the wire. Per-shard partial products are
      added in ring order: int32 sums are exact under reordering
      (count family bit-identical to gather); grm's f32 agrees to
      float tolerance, and its per-variant standardization statistics
      are per-COLUMN — each device holds all N sample rows of its
      current shard — so they are identical math either way;
    - ``transport="none"`` (replicated/staged blocks): no collective
      at all.

    Either way each device slices its row/col sample ranges out of the
    (full or per-shard) block and contracts them locally with
    :func:`genotype.tile_products`. Compile-checked by
    tests/test_parallel.py.
    """
    import jax.numpy as jnp  # noqa: F401 (kernel tile bodies expect jnp up)
    from jax.sharding import PartitionSpec as P

    from spark_examples_tpu.ops import genotype

    mesh = plan.mesh
    n_i, n_j = mesh.devices.shape
    n_dev = n_i * n_j
    kern = kernels.get(metric)
    acc_specs = {
        k: (P() if k in kern.scalar_leaves
            else P(meshes.AXIS_I, meshes.AXIS_J))
        for k in kern.acc_leaves
    }
    block_spec = (
        P() if transport == "none"
        else P(None, (meshes.AXIS_I, meshes.AXIS_J))
    )

    def unpack(chunk):
        if packed:
            from spark_examples_tpu.ingest.bitpack import unpack_dosages

            return unpack_dosages(chunk)
        return chunk

    def contract(acc, chunk, i, j, tn, tm):
        """Fold one RAW (packed or dense) full-or-shard chunk into the
        tile accumulators — shared by every transport; the chunk's
        variant width is whatever the transport delivers.

        Count-family kernels slice their row/col sample ranges BEFORE
        unpacking (the sample axis is axis 0 of the packed byte layout
        too, so slice-then-unpack is bit-identical to
        unpack-then-slice): per device that is (tn + tm) x v of 2-bit
        expansion instead of n x v — the full-block unpack was
        replicated VPU work on every device. Under the fused lowering
        the slices stay packed BYTES all the way into the Pallas body
        (decode + mask + contract in one VMEM pass) — same tiles, same
        int32 sums, bit-identical by the parity suites. Float-family
        kernels (GRM) need whole-chunk per-variant statistics and keep
        the full unpack."""
        if kern.family == "float":
            return kern.tile_body(acc, unpack(chunk), i, j, tn, tm,
                                  grm_precise)
        rows = jax.lax.dynamic_slice_in_dim(chunk, i * tn, tn, axis=0)
        cols = jax.lax.dynamic_slice_in_dim(chunk, j * tm, tm, axis=0)
        if lowering == "fused":
            prods = kern.fused_body(rows, cols)
        else:
            prods = genotype.tile_products(unpack(rows), unpack(cols),
                                           tuple(acc_specs))
        return {k: acc[k] + prods[k] for k in acc_specs}

    def body(acc, block):
        i = jax.lax.axis_index(meshes.AXIS_I)
        j = jax.lax.axis_index(meshes.AXIS_J)
        n = block.shape[0]
        check_tile_divisible(n, mesh)  # trace-time; shapes are concrete
        tn, tm = n // n_i, n // n_j
        if transport == "ring":
            # The overlapped schedule: contract the shard in hand while
            # the next rotates in. The hop is issued FIRST so the
            # collective-permute can ride behind the contraction's
            # matmuls (latency-hiding scheduler on real chips; on the
            # CPU mesh the schedule is still bit-identical, just
            # unoverlapped). Shards hop in their transport dtype —
            # 2-bit packed bytes when the stream is packed.
            perm = meshes.ring_perm(mesh)
            shard = block
            for s in range(n_dev):
                nxt = (
                    jax.lax.ppermute(
                        shard, (meshes.AXIS_I, meshes.AXIS_J), perm)
                    if s < n_dev - 1 else None
                )
                acc = contract(acc, shard, i, j, tn, tm)
                shard = nxt
            return acc
        if transport == "gather":
            # One explicit gather of the variant shards (i major, j
            # minor — the same order P(None, ("i", "j")) split them).
            block = jax.lax.all_gather(
                block, (meshes.AXIS_I, meshes.AXIS_J), axis=1, tiled=True
            )
        return contract(acc, block, i, j, tn, tm)

    return meshes.shard_map(
        body, mesh=mesh, in_specs=(acc_specs, block_spec),
        out_specs=acc_specs, check_vma=False,
    )


@lru_cache(maxsize=64)
def _jitted_update(plan: GramPlan, metric: str, packed: bool,
                   grm_precise: bool = False, block_layout: str = "sharded",
                   transport: str = "gather", lowering: str = "reference"):
    """One jit wrapper per (plan, metric, packed, grm_precise, layout,
    transport, lowering) — re-entering the same job shape reuses the
    compiled executable instead of re-tracing (a fresh ``jax.jit``
    object owns a fresh compilation cache). The donated accumulator
    aliases cleanly in every variant here (same leaf dtypes/shardings
    in and out); the N x N stages whose outputs CANNOT alias their
    inputs live in parallel/pcoa_sharded.py, which donates only the
    alias-able leaves (tests/test_parallel.py asserts the whole sharded
    route compiles with no unusable-donation warnings)."""
    acc_sh = _acc_shardings(plan, metric)
    if plan.mode == "tile2d" and plan.mesh.devices.size > 1:
        sm_transport = (
            "none" if block_layout == "replicated" else transport
        )
        return jax.jit(
            _tile2d_shard_map_impl(plan, metric, packed, grm_precise,
                                   transport=sm_transport,
                                   lowering=lowering),
            in_shardings=(
                acc_sh,
                meshes.replicated(plan.mesh)
                if block_layout == "replicated"
                else plan.block_sharding,
            ),
            out_shardings=acc_sh,
            donate_argnums=(0,),
        )
    block_sh = (
        meshes.replicated(plan.mesh) if block_layout == "replicated"
        else plan.block_sharding
    )
    return jax.jit(
        gram_ops.impl_for(metric, packed, grm_precise, lowering=lowering),
        in_shardings=(acc_sh, block_sh),
        out_shardings=acc_sh,
        donate_argnums=(0,),
    )


# Nominal accelerator compute-rate : ICI-rate ratio (FLOPs per byte) the
# auto transport choice assumes: one ring step pays ~hop_bytes /
# ICI-rate of (hidden) transfer against flops_step / MXU-rate of
# contraction; the hop only disappears behind the matmuls when
# flops_step / hop_bytes clears this ratio. ~512 matches a v5e-class
# chip (~2e14 int8 FLOP/s against ~4e11 B/s of per-link ICI); the exact
# value only moves the crossover shape, and both transports are always
# forcible (--tile2d-transport gather|ring).
RING_FLOP_PER_BYTE = 512.0


def resolve_transport(plan: GramPlan, metric: str, n_samples: int,
                      block_variants: int, packed: bool) -> str:
    """The ``auto`` tile2d transport choice, from the kernel's own FLOPs
    model: ring when one ring step's tile contraction outweighs one
    shard hop at the nominal :data:`RING_FLOP_PER_BYTE` rate ratio (the
    hop then hides behind the MXU), gather otherwise (tiny tiles — D
    small hops cost more latency than one bulk collective). Non-tile2d
    plans and single-device meshes have no transport choice at all."""
    if plan.mode != "tile2d" or plan.mesh.devices.size <= 1:
        return "gather"
    n_dev = plan.mesh.devices.size
    kern = kernels.get(metric)
    # Per-device, per-ring-step contraction: the block's total matmul
    # FLOPs spread over n_dev tiles and n_dev shards.
    flops_step = kern.flops(n_samples, block_variants) / (n_dev * n_dev)
    hop_bytes = n_samples * block_variants / n_dev / (4 if packed else 1)
    return "ring" if flops_step >= RING_FLOP_PER_BYTE * hop_bytes \
        else "gather"


def check_ring_divisible(block_width: int, plan: GramPlan,
                         packed: bool) -> None:
    """Ring transport needs the shard count to divide the block's
    variant width (each device must hold an equal shard to rotate).
    The streamed feeds guarantee this by padding (pad_multiple =
    plan.block_shards), so this names the flags for DIRECT callers —
    instead of the raw shard_map sharding error that otherwise
    surfaces deep inside tracing."""
    n_dev = plan.mesh.devices.size
    if n_dev > 1 and block_width % n_dev:
        unit = "packed bytes" if packed else "variants"
        raise ValueError(
            f"--tile2d-transport ring cannot rotate a block of "
            f"{block_width} {unit} over the {n_dev}-device mesh: the "
            f"shard count must divide the block's variant width "
            f"({block_width} % {n_dev} = {block_width % n_dev}). Fix: "
            f"pick --block-variants a multiple of "
            f"{n_dev * (4 if packed else 1)} (the streamed feeds pad to "
            "this grid automatically; direct update calls must pad "
            "their own blocks — prefetch.pad_block/pad_packed)"
        )


def make_update(plan: GramPlan, metric: str, packed: bool = False,
                grm_precise: bool = False, block_layout: str = "sharded",
                transport: str = "gather", lowering: str = "reference"):
    """Jitted ``(acc, block) -> acc`` with the plan's shardings pinned.

    The computation is byte-identical to the single-chip path. Variant
    mode follows the mesh/annotate/let-XLA-insert recipe (the psum over
    variant shards is exactly the collective wanted there); tile2d mode
    is an explicit shard_map (:func:`_tile2d_shard_map_impl`) because
    the SPMD partitioner, left to its own choice, picked pathological
    collective patterns for it (see that function's docstring).

    ``packed``: blocks arrive 2-bit packed ((N, v_blk/4) uint8,
    ingest/bitpack.py) and are unpacked per-shard on device — in variant
    mode the packed byte axis is what gets sharded, so each chip unpacks
    only its own quarter-width slice.

    ``block_layout``: how blocks reach the update. ``"sharded"`` (the
    host-streamed transport) shards the variant axis across the mesh —
    tile2d mode then reassembles it over ICI inside the update per
    ``transport``. ``"replicated"`` declares the block already fully
    present on every device (staged/on-device generation): tile2d chips
    then slice their operands locally and the hot loop compiles with NO
    collectives (compile-checked by tests/test_parallel.py). Only
    meaningful for tile2d; variant mode's psum is its compute, not its
    transport, so replicated blocks are rejected there rather than
    silently computing the whole N x N redundantly on every chip.

    ``transport``: the tile2d sharded-layout reassembly — ``"gather"``
    (one bulk all_gather in front of the contraction), ``"ring"`` (a
    ppermute ring schedule hiding each hop behind the previous shard's
    contraction; bit-identical for int32-accumulating kernels, allclose
    for grm), or ``"auto"`` (:func:`resolve_transport` per block shape).
    Ignored outside tile2d sharded layouts.

    ``lowering``: the RESOLVED count-family contraction lowering
    (gram_ops.resolve_gram_lowering) — "fused" feeds the packed
    row/col tile slices straight into the kernel's registered Pallas
    body on every transport; "reference" keeps the pinned
    unpack-then-matmul XLA path. Bit-identical either way (int32).
    """
    if block_layout not in ("sharded", "replicated"):
        raise ValueError(f"unknown block_layout {block_layout!r}")
    if transport not in TILE2D_TRANSPORTS:
        raise ValueError(
            f"unknown tile2d transport {transport!r}; valid: "
            f"{' | '.join(TILE2D_TRANSPORTS)}"
        )
    if lowering not in ("reference", "fused"):
        raise ValueError(
            f"unresolved gram lowering {lowering!r}: make_update takes "
            "the RESOLVED choice (reference | fused) — callers resolve "
            "auto via gram.resolve_gram_lowering"
        )
    if lowering == "fused":
        kernels.check_fused_lowering(metric, packed)
        if plan.mode == "variant" and plan.mesh.devices.size > 1:
            raise ValueError(
                "--gram-lowering fused runs the Pallas tile kernel per "
                "device inside the tile2d shard_map; a multi-device "
                "variant-mode plan partitions ONE jitted update across "
                "chips, which cannot split a pallas_call — use "
                "--gram-mode tile2d (or a single-device mesh), or "
                "--gram-lowering auto|reference"
            )
    if block_layout == "replicated" and plan.mode == "variant":
        raise ValueError(
            "block_layout='replicated' under a variant-mode plan would "
            "make every chip compute the full N x N product redundantly "
            "— use the sharded transport (or a tile2d plan)"
        )
    ring = (
        transport == "ring" and block_layout == "sharded"
        and plan.mode == "tile2d" and plan.mesh.devices.size > 1
    )
    if transport == "auto":
        # Direct make_update callers resolve per actual block width at
        # call time via the runner; a bare "auto" here means the caller
        # did not resolve — fall back to the gather transport, which
        # every block shape supports.
        transport = "gather"
        ring = False
    jitted = _jitted_update(plan, metric, packed, grm_precise, block_layout,
                            "ring" if ring else "gather", lowering)
    fused = lowering == "fused"
    n_shards = plan.block_shards
    n_dev = plan.mesh.devices.size
    if block_layout == "replicated":
        want_sharding = meshes.replicated(plan.mesh)

        def update_replicated(acc, block):
            if not (
                isinstance(block, jax.Array)
                and block.sharding == want_sharding
            ):
                block = jax.device_put(np.asarray(block), want_sharding)
            if fused:
                telemetry.count("gram.fused_blocks", 1)
            return jitted(acc, block)

        return update_replicated

    def update(acc, block):
        if not (isinstance(block, jax.Array) and block.sharding == plan.block_sharding):
            block = np.asarray(block)
            if block.shape[1] % n_shards:
                # Pad the variant axis to shardable width — a missing call
                # (or a byte of four missing codes) contributes zero to
                # every gram piece, so this is semantically free (same
                # trick as prefetch.pad_block).
                from spark_examples_tpu.ingest.prefetch import (
                    pad_block, pad_packed,
                )

                width = -(-block.shape[1] // n_shards) * n_shards
                block = (
                    pad_packed(block, width) if packed
                    else pad_block(block, width)
                )
        if ring:
            # Caught BEFORE tracing, with the flags named (the satellite
            # contract): a pre-sharded jax.Array skipped the pad above.
            check_ring_divisible(block.shape[1], plan, packed)
            telemetry.count("gram.ring_steps", n_dev)
        if fused:
            telemetry.count("gram.fused_blocks", 1)
        if not isinstance(block, jax.Array) or (
                block.sharding != plan.block_sharding):
            block = jax.device_put(block, plan.block_sharding)
        return jitted(acc, block)

    return update


def make_gather_probe(plan: GramPlan, n_samples: int, width: int,
                      packed: bool = False):
    """A jitted program running ONLY the tile2d gather transport's bulk
    block ``all_gather`` (no contraction): ``probe(block) -> gathered``
    for a variant-sharded ``(n_samples, width)`` block. Timing it at the
    job's block cadence is the measured gather-wait the ring transport
    exists to hide — the numerator of ``gram.overlap_frac`` and the
    ``gram.gather_wait_s`` histogram the multi-chip bench exports
    (bench.py --multichip)."""
    from jax.sharding import PartitionSpec as P

    def body(block):
        return jax.lax.all_gather(
            block, (meshes.AXIS_I, meshes.AXIS_J), axis=1, tiled=True
        )

    sm = meshes.shard_map(
        body, mesh=plan.mesh,
        in_specs=(P(None, (meshes.AXIS_I, meshes.AXIS_J)),),
        out_specs=P(), check_vma=False,
    )
    return jax.jit(
        sm,
        in_shardings=(plan.block_sharding,),
        out_shardings=meshes.replicated(plan.mesh),
    )

"""Mesh-sharded Gram accumulation: the Spark shuffle, as XLA collectives.

The reference's only distribution strategy was data parallelism over the
variant axis — RDD partitions by genomic range, pair counts merged by a
netty-shuffle ``reduceByKey`` (SURVEY.md §2.2). Its TPU-native successor
is sharding annotations on the *same* jitted computation
(:func:`spark_examples_tpu.ops.gram.update`):

- **variant mode** (N x N fits per chip): the genotype block is sharded
  along the variant axis over every chip in the mesh, the accumulator is
  replicated. XLA's SPMD partitioner turns the indicator matmuls into
  local dots over each chip's variant shard plus one ``psum`` over ICI —
  exactly the "jax.distributed all-gather/all-reduce assembling the full
  N x N Gram on-device" the north star prescribes (BASELINE.json:5).
- **tile2d mode** (the 76k-exome regime, BASELINE.md config 4): the
  accumulator is tiled (rows over mesh axis i, cols over j) so each chip
  holds an (N/p_i, N/p_j) tile; blocks arrive variant-sharded (each chip
  is fed 1/n_dev of the block over the host link) and XLA all-gathers
  the block over ICI before each chip contracts its row-slice against
  its col-slice — host→device traffic per chip drops by n_dev, and the
  gather rides ICI, which is orders of magnitude faster than the host
  link. This is also exactly the transport the multi-host path needs:
  each process feeds only its own variant slice
  (parallel/multihost.py).
- **replicated mode**: single-chip degenerate case (mesh (1,1)).

Mode choice is automatic from accumulator-memory footprint unless forced.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu.core import meshes
from spark_examples_tpu.ops import gram as gram_ops

# Rough per-chip HBM budget for resident accumulators (bytes).
_ACC_BUDGET = 8 * 2**30


@dataclass(frozen=True)
class GramPlan:
    mesh: Mesh
    mode: str  # replicated | variant | tile2d

    @property
    def acc_sharding(self) -> NamedSharding:
        if self.mode == "tile2d":
            return meshes.tile2d(self.mesh)
        return meshes.replicated(self.mesh)

    @property
    def scalar_sharding(self) -> NamedSharding:
        return meshes.replicated(self.mesh)

    @property
    def block_sharding(self) -> NamedSharding:
        # Both multi-device modes transport blocks variant-sharded: in
        # variant mode that IS the compute layout (local dot + psum); in
        # tile2d mode XLA all-gathers the shards over ICI inside the
        # update — either way each chip's host link carries 1/n_dev of
        # every block, and each *process* can feed only its own slice.
        if self.mode in ("variant", "tile2d"):
            return meshes.variants_flat(self.mesh)
        return meshes.replicated(self.mesh)

    @property
    def block_shards(self) -> int:
        """How many ways the variant axis of a block is split."""
        return self.mesh.devices.size if self.mode != "replicated" else 1


def plan_for(
    mesh: Mesh, n_samples: int, metric: str, mode: str = "auto"
) -> GramPlan:
    """Pick a distribution mode (or validate a forced one)."""
    if mode == "auto":
        n_dev = mesh.devices.size
        n_acc = max(len(gram_ops.PIECES_FOR_METRIC.get(metric, ("zz",))), 1)
        acc_bytes = 4 * n_samples * n_samples * n_acc
        if n_dev == 1:
            mode = "replicated"
        elif acc_bytes <= _ACC_BUDGET:
            mode = "variant"
        else:
            mode = "tile2d"
    if mode not in ("replicated", "variant", "tile2d"):
        raise ValueError(f"unknown gram mode {mode!r}")
    return GramPlan(mesh, mode)


def _acc_shardings(plan: GramPlan, metric: str):
    """Per-leaf shardings for the accumulator pytree (GRM has a scalar)."""
    if metric == "grm":
        return {"zz": plan.acc_sharding, "nvar": plan.scalar_sharding}
    pieces = gram_ops.PIECES_FOR_METRIC[metric]
    return {k: plan.acc_sharding for k in pieces}


def init_sharded(plan: GramPlan, n: int, metric: str):
    """Zero accumulators laid out per the plan."""
    shardings = _acc_shardings(plan, metric)
    acc = gram_ops.init(n, metric)
    return {k: jax.device_put(v, shardings[k]) for k, v in acc.items()}


@lru_cache(maxsize=64)
def _jitted_update(plan: GramPlan, metric: str, packed: bool,
                   grm_precise: bool = False):
    """One jit wrapper per (plan, metric, packed, grm_precise) —
    re-entering the same job shape reuses the compiled executable instead
    of re-tracing (a fresh ``jax.jit`` object owns a fresh compilation
    cache)."""
    acc_sh = _acc_shardings(plan, metric)
    return jax.jit(
        gram_ops.impl_for(metric, packed, grm_precise),
        in_shardings=(acc_sh, plan.block_sharding),
        out_shardings=acc_sh,
        donate_argnums=(0,),
    )


def make_update(plan: GramPlan, metric: str, packed: bool = False,
                grm_precise: bool = False):
    """Jitted ``(acc, block) -> acc`` with the plan's shardings pinned.

    The computation is byte-identical to the single-chip path; only the
    sharding annotations differ. XLA SPMD inserts the psum (variant mode)
    or slices the dots (tile2d) — no hand-written collectives, per the
    mesh/annotate/let-XLA-insert recipe.

    ``packed``: blocks arrive 2-bit packed ((N, v_blk/4) uint8,
    ingest/bitpack.py) and are unpacked per-shard on device — in variant
    mode the packed byte axis is what gets sharded, so each chip unpacks
    only its own quarter-width slice.
    """
    jitted = _jitted_update(plan, metric, packed, grm_precise)
    n_shards = plan.block_shards

    def update(acc, block):
        if not (isinstance(block, jax.Array) and block.sharding == plan.block_sharding):
            block = np.asarray(block)
            if block.shape[1] % n_shards:
                # Pad the variant axis to shardable width — a missing call
                # (or a byte of four missing codes) contributes zero to
                # every gram piece, so this is semantically free (same
                # trick as prefetch.pad_block).
                from spark_examples_tpu.ingest.prefetch import (
                    pad_block, pad_packed,
                )

                width = -(-block.shape[1] // n_shards) * n_shards
                block = (
                    pad_packed(block, width) if packed
                    else pad_block(block, width)
                )
            block = jax.device_put(block, plan.block_sharding)
        return jitted(acc, block)

    return update

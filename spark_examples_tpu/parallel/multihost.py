"""Multi-host job surface: per-process ingest feeding a process-spanning mesh.

The reference's executors each read *their own* RDD partition's genomic
range and merged pair counts through the shuffle (SURVEY.md §2.2, §3.5).
The TPU-native successor, completing the DCN story SURVEY §5 names
("`jax.distributed` init plus host-side ingest feeding"):

- **partition the reading** — every process builds a source over only its
  share of the input: genomic-range partitions (``partition_ranges``) for
  file sources driven by ``--references``, block-aligned variant windows
  (:class:`~spark_examples_tpu.ingest.source.WindowSource`) for sources
  with cheap random access (synthetic, memmapped packed/array stores);
- **assemble blocks without replication** — each process feeds its local
  slab into :func:`jax.make_array_from_process_local_data` under the
  plan's variant-sharded block transport, so no process ever
  materializes another process's variants (the global block exists only
  as its per-device shards);
- **agree on the step count** — the gram update is one SPMD program per
  block; every process must execute it the same number of times. Range
  partitions are only approximately equal, so exhausted processes feed
  all-MISSING slabs (semantically zero for every gram piece) until the
  last straggler drains. The agreement itself is amortized (VERDICT r4
  weak #6 — the naive protocol was one synchronous allgather per block,
  ~10k DCN control-plane round-trips at the 40M-variant scale): sources
  that know their length a priori (``exact_n_variants`` — synthetic,
  memmapped packed/array stores, the WindowSource partitions
  ``build_source`` makes from them) agree on the global step count in
  ONE upfront allgather, stream with zero mid-stream control traffic,
  and close with ONE terminal agreement round (every process allgathers
  an ok flag, so a broken exact_n_variants claim aborts every process
  within one consensus period instead of hanging peers until a
  distributed timeout); unknown-length sources (VCF ranges, filtered
  streams) fall
  back to one "anyone still has data?" consensus per
  ``consensus_every`` blocks, padding stragglers within each group.

The accumulation itself is unchanged — the same jitted update with the
same shardings (parallel/gram_sharded.py); XLA's collectives simply span
processes once the mesh does.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import multihost_utils

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.dtypes import GENOTYPE_DTYPE, MISSING
from spark_examples_tpu.ingest.prefetch import (
    PACKED_MISSING,
    padded_width,
    stream_host_blocks,
)


def is_multihost() -> bool:
    return jax.process_count() > 1


def allgather(value) -> np.ndarray:
    """Gather one small host value from every process -> (P, ...) array.

    Thin wrapper over ``multihost_utils.process_allgather`` so call sites
    stay grep-able; used for step-count consensus, global variant totals,
    and stream-stat merges — control-plane traffic, never genotype data.
    """
    return np.asarray(multihost_utils.process_allgather(np.asarray(value)))


def allreduce_sum(x: np.ndarray) -> np.ndarray:
    """Sum one per-process host array across processes ON DEVICE (one
    XLA all-reduce riding DCN) and return the summed host value.

    The data-plane companion to :func:`allgather`, whose contract is
    small control-plane values only: gathering a (A, N_ref) statistic
    matrix would materialize P copies on every host and move P times
    the bytes, where the reduce moves one array's worth per link and
    peaks at one extra copy. Requires identical shape/dtype on every
    process; integer dtypes keep integer (wraparound) semantics, so
    callers own the same overflow budget as any int32 accumulation.
    """
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    x = np.asarray(x)
    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, d)
    mesh = Mesh(
        np.asarray([by_proc[p] for p in sorted(by_proc)]), ("p",)
    )
    sharding = NamedSharding(mesh, P("p"))
    g = jax.make_array_from_process_local_data(sharding, x[None])
    out = jax.jit(
        lambda t: t.sum(axis=0),
        out_shardings=NamedSharding(mesh, P()),
    )(g)
    return np.asarray(out.addressable_data(0))


def fetch_replicated(x):
    """``np.asarray`` that tolerates process-spanning arrays.

    A replicated global array is not "fully addressable" from any one
    process, so ``np.asarray`` on it raises — but every addressable
    shard holds the complete value. Tile-sharded matrices must go
    through the sharded solve instead of ever being fetched whole, and
    feeding one here raises rather than silently returning a single
    tile as if it were the full matrix.
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        local = x.addressable_data(0)
        if local.shape != x.shape:
            raise ValueError(
                f"fetch_replicated got a {x.shape} array whose local "
                f"shard is only {local.shape} — a sharded (not "
                "replicated) layout; route it through the sharded "
                "solve / per-shard IO instead of fetching it whole"
            )
        return np.asarray(local)
    return np.asarray(x)


def _exact_local_steps(source, block_variants: int,
                       start_variant: int) -> int:
    """Blocks this process will stream, or -1 when the source cannot say
    without streaming (VCF range shares, filtered/LD-pruned streams)."""
    if not getattr(source, "exact_n_variants", False):
        return -1
    remaining = max(0, source.n_variants - start_variant)
    return -(-remaining // block_variants)


def stream_global_blocks(
    source,
    block_variants: int,
    start_variant: int,
    plan,
    pack: bool,
    stats: dict | None = None,
    prefetch: int = 2,
    consensus_every: int = 8,
):
    """Yield ``(global_block, local_meta | None)`` across all processes.

    ``source`` is this process's partition (window or range share). Each
    yielded global block is variant-sharded per ``plan.block_sharding``;
    its global width is ``P * padded_local_width``, of which this
    process materialized only its own slab. ``local_meta`` is None on
    steps where this process had no data left (its slab was all-MISSING
    padding).

    The slab itself is shard-aware for store-backed partitions: the
    producer decodes the window's variants straight into the slab in
    one native call (``stream_host_blocks`` ``direct`` drive →
    ``decode_range_into``), so a host never decodes chunks its devices
    do not consume and never slices/pads after decode — aggregate
    ingest scales with host count. Per-slab bytes are exported as the
    ``multihost.shard_feed_bytes`` counter, and assembly runs one block
    ahead of the yield so the next block's per-shard device transfer
    overlaps the current update (the tile2d ring schedule's host-side
    double buffer).

    Control-plane cost: ONE upfront step-count allgather plus ONE
    terminal contract-agreement round when every process's source knows
    its length (``exact_n_variants``), else one
    has-data consensus per ``consensus_every`` blocks (stragglers pad
    out each group; worst case wastes ``consensus_every - 1``
    all-padding steps at the tail — semantically zero, each costing one
    block update). ``stats`` (when given) records the number of
    control-plane round-trips under ``"consensus_rounds"``.

    Every process MUST drain this iterator to the end — breaking out
    early desynchronizes the consensus allgather across processes.
    """
    n_proc = jax.process_count()
    n_dev = plan.mesh.devices.size
    if n_dev % n_proc:
        raise ValueError(
            f"mesh of {n_dev} devices not divisible into {n_proc} "
            "processes"
        )
    n_local_dev = n_dev // n_proc
    w_local = padded_width(block_variants, n_local_dev, pack)
    n = source.n_samples
    if pack:
        missing_slab = np.full((n, w_local), PACKED_MISSING, np.uint8)
    else:
        missing_slab = np.full((n, w_local), MISSING, GENOTYPE_DTYPE)
    sharding = plan.block_sharding

    def gather_round(value) -> np.ndarray:
        if stats is not None:
            stats["consensus_rounds"] = stats.get("consensus_rounds", 0) + 1
        # Chaos site: a "delay" fault here is a straggling control plane
        # — the collective must absorb it, not deadlock or reorder.
        # (Fired OUTSIDE the span: an injected local delay is this
        # rank's own lateness, while the span measures time spent
        # WAITING IN the collective for peers — the per-rank wait skew
        # is the straggler metric, visible on the ranks that did NOT
        # straggle.)
        faults.fire("multihost.consensus")
        with telemetry.span("multihost.consensus", cat="multihost"):
            return allgather(value)

    def assemble(item):
        slab, meta = item if item is not None else (missing_slab, None)
        if slab.shape[1] != w_local:  # defensive: all slabs must agree
            raise AssertionError(
                f"local slab width {slab.shape[1]} != agreed {w_local}"
            )
        if meta is not None:
            # Aggregate-ingest accounting: bytes THIS process fed into
            # the mesh (its own shard only — padding slabs feed no
            # data). Summed across hosts this is the scales-with-host-
            # count ingest number the shard-aware feed buys.
            telemetry.count("multihost.shard_feed_bytes", slab.nbytes)
        gblock = jax.make_array_from_process_local_data(sharding, slab)
        return gblock, meta

    it = stream_host_blocks(
        source, block_variants, start_variant, prefetch=prefetch,
        pad_multiple=n_local_dev, pack=pack, stats=stats,
    )
    try:
        local_steps = _exact_local_steps(source, block_variants,
                                         start_variant)
        gathered = gather_round(np.int64(local_steps))
        if (gathered >= 0).all():
            # Every process pre-counted: one agreed total, zero further
            # control traffic. Assembly runs ONE block ahead of the
            # yield: block k+1's per-shard H2D transfer (the
            # make_array placement) is dispatched while the consumer's
            # update still runs on block k — the double-buffered feed
            # that keeps the ring schedule's devices fed from the host
            # side. Cursor/checkpoint semantics are untouched (the
            # consumer sees the same blocks in the same order; only
            # production runs ahead).
            produced = 0
            pending = None
            for _ in range(int(gathered.max())):
                item = next(it, None)
                produced += item is not None
                assembled = assemble(item)
                if pending is not None:
                    yield pending
                pending = assembled
            if pending is not None:
                yield pending
            # Contract watchdog: every process joins ONE final agreement
            # round on its own ok flag, so a broken exact_n_variants
            # claim aborts ALL processes within this consensus period —
            # a process-local raise would leave peers parked inside the
            # next collective until a distributed timeout (they cannot
            # learn the stream ended early any other way).
            ok = produced == local_steps and next(it, None) is None
            oks = gather_round(np.int32(ok))
            if not oks.all():
                bad = [int(i) for i in np.flatnonzero(oks == 0)]
                raise RuntimeError(
                    f"process(es) {bad} streamed a different block count "
                    "than their claimed exact_n_variants (this process: "
                    f"{'ok' if ok else f'{produced} blocks against claimed {local_steps}'}) "
                    "— the source's contract is broken; fix the source "
                    "(trusting the claim would silently corrupt the "
                    "global accumulation). All processes abort together "
                    "in this agreement round."
                )
            return
        # Unknown-length fallback (some process reported -1): one
        # has-data consensus per group of consensus_every blocks;
        # stragglers pad out each group with missing slabs.
        pending = next(it, None)
        while bool(gather_round(np.int32(pending is not None)).any()):
            for _ in range(max(1, consensus_every)):
                item = pending
                pending = next(it, None) if item is not None else None
                yield assemble(item)
    finally:
        it.close()  # stop the producer thread on any exit path

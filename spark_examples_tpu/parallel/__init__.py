from spark_examples_tpu.parallel import gram_sharded  # noqa: F401
from spark_examples_tpu.parallel.gram_sharded import (  # noqa: F401
    GramPlan,
    init_sharded,
    make_update,
    plan_for,
)

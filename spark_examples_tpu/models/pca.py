"""PCA on the similarity matrix — the flagship ``VariantsPcaDriver`` math.

Reference (SURVEY.md §3.1): N x N shared-alt similarity -> center by
row/col/grand means -> MLlib ``RowMatrix.computePrincipalComponents(k)``
-> project rows -> per-sample PC coordinates.

For a *symmetric* centered matrix C, MLlib's route (eigenvectors v of the
column covariance C^T C / n, then projection C v) is algebraically the
spectral route used here: eigenvectors of C^T C = C^2 are eigenvectors of
C ordered by |lambda|, and the projection is C v = lambda v. So the TPU
path runs one symmetric eigh of C (ordered by |lambda|) and scales
eigenvectors by their eigenvalues — identical output (up to per-component
sign, the usual PCA ambiguity) at half the work; the CPU oracle implements
MLlib's covariance route literally and the parity test pins the
equivalence.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from spark_examples_tpu.ops.centering import center_matrix


@dataclass
class PCAResult:
    coords: jnp.ndarray  # (N, k) projections onto top components
    eigenvalues: jnp.ndarray  # (k,) matrix eigenvalues, by descending |.|
    # Accuracy-ladder rung that produced the eigenpairs (see
    # models/pcoa.PCoAResult.solver) — "exact" for this dense route.
    solver: str = "exact"


@partial(jax.jit, static_argnames=("k",))
def _fit(similarity, k):
    c = center_matrix(similarity)
    c = 0.5 * (c + c.T)  # guard symmetry against accumulation round-off
    vals, vecs = jnp.linalg.eigh(c)
    order = jnp.argsort(-jnp.abs(vals))[:k]
    vals_k = vals[order]
    vecs_k = vecs[:, order]
    coords = vecs_k * vals_k[None, :]  # projection C v = lambda v
    return coords, vals_k


def fit_pca(similarity: jnp.ndarray, k: int = 10) -> PCAResult:
    coords, vals = _fit(similarity, k)
    return PCAResult(coords, vals)

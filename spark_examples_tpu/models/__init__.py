from spark_examples_tpu.models import pca, pcoa  # noqa: F401

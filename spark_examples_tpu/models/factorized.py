"""Factorized sketch-rung model artifact: servable without any N x N.

The exact routes persist projection models whose centering statistics
come from the materialized dense matrix — which is exactly what the
sketch rungs (PR 7/11) never build, so the cohorts that NEED the
sketch were the ones that could not be served (ROADMAP item 1). This
module closes that gap with the randomized-factorization discipline of
arXiv:2110.03423 / arXiv:1612.08709: persist the rank-k basis, the
eigenvalues, and the *streamed* centering statistics the solver now
folds into its variant pass (solvers/sketch.py ``cm`` leaf), and
project queries against the basis only — an (A, k) product, never an
(A, N) times (N, N) chain.

Two families, one ``kind="factorized"`` archive:

- ``family="pca"`` — pca-family factor metrics (shared-alt): the model
  stores V, lambda, and the similarity column/grand means finalized
  from the streamed column mass; projection reuses the exact route's
  ``_project_pca`` centering formula bit for bit.
- ``family="pcoa"`` — ratio (dual-sketch) metrics on the corrected
  rung: the model additionally stores the denominator's exact rank-1
  scale diagonal ``a`` and its floor, so a query row's scaled
  similarity ``NUM_qj / (a_q a_j)`` (self-similarity pinned at 1)
  Gower-centers with the stored column/grand means and projects as
  ``(b @ V) / sqrt(lambda)``.

The fingerprint (:meth:`FactorizedModel.digest`) carries the solver
rung, sketch rank, and probe seed alongside the arrays — the accuracy
ladder's honesty contract: two fits differing only in rung can never
share a serving result-cache namespace.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

from spark_examples_tpu import kernels
from spark_examples_tpu.pipelines.project import (
    SCHEMA_VERSION,
    ModelFormatError,
)

FAMILIES = ("pca", "pcoa")

# Required archive members (beyond schema_version); the pcoa family
# additionally persists the denominator scale diagonal and its floor.
_REQUIRED = ("kind", "family", "metric", "eigvecs", "eigvals",
             "colmean", "grand", "sample_ids", "solver", "rank", "seed")
_REQUIRED_PCOA = ("scale", "scale_floor")


@dataclass(frozen=True)
class FactorizedModel:
    """A loaded, validated factorized model — everything the factorized
    projection paths (pipelines/project.py, serve/engine.py) need.
    Arrays are float64 exactly as persisted; consumers cast to f32 at
    the device boundary, matching the dense ProjectionModel contract.
    """

    kind: str      # always "factorized"
    family: str    # "pca" | "pcoa" (which projection formula applies)
    metric: str
    eigvecs: np.ndarray   # (N, k) basis
    eigvals: np.ndarray   # (k,)
    colmean: np.ndarray   # (N,) streamed centering column means
    grand: float
    sample_ids: list[str]
    solver: str    # accuracy-ladder rung that fitted the basis
    rank: int      # sketch rank (probe columns)
    seed: int      # probe RNG seed
    scale: np.ndarray | None = None  # (N,) denominator diag a; pcoa only
    scale_floor: float = 0.0
    schema_version: int = SCHEMA_VERSION

    @property
    def n_ref(self) -> int:
        return int(self.eigvecs.shape[0])

    @property
    def n_components(self) -> int:
        return int(self.eigvecs.shape[1])

    def digest(self) -> str:
        """Content fingerprint namespacing the serving result cache.
        Unlike the dense model's digest, the RUNG PROVENANCE (solver/
        rank/seed) is part of the hash: a corrected-rung refit at a
        different rank is a different model even when the arrays
        happen to collide at this precision."""
        h = hashlib.sha256()
        h.update(
            f"{self.kind}:{self.family}:{self.metric}:{self.solver}:"
            f"{self.rank}:{self.seed}:{self.schema_version}".encode()
        )
        for a in (self.eigvecs, self.eigvals, self.colmean):
            h.update(np.ascontiguousarray(a).tobytes())
        h.update(np.float64(self.grand).tobytes())
        if self.scale is not None:
            h.update(np.ascontiguousarray(self.scale).tobytes())
            h.update(np.float64(self.scale_floor).tobytes())
        return h.hexdigest()[:16]


def save_factorized_model(
    path: str,
    *,
    family: str,
    metric: str,
    eigenvectors: np.ndarray,
    eigenvalues: np.ndarray,
    colmean: np.ndarray,
    grand: float,
    sample_ids: list[str],
    solver: str,
    rank: int,
    seed: int,
    scale: np.ndarray | None = None,
    scale_floor: float = 0.0,
) -> None:
    """Persist a sketch-rung fit as a factorized model.

    ``eigenvectors`` is the RAW basis V (the sketch drivers hold it
    directly — no coords/lambda recovery division). Components are
    dropped by the same keep rules the dense savers apply: pca keeps
    ``|lambda| > 1e-12``, pcoa keeps ``lambda > 0`` (negative-inertia
    axes carry no metric information and sqrt(lambda) is undefined).
    """
    if family not in FAMILIES:
        raise ValueError(
            f"factorized model family must be one of {FAMILIES}, "
            f"got {family!r}"
        )
    vals = np.asarray(eigenvalues, np.float64)
    vecs = np.asarray(eigenvectors, np.float64)
    keep = (np.abs(vals) > 1e-12) if family == "pca" else (vals > 0)
    payload = dict(
        schema_version=np.int64(SCHEMA_VERSION),
        kind=np.asarray("factorized"),
        family=np.asarray(family),
        metric=np.asarray(metric),
        eigvecs=vecs[:, keep],
        eigvals=vals[keep],
        colmean=np.asarray(colmean, np.float64),
        grand=np.float64(grand),
        sample_ids=np.asarray(sample_ids),
        solver=np.asarray(solver),
        rank=np.int64(rank),
        seed=np.int64(seed),
    )
    if family == "pcoa":
        if scale is None:
            raise ValueError(
                "a pcoa-family factorized model needs the denominator "
                "scale diagonal (scale=) — the fit's state['scale']"
            )
        payload["scale"] = np.asarray(scale, np.float64)
        payload["scale_floor"] = np.float64(scale_floor)
    np.savez(path, **payload)


def parse_factorized(mdl, path: str, version: int) -> FactorizedModel:
    """Decode an open ``kind="factorized"`` npz into a validated
    :class:`FactorizedModel` — called by ``project.load_model``'s kind
    dispatch with the archive already open and schema-gated, so only
    the factorized-specific rungs of the error ladder live here."""
    names = set(mdl.files)
    family = str(mdl["family"]) if "family" in names else None
    if family is not None and family not in FAMILIES:
        raise ModelFormatError(
            f"model file {path!r} has unknown factorized family "
            f"{family!r} (supported: {FAMILIES})"
        )
    required = _REQUIRED + (_REQUIRED_PCOA if family == "pcoa" else ())
    missing = [k for k in required if k not in names]
    if missing:
        raise ModelFormatError(
            f"model file {path!r} (kind='factorized', schema_version "
            f"{version}) is missing required field(s) {missing} — "
            "truncated save or a file from an incompatible build; "
            "refit with --save-model on the sketch ladder"
        )
    pcoa = family == "pcoa"
    return FactorizedModel(
        kind="factorized",
        family=family,
        metric=str(mdl["metric"]),
        eigvecs=np.asarray(mdl["eigvecs"], np.float64),
        eigvals=np.asarray(mdl["eigvals"], np.float64),
        colmean=np.asarray(mdl["colmean"], np.float64),
        grand=float(mdl["grand"]),
        sample_ids=[str(s) for s in mdl["sample_ids"]],
        solver=str(mdl["solver"]),
        rank=int(mdl["rank"]),
        seed=int(mdl["seed"]),
        scale=np.asarray(mdl["scale"], np.float64) if pcoa else None,
        scale_floor=float(mdl["scale_floor"]) if pcoa else 0.0,
        schema_version=version,
    )


def check_factorized_projectable(model: FactorizedModel) -> tuple[str, ...]:
    """The factorized half of ``project.check_projectable``: which
    cross statistics to stream for this model, or a ValueError naming
    why it cannot project. Registry-derived, like the dense table."""
    kern = kernels.maybe_get(model.metric)
    if model.family == "pca":
        spec = kern.sketch if kern is not None else None
        if not (isinstance(spec, kernels.FactorSketch) and spec.pca_family):
            raise ValueError(
                f"factorized pca model of metric {model.metric!r} is "
                "not projectable: the metric is not a pca-family "
                "factor kernel"
            )
        # The similarity cross statistic — same row as the dense
        # PROJECTABLE table's ("pca", "shared-alt") entry.
        return ("s",)
    if (kern is None or kern.cross is None or kern.cross.num is None):
        raise ValueError(
            f"factorized pcoa model of metric {model.metric!r} is not "
            "projectable: the metric declares no cross numerator "
            f"(savable sketch metrics: "
            f"{' | '.join(kernels.factorized_savable_names())})"
        )
    return kern.cross.stats

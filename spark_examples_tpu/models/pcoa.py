"""PCoA (classical MDS) — the Stanford fork's PCoA entrypoint math.

Reference (SURVEY.md §3.3): load distance matrix -> D^2 -> double-center
(-1/2 J D^2 J) -> symmetric eig -> coords_i = eigvec_i * sqrt(lambda_i).
Negative eigenvalues (non-Euclidean distances like Bray-Curtis produce
them) are clamped to zero coordinates, matching scikit-bio's classical
PCoA behaviour so the CPU oracle pins the same convention.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from spark_examples_tpu.core.config import (
    EIGH_ITERS_DEFAULT,
    EIGH_OVERSAMPLE_DEFAULT,
)
from spark_examples_tpu.ops.centering import gower_center
from spark_examples_tpu.ops.eigh import (
    coords_from_eigpairs,
    randomized_eigh,
    top_k_eigh,
)


@dataclass
class PCoAResult:
    coords: jnp.ndarray  # (N, k) principal coordinates
    eigenvalues: jnp.ndarray  # (k,) descending
    proportion_explained: jnp.ndarray  # (k,) fraction of positive inertia
    # Which accuracy-ladder rung produced the eigenpairs (core.config
    # SOLVER_LADDER): "exact" for the dense/randomized routes in this
    # module and parallel/pcoa_sharded; the streaming sketch solver
    # (spark_examples_tpu/solvers) stamps its own rung. Recorded into
    # the model artifact and telemetry by the job layer.
    solver: str = "exact"


@partial(jax.jit, static_argnames=("k", "method", "iters", "oversample"))
def _fit(distance, k, method, key, iters, oversample):
    b = gower_center(distance)
    trace = jnp.trace(b)  # total inertia = sum of all eigenvalues
    if method == "dense":
        vals, vecs = top_k_eigh(b, k)
    else:
        vals, vecs = randomized_eigh(b, k, key, oversample=oversample,
                                     iters=iters)
    coords = coords_from_eigpairs(vals, vecs)
    prop = jnp.maximum(vals, 0.0) / jnp.maximum(trace, 1e-30)
    return coords, vals, prop


def fit_pcoa(
    distance: jnp.ndarray,
    k: int = 10,
    method: str = "dense",
    key: jax.Array | None = None,
    iters: int = EIGH_ITERS_DEFAULT,
    oversample: int = EIGH_OVERSAMPLE_DEFAULT,
) -> PCoAResult:
    """PCoA on an (N, N) distance matrix. ``method``: dense | randomized
    (``iters``/``oversample`` tune the randomized solver — the
    ``--eigh-iters``/``--eigh-oversample`` CLI knobs; ignored by
    dense)."""
    if key is None:
        key = jax.random.key(0)
    if method == "dense":
        # The knobs don't reach the dense solver, but as static jit args
        # distinct values would still retrace/recompile the full N x N
        # eigh program for a bit-identical result — normalize them.
        iters, oversample = 0, 0
    coords, vals, prop = _fit(distance, k, method, key, iters, oversample)
    return PCoAResult(coords, vals, prop)

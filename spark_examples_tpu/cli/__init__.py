from spark_examples_tpu.cli.main import main  # noqa: F401

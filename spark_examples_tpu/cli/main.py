"""CLI — the ``spark-submit --class ...Driver`` surface, as subcommands.

Flag semantics mirror the reference's Scallop confs (SURVEY.md §5
"Config / flag system"): ``--references chr:start:end``, ``--output-path``,
block/partition sizing, plus the mandated backend gate
``--backend={cpu-reference|jax-tpu}`` (BASELINE.json:5 prescribes
``{spark-mllib|jax-tpu}``; the CPU oracle stands in for MLlib here).

    python -m spark_examples_tpu similarity --metric ibs --output-path m.tsv
    python -m spark_examples_tpu pcoa --num-pc 10 --output-path coords.tsv
    python -m spark_examples_tpu pca  --output-path coords.tsv
    python -m spark_examples_tpu search-variants --positions 16050075
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from spark_examples_tpu.version import __version__  # noqa: F401 - CLI flag
from spark_examples_tpu import kernels
from spark_examples_tpu.core import config
from spark_examples_tpu.core.config import (
    ComputeConfig,
    IngestConfig,
    JobConfig,
    ReferenceRange,
)


_SOURCES = ("synthetic", "vcf", "packed", "plink", "parquet", "store")


def _source_arg(value: str) -> str:
    """A source name, or the one-flag store form ``store:<dir>`` — an
    argparse ``type`` instead of ``choices`` so the parameterized form
    validates without enumerating every possible directory. The ':'
    spelling is the STORE's only (other sources take --path), and an
    empty dir is rejected here so both mistakes die as usage errors,
    not mid-job tracebacks."""
    base, sep, rest = value.partition(":")
    if base not in _SOURCES or (sep and base != "store"):
        raise argparse.ArgumentTypeError(
            f"invalid source {value!r} (choose from "
            f"{', '.join(_SOURCES)}, or store:<dir>; other sources "
            "take --path)"
        )
    if sep and not rest:
        raise argparse.ArgumentTypeError(
            "bad source 'store:': expected store:<dir> (the compacted "
            "store directory)"
        )
    return value


def _needs_ref_path(args) -> bool:
    """Whether --ref-path is still required: synthetic generates its
    panel and store:<dir> carries the path in the source spec."""
    return (not args.ref_path and args.ref_source != "synthetic"
            and not args.ref_source.startswith("store:"))


def _add_common(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("ingest")
    g.add_argument("--source", default="synthetic", type=_source_arg,
                   metavar="{" + ",".join(_SOURCES) + "}",
                   help="genotype source; 'store' is the content-"
                   "addressed dataset store (compact one with the "
                   "`ingest` subcommand), also spellable store:<dir>")
    g.add_argument("--path", default=None,
                   help="input for vcf (.vcf/.vcf.gz), packed (store "
                   "dir), plink (fileset prefix or .bed path), "
                   "parquet (.parquet variant table), or store "
                   "(compacted store dir) sources")
    g.add_argument("--references", nargs="*", default=[],
                   metavar="CONTIG:START:END",
                   help="genomic ranges to ingest (VCF region filter)")
    g.add_argument("--n-samples", type=int, default=2504)
    g.add_argument("--n-variants", type=int, default=100_000)
    g.add_argument("--n-populations", type=int, default=5)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--block-variants", type=int, default=8192,
                   help="variants per streamed block (the partition size)")
    g.add_argument("--splits-per-contig", type=int, default=1,
                   help="split each --references range into N sub-ranges "
                   "read concurrently (the reference partitioner's "
                   "FixedContigSplits); 1 disables")
    g.add_argument("--ingest-workers", type=int, default=4,
                   help="host-side ingest parallelism: concurrent range "
                   "readers for --splits-per-contig AND parse/pack/"
                   "hash/write workers for `ingest` compaction "
                   "(ordered reassembly keeps the output bit-identical "
                   "to 1 worker; see README 'Performance tuning')")
    g.add_argument("--maf", type=float, default=0.0,
                   help="drop variants with minor-allele frequency below "
                   "this (QC stream filter)")
    g.add_argument("--max-missing", type=float, default=1.0,
                   help="drop variants with missing-call rate above this")
    g.add_argument("--ld-prune-r2", type=float, default=0.0,
                   help="LD-prune: drop variants whose within-window r^2 "
                   "against a kept variant exceeds this (0 = off; the "
                   "PLINK --indep-pairwise analogue)")
    g.add_argument("--ld-window", type=int, default=256,
                   help="LD pruning window (variant count)")
    g.add_argument("--ld-carry", type=int, default=0,
                   help="kept variants carried across window boundaries "
                   "(0 = auto: window/4)")
    g.add_argument("--prefetch-blocks", type=int, default=2,
                   help="host->device pipeline depth (blocks queued "
                   "while earlier transfers drain; minimum 1 — the "
                   "stream cannot run unbuffered)")
    g.add_argument("--io-retries", type=int, default=3,
                   help="transient-IO retries per incident (consecutive "
                   "failures without a successfully read block) for "
                   "file-backed sources: a failed block read re-opens "
                   "the source and seeks back to the cursor (0 "
                   "disables; corrupt blocks always fail fast)")
    g.add_argument("--io-retry-backoff", type=float, default=0.05,
                   help="initial retry backoff in seconds (exponential "
                   "with jitter)")
    g.add_argument("--store-cache-mb", type=int, default=256,
                   help="host-RAM budget of the dataset store's decode "
                   "cache (dense chunk decodes, LRU with hit/miss "
                   "accounting; 0 disables — see README 'Dataset "
                   "store')")
    g.add_argument("--readahead-chunks", type=int, default=2,
                   help="dataset-store readahead depth FLOOR: chunks "
                   "decoded + digest-verified AHEAD of the streaming "
                   "cursor by a background pool into the decode cache, "
                   "so the store-cold tier runs at store-hit "
                   "throughput (0 disables; see README 'Performance "
                   "tuning')")
    g.add_argument("--readahead-chunks-max", type=int, default=16,
                   help="cadence-adaptive readahead ceiling: the pool "
                   "deepens from --readahead-chunks toward this when "
                   "the measured consumer cadence outruns the "
                   "per-chunk decode latency, and shrinks back when it "
                   "does not (live depth = the store.readahead.depth "
                   "gauge; 0 pins the depth at the floor)")
    g.add_argument("--store-codec", default="zlib",
                   choices=list(config.STORE_CODEC_SPECS),
                   help="chunk payload codec for `ingest` compactions: "
                   "raw = uncompressed 2-bit payload, zlib = per-chunk "
                   "deflate (deterministic, ~several-fold smaller on "
                   "real genotypes), zlib-dict = deflate with a "
                   "per-contig dictionary trained during compaction "
                   "(helps small chunks); reads auto-detect per chunk "
                   "from the manifest")
    g.add_argument("--store-replicas", nargs="*", default=[],
                   metavar="DIR",
                   help="peer store directories holding content-"
                   "addressed copies of the chunks: a chunk that fails "
                   "its digest verify is healed in place from a "
                   "replica (else from the manifest's recorded origin) "
                   "instead of failing the run (see README 'Failure "
                   "modes & recovery')")
    s = p.add_argument_group("supervision")
    s.add_argument("--supervise", action="store_true",
                   help="run this job as a supervised, crash-resumable "
                   "unit of work: a child process streams under a "
                   "heartbeat watchdog, and a crash, kill, hang, or "
                   "stall restarts it from the latest sha256-verified "
                   "checkpoint (pair with --checkpoint-dir/"
                   "--checkpoint-every-blocks so restarts resume "
                   "instead of recomputing)")
    s.add_argument("--supervise-max-restarts", type=int, default=3,
                   help="restarts before the supervisor gives up and "
                   "exits with the last failure")
    s.add_argument("--supervise-stall-timeout", type=float, default=60.0,
                   help="seconds of frozen progress (heartbeats alive, "
                   "no forward motion) before the watchdog kills and "
                   "restarts; the effective budget never drops below "
                   "50 block-periods of the job's own reported block "
                   "p95")
    c = p.add_argument_group("compute")
    # Enum choices come from the config-time validators (core/config
    # enum tuples) — one source of truth, so argparse and validation
    # can never drift (graftlint: registry-literal).
    c.add_argument("--backend", default="jax-tpu",
                   choices=list(config.BACKENDS))
    # Choices come from the kernel registry (jax-free import) — adding
    # a kernel registration makes it CLI-reachable with no edit here.
    c.add_argument("--metric", default="ibs",
                   choices=list(kernels.names()))
    c.add_argument("--num-pc", type=int, default=10)
    c.add_argument("--mesh-shape", default=None,
                   help="IxJ, e.g. 2x4 (default: auto-factor devices)")
    c.add_argument("--gram-mode", default="auto",
                   choices=list(config.GRAM_MODES))
    c.add_argument("--tile2d-transport", default="auto",
                   choices=list(config.TILE2D_TRANSPORTS),
                   help="tile2d block reassembly over ICI: 'gather' = "
                   "one bulk all_gather serially before each "
                   "contraction; 'ring' = ppermute ring schedule "
                   "hiding each shard hop behind the previous shard's "
                   "contraction (bit-identical for count kernels); "
                   "'auto' = ring when the kernel's FLOPs model says "
                   "the contraction outweighs the hop (see README "
                   "'Multi-chip execution')")
    c.add_argument("--eigh-mode", default="auto",
                   choices=list(config.EIGH_MODES))
    c.add_argument("--eigh-iters", type=int,
                   default=config.EIGH_ITERS_DEFAULT,
                   help="randomized solver power iterations (default "
                   "meets the documented accuracy contract; see "
                   "BASELINE.md)")
    c.add_argument("--eigh-oversample", type=int,
                   default=config.EIGH_OVERSAMPLE_DEFAULT,
                   help="randomized solver subspace oversample (k+p "
                   "probe columns)")
    c.add_argument("--solver", default="exact",
                   choices=list(config.SOLVER_LADDER),
                   help="pcoa/pca eigensolve accuracy ladder: 'exact' "
                   "materializes the N x N Gram (today's route); "
                   "'sketch' folds a low-rank range sketch into (N, "
                   "rank) state during the single variant pass and "
                   "solves from the Nystrom core — no N x N anywhere, "
                   "the route for cohorts past single-chip HBM; "
                   "'corrected' adds --sketch-iters extra streamed "
                   "power-iteration passes before a Rayleigh solve "
                   "(see README 'Solvers & the accuracy ladder')")
    c.add_argument("--sketch-rank", type=int,
                   default=config.SKETCH_RANK_DEFAULT,
                   help="sketch probe columns (>= --num-pc; clamped to "
                   "N): the r of the O(N*r) solver state; components "
                   "+ ~32-54 oversample is the usual shape")
    c.add_argument("--sketch-iters", type=int,
                   default=config.SKETCH_ITERS_DEFAULT,
                   help="extra streamed passes of the corrected rung "
                   "(each one full pass over the cohort; error "
                   "contracts ~(lambda_{r+1}/lambda_k)^2 per pass)")
    c.add_argument("--sketch-seed", type=int, default=0,
                   help="probe RNG seed — a resumed/supervised job "
                   "must keep it (the checkpoint records it and "
                   "rejects a mismatch)")
    c.add_argument("--gram-lowering", default="auto",
                   choices=list(config.GRAM_LOWERINGS),
                   help="count-family contraction lowering: 'reference' "
                   "= the pinned unpack-then-matmul XLA path; 'fused' = "
                   "the packed Pallas kernel (decode + mask + contract "
                   "in one VMEM pass — bit-identical, interpreted "
                   "off-TPU); 'auto' = fused on TPU for fused-capable "
                   "kernels on a packed stream, reference elsewhere")
    c.add_argument("--braycurtis-method", default="auto",
                   choices=list(config.BRAYCURTIS_METHODS),
                   help="braycurtis lowering: auto (pallas on an "
                   "accelerator, exact on CPU), elementwise VPU path, "
                   "threshold-decomposed MXU matmuls (quantised), or the "
                   "fused-VMEM Pallas kernel (interpreted on CPU)")
    c.add_argument("--braycurtis-levels", type=int, default=256)
    c.add_argument("--grm-precise", action="store_true",
                   help="accumulate the GRM's Z Z^T in f32 instead of "
                   "bf16 (half MXU rate, ~1e-3 better accuracy)")
    c.add_argument(
        "--checkpoint-dir", default=None,
        help="directory for partial-Gram checkpoint/resume; multi-host "
        "jobs REQUIRE this to be on a filesystem shared by every "
        "process (each process writes its own tiles; process 0 "
        "rotates after a barrier)",
    )
    c.add_argument("--checkpoint-every-blocks", type=int, default=0)
    p.add_argument("--output-path", default=None)
    p.add_argument("--timings", action="store_true",
                   help="print per-phase timing JSON to stderr")
    p.add_argument("--telemetry-dir", default=None,
                   help="export structured telemetry: per-rank Chrome "
                   "trace events (rank<k>/trace.jsonl, loadable in "
                   "Perfetto / chrome://tracing), a metrics registry "
                   "dump (rank<k>/metrics.json: counters, gauges, "
                   "p50/p95/p99 histograms, derived throughputs), and "
                   "a merged summary table on rank 0 (see README "
                   "'Observability')")
    p.add_argument("--trace-events", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="buffer per-block span events into "
                   "trace.jsonl (--no-trace-events keeps the "
                   "metrics.json export but skips the event timeline "
                   "for very long streams)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   metavar="RATE",
                   help="request-trace sampling rate in [0, 1]: a "
                   "served request's trace.* spans and slowest-K "
                   "exemplar entry are kept iff its trace_id samples "
                   "in (deterministic on the id, so every hedge leg "
                   "and replica subprocess makes the SAME keep/drop "
                   "decision); 1 keeps everything, 0 disables request "
                   "tracing")
    p.add_argument("--telemetry-flush-s", type=float, default=0.0,
                   metavar="SECONDS",
                   help="publish live telemetry snapshots every this "
                   "many seconds (atomic metrics.json + rolling "
                   "live_trace.jsonl under --telemetry-dir) so the "
                   "running job is observable without killing it; "
                   "0 = export at exit only")
    p.add_argument("--live-port", type=int, default=None, metavar="PORT",
                   help="bind a live-introspection HTTP sidecar on "
                   "this port (0 = ephemeral): GET /metrics "
                   "(Prometheus text), /debug/telemetry (full live "
                   "snapshot JSON), /healthz — works mid-run for "
                   "batch jobs (gram/sketch/ingest); under "
                   "--supervise the parent proxies it so the "
                   "endpoint stays up across child restarts")
    p.add_argument("--trace-dir", default=None,
                   help="capture a jax.profiler trace of the job into this "
                   "directory (view with tensorboard's profile plugin)")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans: fail loudly at the first "
                   "NaN-producing op instead of emitting NaN coordinates "
                   "(numeric sanitizer, SURVEY.md §5; slows compute)")


def _job_from_args(args) -> JobConfig:
    mesh_shape = None
    if args.mesh_shape:
        i, j = args.mesh_shape.lower().split("x")
        mesh_shape = (int(i), int(j))
    return JobConfig(
        telemetry=config.TelemetryConfig(
            dir=args.telemetry_dir,
            trace_events=args.trace_events,
            flush_s=args.telemetry_flush_s,
            live_port=args.live_port,
            trace_sample=args.trace_sample,
        ),
        ingest=IngestConfig(
            source=args.source,
            path=args.path,
            references=[ReferenceRange.parse(r) for r in args.references],
            n_samples=args.n_samples,
            n_variants=args.n_variants,
            n_populations=args.n_populations,
            block_variants=args.block_variants,
            seed=args.seed,
            splits_per_contig=args.splits_per_contig,
            ingest_workers=args.ingest_workers,
            store_replicas=list(args.store_replicas),
            maf=args.maf,
            max_missing=args.max_missing,
            ld_r2=args.ld_prune_r2,
            ld_window=args.ld_window,
            ld_carry=args.ld_carry,
            prefetch_blocks=args.prefetch_blocks,
            io_retries=args.io_retries,
            io_retry_backoff_s=args.io_retry_backoff,
            store_cache_mb=args.store_cache_mb,
            readahead_chunks=args.readahead_chunks,
            readahead_chunks_max=args.readahead_chunks_max,
            store_codec=args.store_codec,
        ),
        compute=ComputeConfig(
            backend=args.backend,
            metric=args.metric,
            num_pc=args.num_pc,
            mesh_shape=mesh_shape,
            gram_mode=args.gram_mode,
            tile2d_transport=args.tile2d_transport,
            gram_lowering=args.gram_lowering,
            eigh_mode=args.eigh_mode,
            eigh_iters=args.eigh_iters,
            eigh_oversample=args.eigh_oversample,
            solver=args.solver,
            sketch_rank=args.sketch_rank,
            sketch_iters=args.sketch_iters,
            sketch_seed=args.sketch_seed,
            braycurtis_method=args.braycurtis_method,
            braycurtis_levels=args.braycurtis_levels,
            grm_precise=args.grm_precise,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every_blocks=args.checkpoint_every_blocks,
            neighbors_output=getattr(
                args, "neighbors_output",
                ComputeConfig.neighbors_output),
            neighbors_k=getattr(args, "neighbors_k",
                                ComputeConfig.neighbors_k),
            minhash_hashes=getattr(args, "minhash_hashes",
                                   ComputeConfig.minhash_hashes),
            minhash_bands=getattr(args, "minhash_bands",
                                  ComputeConfig.minhash_bands),
            minhash_seed=getattr(args, "minhash_seed",
                                 ComputeConfig.minhash_seed),
            minhash_bucket_cap=getattr(args, "minhash_bucket_cap",
                                       ComputeConfig.minhash_bucket_cap),
        ),
        output_path=args.output_path,
        model_path=getattr(args, "save_model", None),
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="spark_examples_tpu",
        description="TPU-native population-genomics pipelines "
        "(similarity / PCoA / PCA / search)",
    )
    parser.add_argument("--version", action="version",
                        version=f"%(prog)s {__version__}")
    sub = parser.add_subparsers(dest="command", required=True)

    p_sim = sub.add_parser("similarity", help="pairwise similarity matrix")
    _add_common(p_sim)

    p_pcoa = sub.add_parser("pcoa", help="principal coordinates analysis")
    _add_common(p_pcoa)
    p_pcoa.add_argument("--matrix-path", default=None,
                        help="consume a persisted similarity/distance matrix")
    p_pcoa.add_argument("--matrix-kind", default="auto",
                        choices=["auto", "distance", "similarity"],
                        help="what the persisted matrix holds (auto: trust "
                        "the file's sidecar, else assume distance)")
    p_pcoa.add_argument("--stream-refresh-blocks", type=int, default=0,
                        help="streaming mode: emit coordinate snapshots "
                        "every N blocks via warm rank-k subspace "
                        "refreshes (incremental PCoA)")
    p_pcoa.add_argument("--save-model", default=None,
                        help="persist the fitted embedding (.npz) so "
                        "`project` can later place new samples into "
                        "this coordinate space")

    p_pca = sub.add_parser("pca", help="flagship variants-PCA driver")
    _add_common(p_pca)
    p_pca.add_argument("--save-model", default=None,
                       help="persist the fitted PCA embedding (.npz) so "
                       "`project` can later place new samples into this "
                       "coordinate space")
    # The PCA driver is defined on the shared-alt similarity (the
    # reference's VariantsPcaDriver counting); any other --metric would
    # be silently ignored, so reject it instead.
    p_pca.set_defaults(metric="shared-alt")

    p_sv = sub.add_parser("search-variants",
                          help="genotype histograms at positions")
    _add_common(p_sv)
    p_sv.add_argument("--positions", nargs="*", type=int, default=None)

    p_ss = sub.add_parser("sample-stats",
                          help="per-sample QC: call rate and "
                          "heterozygosity over one streaming pass")
    _add_common(p_ss)

    p_proj = sub.add_parser(
        "project",
        help="place NEW samples into a fitted reference PCoA space "
        "(out-of-sample Nystrom extension; fit with pcoa --save-model)",
    )
    _add_common(p_proj)  # --source/--path describe the NEW cohort
    p_proj.add_argument("--model", required=True,
                        help=".npz from pcoa --save-model")
    p_proj.add_argument("--ref-source", default="plink",
                        type=_source_arg,
                        metavar="{" + ",".join(_SOURCES) + "}",
                        help="reference cohort genotypes (the panel the "
                        "model was fitted on); store:<dir> works here "
                        "too")
    p_proj.add_argument("--ref-path", default=None)

    p_nb = sub.add_parser(
        "neighbors",
        help="sparse top-k nearest neighbors. Default COHORT mode: "
        "MinHash signatures folded into one streamed variant pass, LSH "
        "banding proposes candidate pairs, ONLY those pairs are "
        "evaluated exactly through the metric's pairwise finalize, and "
        "the per-sample top-k (or the raw candidate edge list with "
        "--neighbors-output pairs) is written as a self-describing "
        "binary to --output-path. With --model: QUERY-VS-PANEL mode — "
        "rank each new sample's k nearest panel members by exact "
        "similarity, bit-identical to a fleet route's POST /neighbors "
        "(see README 'Top-k neighbors')",
    )
    _add_common(p_nb)
    # Choices come from the config enum tuple (the __post_init__
    # validator's source of truth), so argparse and config-time
    # validation can never drift (graftlint: registry-literal).
    p_nb.add_argument("--neighbors-output",
                      default=ComputeConfig.neighbors_output,
                      choices=list(config.NEIGHBORS_OUTPUTS),
                      help="'topk' = per-sample k best neighbors "
                      "(sparse, the default); 'pairs' = the evaluated "
                      "candidate edge list with exact similarities")
    p_nb.add_argument("--neighbors-k", type=int,
                      default=ComputeConfig.neighbors_k,
                      help="neighbors kept per sample (topk output)")
    p_nb.add_argument("--minhash-hashes", type=int,
                      default=ComputeConfig.minhash_hashes,
                      help="MinHash signature length (k seeded "
                      "permutations; must be a multiple of "
                      "--minhash-bands)")
    p_nb.add_argument("--minhash-bands", type=int,
                      default=ComputeConfig.minhash_bands,
                      help="LSH bands: more bands (fewer rows each) = "
                      "more candidates/higher recall; fewer bands = "
                      "stronger filtering")
    p_nb.add_argument("--minhash-seed", type=int,
                      default=ComputeConfig.minhash_seed,
                      help="permutation seed — a resumed/supervised "
                      "job must keep it (the checkpoint records it "
                      "and rejects a mismatch)")
    p_nb.add_argument("--minhash-bucket-cap", type=int,
                      default=ComputeConfig.minhash_bucket_cap,
                      help="max samples per band bucket; an over-cap "
                      "bucket keeps its first members and counts the "
                      "rest in neighbors.bucket_overflows (degenerate-"
                      "bucket quadratic blowup guard)")
    p_nb.add_argument("--model", default=None,
                      help=".npz from pcoa --save-model: switch to "
                      "query-vs-panel mode (--source/--path = the NEW "
                      "queries, --ref-source/--ref-path = the panel)")
    p_nb.add_argument("--ref-source", default="packed",
                      type=_source_arg,
                      metavar="{" + ",".join(_SOURCES) + "}",
                      help="reference panel genotypes (query-vs-panel "
                      "mode); store:<dir> works here too")
    p_nb.add_argument("--ref-path", default=None)

    p_srv = sub.add_parser(
        "serve",
        help="long-lived online projection server: model + reference "
        "panel staged device-resident once, queries answered through "
        "an async micro-batching queue (bit-identical to the offline "
        "`project` CLI); default mode binds a local HTTP endpoint, "
        "--loadgen N instead drives it with N closed-loop clients and "
        "prints the serving report. --fleet fleet.json switches to "
        "FLEET mode: many named (model, panel) routes in one process "
        "under an HBM-budgeted warm panel pool with LRU eviction and "
        "priority-class admission (see README 'Fleet serving')",
    )
    _add_common(p_srv)  # --source/--path describe the LOADGEN query pool
    p_srv.add_argument("--model", default=None,
                       help=".npz from pcoa/pca --save-model "
                       "(single-model mode; --fleet replaces it)")
    p_srv.add_argument("--fleet", default=None, metavar="MANIFEST",
                       help="fleet manifest JSON (route registry: "
                       "name -> model path + panel source); serves "
                       "every route from one process — POST /project "
                       "with a 'route' field, or /project/<route>")
    p_srv.add_argument("--fleet-budget-mb", type=float,
                       default=config.ServeConfig.fleet_budget_mb,
                       help="warm panel pool budget (fleet mode): "
                       "staged panels past it are LRU-evicted and "
                       "re-stage on demand through the store "
                       "(fleet.restage_total counts the cold starts); "
                       "a budget_mb in the manifest wins")
    p_srv.add_argument("--queue-interactive", type=int,
                       default=config.ServeConfig.queue_interactive,
                       help="interactive-class admission bound (fleet "
                       "mode): the protected class's shed threshold")
    p_srv.add_argument("--queue-batch", type=int,
                       default=config.ServeConfig.queue_batch,
                       help="batch-class admission bound (fleet mode): "
                       "backfill sheds here first under overload while "
                       "interactive keeps admitting")
    p_srv.add_argument("--deadline-interactive-ms", type=float,
                       default=config.ServeConfig.deadline_interactive_ms,
                       help="default deadline for interactive-class "
                       "requests (fleet mode; 0 = none)")
    p_srv.add_argument("--deadline-batch-ms", type=float,
                       default=config.ServeConfig.deadline_batch_ms,
                       help="default deadline for batch-class requests "
                       "(fleet mode; 0 = none)")
    p_srv.add_argument("--ref-source", default="packed",
                       type=_source_arg,
                       metavar="{" + ",".join(_SOURCES) + "}",
                       help="reference panel genotypes (the panel the "
                       "model was fitted on) — staged to device once; "
                       "store:<dir> works here too")
    p_srv.add_argument("--ref-path", default=None)
    p_srv.add_argument("--max-batch", type=int,
                       default=config.ServeConfig.max_batch,
                       help="micro-batch ceiling; batches are padded to "
                       "this so one compiled program serves every size")
    p_srv.add_argument("--max-linger-ms", type=float,
                       default=config.ServeConfig.max_linger_ms,
                       help="max wait past the first queued query while "
                       "coalescing a batch (the latency/throughput dial)")
    p_srv.add_argument("--max-queue", type=int,
                       default=config.ServeConfig.max_queue,
                       help="bounded admission queue; a full queue sheds "
                       "with an explicit ServerOverloaded (HTTP 429)")
    p_srv.add_argument("--cache-entries", type=int,
                       default=config.ServeConfig.cache_entries,
                       help="LRU result cache size, keyed by genotype "
                       "digest (0 disables)")
    p_srv.add_argument("--deadline-ms", type=float,
                       default=config.ServeConfig.deadline_ms,
                       help="default per-request deadline (0 = none); "
                       "expired requests answer DeadlineExceeded/504")
    p_srv.add_argument("--host", default=config.ServeConfig.host)
    p_srv.add_argument("--port", type=int, default=config.ServeConfig.port,
                       help="HTTP bind port (0 = ephemeral)")
    p_srv.add_argument("--loadgen", type=int, default=0, metavar="CLIENTS",
                       help="instead of serving HTTP, drive the server "
                       "with this many concurrent closed-loop clients "
                       "(queries from --source/--path) and print the "
                       "offered/sustained QPS + latency report as JSON")
    p_srv.add_argument("--loadgen-requests", type=int, default=50,
                       help="requests per loadgen client")
    p_srv.add_argument("--loadgen-seed", type=int,
                       default=config.ServeConfig.loadgen_seed,
                       help="seeds the loadgen hedge-delay ring and "
                       "burst schedule so SOAK-REPRO lines and bench "
                       "runs replay deterministically")
    p_srv.add_argument("--drain-timeout-s", type=float,
                       default=config.ServeConfig.drain_timeout_s,
                       help="SIGTERM drain budget: admitted requests "
                       "get this long to resolve; stragglers past it "
                       "fail loudly (ServerClosed) and are counted in "
                       "serve.drain_abandoned in the final telemetry "
                       "flush")
    p_srv.add_argument("--port-file", default=None, metavar="PATH",
                       help="after the HTTP endpoint binds, atomically "
                       "write {\"port\": N} here — how a controller "
                       "parent discovers an ephemeral (--port 0) "
                       "child's address")

    p_ck = sub.add_parser(
        "cross-kinship",
        help="KING-robust kinship BETWEEN two cohorts (same variant "
        "set): phi ~ 0.5 flags the same individual in both, ~0.25 "
        "first-degree relatives — the cross-dataset dedupe/QC screen",
    )
    _add_common(p_ck)  # --source/--path describe the NEW cohort
    p_ck.add_argument("--ref-source", default="plink",
                      type=_source_arg,
                      metavar="{" + ",".join(_SOURCES) + "}")
    p_ck.add_argument("--ref-path", default=None)
    p_ck.add_argument("--min-phi", type=float, default=0.177,
                      help="console report threshold (0.177 ~ the "
                      "KING 2nd-degree cutoff); the full matrix goes "
                      "to --output-path")

    p_pack = sub.add_parser(
        "pack",
        help="ETL: stream any source into the 2-bit packed store "
        "(parse once; later jobs read zero-copy packed bytes)",
    )
    _add_common(p_pack)

    p_ing = sub.add_parser(
        "ingest",
        help="compact any source ONCE into the content-addressed "
        "dataset store: 2-bit packed sha256-named chunk files + a JSON "
        "manifest (catalog: sample ids, contig/position index, "
        "per-chunk digests). Every later job reads it with "
        "--source store:<dir> — mmap zero-copy, range queries, "
        "verified reads",
    )
    _add_common(p_ing)
    p_ing.add_argument("--chunk-variants", type=int, default=16384,
                       help="catalog granularity: variants per chunk "
                       "file (the unit of range addressing, integrity "
                       "verification, and decode caching; must be a "
                       "multiple of 4)")

    p_store = sub.add_parser(
        "store",
        help="dataset-store maintenance. `store heal --path <dir>`: "
        "repair every quarantined chunk in place — a verified copy "
        "from a --replica dir, else a re-compaction of the chunk's "
        "origin span recorded in the manifest — re-verify against the "
        "content address, and clear the quarantine ledger entries that "
        "healed",
    )
    p_store.add_argument("verb", choices=["heal"],
                         help="maintenance action")
    p_store.add_argument("--path", required=True,
                         help="the store directory")
    p_store.add_argument("--replica", action="append", default=[],
                         metavar="DIR",
                         help="peer store directory to copy verified "
                         "chunks from (repeatable; tried before origin "
                         "re-compaction)")
    p_store.add_argument("--verify-all", action="store_true",
                         help="re-hash EVERY chunk against its content "
                         "address (not just the quarantine ledger) and "
                         "heal whatever fails")

    p_tel = sub.add_parser(
        "telemetry",
        help="telemetry maintenance. `telemetry stitch --path <dir>`: "
        "merge a job's per-attempt, per-rank exports "
        "(attempt<a>/rank<r>/trace.jsonl from supervised restarts, "
        "rank<r>/ otherwise) into ONE Perfetto-loadable session trace "
        "on a shared wall-clock timeline, with the supervisor's "
        "crash/hang/stall incidents as restart markers; add --fleet "
        "to treat <dir> as a fleet workdir (one track per replica "
        "slot, controller-ledger incidents as markers). `telemetry "
        "timeline --path <dir>`: render the fleet controller's "
        "timeline.jsonl ring (route p99 / queue depth / replica-count "
        "history with incident markers) as a JSON report + stderr "
        "table",
    )
    p_tel.add_argument("verb", choices=["stitch", "timeline"],
                       help="maintenance action")
    p_tel.add_argument("--path", required=True,
                       help="the --telemetry-dir of the job to stitch, "
                       "or (timeline / stitch --fleet) the fleet "
                       "workdir holding timeline.jsonl / per-slot "
                       "exports")
    p_tel.add_argument("--output", default=None,
                       help="stitched trace path (default: "
                       "<path>/stitched_trace.jsonl, or "
                       "<path>/stitched_fleet_trace.jsonl with --fleet)")
    p_tel.add_argument("--fleet", action="store_true",
                       help="stitch a fleet workdir: every replica "
                       "slot's attempt/rank exports on one Perfetto "
                       "timeline, one pid block per slot, controller "
                       "incidents (controller.json + rotated .old) as "
                       "global markers")
    p_tel.add_argument("--last", type=int, default=30, metavar="N",
                       help="timeline verb: rows rendered from the "
                       "tail of the ring (default 30)")

    p_lint = sub.add_parser(
        "lint",
        help="run graftlint, the AST-based invariant analyzer suite "
        "distilled from this repo's bug history (registry-literal "
        "drift, donation safety, blocking-under-lock, atomic-write "
        "discipline, jax-import purity, telemetry/fault-site names, "
        "thread hygiene) — exit 1 on findings; see README 'Static "
        "analysis'",
    )
    p_lint.add_argument("paths", nargs="*",
                        help="files/dirs (default: the production tree)")
    p_lint.add_argument("--rules", default=None, metavar="ID[,ID...]")
    p_lint.add_argument("--format", default="text",
                        choices=["text", "json"])
    p_lint.add_argument("--list-rules", action="store_true")

    p_cov = sub.add_parser("coverage",
                           help="per-base read coverage over ranges "
                           "(the SearchReads example tier)")
    p_cov.add_argument("--references", nargs="*", default=[],
                       metavar="CONTIG:START:END")
    p_cov.add_argument("--reads-source", default="synthetic",
                       choices=["synthetic", "sam"])
    p_cov.add_argument("--path", default=None, help="SAM file path")
    p_cov.add_argument("--reads-per-range", type=int, default=100_000)
    p_cov.add_argument("--read-length", type=int, default=150)
    p_cov.add_argument("--seed", type=int, default=0)
    p_cov.add_argument("--output-path", default=None,
                       help="write per-base depth TSV")

    args = parser.parse_args(argv)

    if args.command == "lint":
        # Thin wrapper over tools.graftlint — dispatched BEFORE any jax
        # import (the suite is contractually device-free, like the
        # supervised parent it lints).
        argv_lint = list(args.paths)
        if args.rules:
            argv_lint += ["--rules", args.rules]
        if args.list_rules:
            argv_lint += ["--list-rules"]
        argv_lint += ["--format", args.format]
        from tools.graftlint.__main__ import main as graftlint_main

        return graftlint_main(argv_lint)
    if args.command == "coverage":
        return _run_coverage(args)
    if args.command == "store":
        return _run_store_admin(args)
    if args.command == "telemetry":
        return _run_telemetry_admin(args)
    if getattr(args, "supervise", False):
        # The supervision layer: re-invoke this same command (flag
        # stripped) as a watched child and restart it on crash/hang/
        # stall — BEFORE any jax import, so the parent stays a light
        # watchdog that never holds a device. --live-port moves to the
        # parent: it proxies the children's ephemeral sidecars so the
        # scrape endpoint survives restarts; --telemetry-dir (kept on
        # the child) tells the parent where its incident ledger goes.
        from spark_examples_tpu.core.supervisor import supervise_cli

        # Same config-time knob validation the child will run — caught
        # HERE so a bad flag (e.g. --live-port 99999, which the PARENT
        # binds for its proxy) is a clean usage error, not a raw
        # OverflowError from the watchdog or a doomed restart loop.
        # Dataclass construction only: still no jax in the parent.
        try:
            _job_from_args(args)
        except ValueError as e:
            parser.error(str(e))

        return supervise_cli(
            list(argv) if argv is not None else sys.argv[1:],
            max_restarts=args.supervise_max_restarts,
            stall_timeout_s=args.supervise_stall_timeout,
            live_port=getattr(args, "live_port", None),
            telemetry_dir=getattr(args, "telemetry_dir", None),
        )
    if args.command == "pca" and args.metric != "shared-alt":
        parser.error(
            f"pca computes the shared-alt similarity by definition; "
            f"--metric {args.metric} is not accepted (use the similarity "
            "or pcoa subcommands for other metrics)"
        )
    if (getattr(args, "solver", "exact") != "exact"
            and args.command not in ("pcoa", "pca")):
        parser.error(
            f"--solver {args.solver} applies to the pcoa/pca eigensolve; "
            f"'{args.command}' does not solve an eigenproblem (and "
            "similarity's OUTPUT is the N x N matrix the sketch exists "
            "to avoid)"
        )

    try:
        job = _job_from_args(args)
    except ValueError as e:
        # Config-time knob validation (core/config.py) — surface it as
        # the usage error it is, flag names intact, not a traceback.
        parser.error(str(e))

    # Imports deferred so --help stays instant (no jax/TPU init).
    import os

    import jax

    # Persistent compile cache: first-run jit of the big kernels (eigh
    # especially) costs tens of seconds on TPU; cache across invocations.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "SPARK_EXAMPLES_TPU_CACHE",
            os.path.expanduser("~/.cache/spark_examples_tpu/jax"),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    if getattr(args, "debug_nans", False):
        jax.config.update("jax_debug_nans", True)

    import contextlib

    from spark_examples_tpu.core import profiling, telemetry
    from spark_examples_tpu.pipelines import jobs as J
    from spark_examples_tpu.pipelines.runner import build_source

    # --trace-dir wraps the whole job in a jax.profiler capture (the
    # Spark-web-UI replacement, SURVEY.md §5); exit stack so every
    # command path below stops the trace on its way out. --telemetry-dir
    # arms the structured-telemetry layer the same way: configured
    # before the job, exported on every exit path (including
    # BrokenPipeError) so a piped-and-truncated run still leaves its
    # trace behind.
    with contextlib.ExitStack() as stack:
        stack.enter_context(profiling.trace(getattr(args, "trace_dir", None)))
        # Supervised child? Start the heartbeat the parent watchdog
        # reads (no-op unless the env names a heartbeat path).
        from spark_examples_tpu.core.supervisor import maybe_start_heartbeat

        hb = maybe_start_heartbeat()
        if hb is not None:
            stack.callback(hb.stop)
        if job.telemetry.dir:
            telemetry.configure(dir=job.telemetry.dir,
                                trace_events=job.telemetry.trace_events,
                                flush_s=job.telemetry.flush_s,
                                trace_sample=job.telemetry.trace_sample)

            def _export_telemetry():
                d = telemetry.export()
                if d:
                    print(f"telemetry -> {d}", file=sys.stderr)

            stack.callback(_export_telemetry)
            # LIFO: the flusher stops (one final publish) BEFORE the
            # full export writes the definitive trace.jsonl.
            stack.callback(telemetry.stop_periodic_flush)
        else:
            telemetry.set_trace_sample(job.telemetry.trace_sample)
        # Live introspection sidecar: the --live-port flag, or the
        # environment when a supervisor parent armed this child with
        # an ephemeral port + port file for its proxy.
        from spark_examples_tpu.core.live import maybe_start_live

        live_server = maybe_start_live(port=job.telemetry.live_port)
        if live_server is not None:
            stack.callback(live_server.shutdown)
            if job.telemetry.live_port is not None:
                # Only the explicitly flagged sidecar announces itself:
                # an env-armed one (a supervised child) binds a private
                # ephemeral port that dies on the next restart — the
                # parent already printed ITS proxy endpoint, and naming
                # the child's here would steer the operator to the
                # wrong socket.
                print(
                    f"live telemetry on http://{live_server.host}:"
                    f"{live_server.port} (GET /metrics, "
                    "/debug/telemetry, /healthz)",
                    file=sys.stderr,
                )
        try:
            return _dispatch(args, parser, job, J, build_source)
        except BrokenPipeError:
            # Downstream closed early (`... | head`): normal for a CLI.
            # Point stdout at devnull so the interpreter's shutdown
            # flush doesn't raise a second time, and exit cleanly.
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, sys.stdout.fileno())
            return 0


_PREVIEW_ROWS = 50


def _emit_table(job, header: str, lines: list[str], noun: str,
                preview: list[str] | None = None) -> None:
    """Shared table-output protocol of the search/stats tiers: full TSV
    to ``--output-path`` (if set), up to ``_PREVIEW_ROWS`` console rows
    (``preview`` — a pretty per-row rendering — or the TSV itself with
    its header), and a '... N more' tail pointing at the file."""
    import os

    if job.output_path:
        os.makedirs(os.path.dirname(job.output_path) or ".", exist_ok=True)
        with open(job.output_path, "w") as f:
            f.write(header)
            f.writelines(lines)
    shown = preview if preview is not None else lines
    if preview is None:
        sys.stdout.write(header)
    sys.stdout.writelines(shown[:_PREVIEW_ROWS])
    if len(shown) > _PREVIEW_ROWS:
        tail = f"... {len(shown) - _PREVIEW_ROWS} more {noun}"
        if job.output_path:
            tail += f" (full table in {job.output_path})"
        print(tail)


def _dispatch(args, parser, job, J, build_source) -> int:
    if args.command == "similarity":
        res = J.similarity_matrix_job(job)
        print(
            f"similarity[{res.metric}] {res.similarity.shape[0]}x"
            f"{res.similarity.shape[1]} over {res.n_variants} variants"
            + (f" -> {job.output_path}" if job.output_path else "")
        )
        timer = res.timer
    elif args.command == "pcoa":
        refresh = getattr(args, "stream_refresh_blocks", 0)
        if refresh > 0:
            import dataclasses as _dc

            from spark_examples_tpu.pipelines.streaming import (
                incremental_pcoa_job,
            )

            if args.matrix_path:
                parser.error("--stream-refresh-blocks streams the cohort; "
                             "it cannot consume a persisted --matrix-path")
            if args.save_model:
                parser.error(
                    "--save-model is not supported by the streaming "
                    "route (it needs the final dense distance matrix "
                    "for the projection centering statistics) — fit "
                    "the model with a batch pcoa run"
                )
            job = job.replace(compute=_dc.replace(
                job.compute, stream_refresh_blocks=refresh))
            out, snapshots = incremental_pcoa_job(job)
            for s in snapshots:
                print(f"snapshot@{s.n_variants} variants: "
                      f"top eigenvalue {s.eigenvalues[0]:.6g}")
            _print_coords(out, job)
        else:
            out = J.pcoa_job(job, matrix_path=args.matrix_path,
                             matrix_kind=getattr(args, "matrix_kind", "auto"))
            _print_coords(out, job)
        timer = out.timer
    elif args.command == "pca":
        out = J.variants_pca_job(job)
        _print_coords(out, job)
        timer = out.timer
    elif args.command == "search-variants":
        from spark_examples_tpu.pipelines.examples import genotype_histogram

        src = build_source(job.ingest)
        positions = set(args.positions) if args.positions else None
        counts = genotype_histogram(src, job.ingest.block_variants, positions)
        _emit_table(
            job,
            header="contig\tposition\thom_ref\thet\thom_alt\tmissing\taf\n",
            lines=[
                f"{c.contig or '?'}\t{c.position}\t{c.hom_ref}\t"
                f"{c.het}\t{c.hom_alt}\t{c.missing}\t"
                f"{c.allele_freq:.6f}\n"
                for c in counts
            ],
            noun="variants",
            preview=[
                f"{c.contig or '?'}:{c.position}\t0/0={c.hom_ref}\t"
                f"0/1={c.het}\t1/1={c.hom_alt}\t./.={c.missing}\t"
                f"af={c.allele_freq:.4f}\n"
                for c in counts
            ],
        )
        return 0
    elif args.command == "sample-stats":
        from spark_examples_tpu.pipelines.examples import sample_stats

        stats = sample_stats(build_source(job.ingest),
                             job.ingest.block_variants)
        _emit_table(
            job,
            header=("sample\tn_called\tcall_rate\tn_het\thet_rate\t"
                    "n_hom_alt\n"),
            lines=[
                f"{s.sample_id}\t{s.n_called}\t{s.call_rate:.6f}\t"
                f"{s.n_het}\t{s.het_rate:.6f}\t{s.n_hom_alt}\n"
                for s in stats
            ],
            noun="samples",
        )
        return 0
    elif args.command == "cross-kinship":
        import dataclasses as _dc

        from spark_examples_tpu.pipelines.project import cross_kinship_job

        if _needs_ref_path(args):
            parser.error("cross-kinship requires --ref-path")
        if args.maf > 0.0 or args.max_missing < 1.0 or args.ld_prune_r2 > 0:
            parser.error(
                "--maf/--max-missing/--ld-prune-r2 cannot apply during "
                "cross-kinship (data-dependent masks would keep "
                "different variant subsets per cohort); filter both "
                "cohorts to the same sites beforehand"
            )
        ref_cfg = _dc.replace(job.ingest, source=args.ref_source,
                              path=args.ref_path)
        src_ref = build_source(ref_cfg)
        res = cross_kinship_job(
            job, source_new=build_source(job.ingest),
            source_ref=src_ref,
        )
        phi = res.similarity
        ref_ids = src_ref.sample_ids
        hits = [
            (res.sample_ids[i], ref_ids[j], float(phi[i, j]))
            for i, j in zip(*np.nonzero(phi >= args.min_phi))
        ]
        print(
            f"cross-kinship {phi.shape[0]}x{phi.shape[1]} over "
            f"{res.n_variants} variants; {len(hits)} pairs with "
            f"phi >= {args.min_phi}"
            + (f" -> {job.output_path}" if job.output_path else "")
        )
        for a, b, p in sorted(hits, key=lambda t: -t[2])[:50]:
            print(f"{a}\t{b}\tphi={p:.4f}")
        timer = res.timer
    elif args.command == "project":
        import dataclasses as _dc

        from spark_examples_tpu.pipelines.project import pcoa_project_job

        if _needs_ref_path(args):
            parser.error("project requires --ref-path (the panel "
                         "genotypes the model was fitted on)")
        if args.maf > 0.0 or args.max_missing < 1.0 or args.ld_prune_r2 > 0.0:
            parser.error(
                "--maf/--max-missing/--ld-prune-r2 cannot apply during "
                "project: these masks are data-dependent, so each cohort "
                "would keep a DIFFERENT variant subset and cross-"
                "statistics would mix misaligned variants. Filter/prune "
                "the panel once (pack --maf/--ld-prune-r2 ... into a "
                "store), fit the model on that store, and supply a new "
                "cohort genotyped at the same sites"
            )
        ref_cfg = _dc.replace(job.ingest, source=args.ref_source,
                              path=args.ref_path)
        out = pcoa_project_job(
            job, model_path=args.model,
            source_new=build_source(job.ingest),
            source_ref=build_source(ref_cfg),
        )
        _print_coords(out, job)
        timer = out.timer
    elif args.command == "neighbors":
        return _run_neighbors(args, parser, job, build_source)
    elif args.command == "serve":
        return _run_serve(args, parser, job, build_source)
    elif args.command == "pack":
        import time as _time

        from spark_examples_tpu.ingest.packed import pack_source

        if not job.output_path:
            parser.error("pack requires --output-path (the store dir)")
        src = build_source(job.ingest)
        t0 = _time.perf_counter()
        written = pack_source(job.output_path, src,
                              job.ingest.block_variants)
        dt = _time.perf_counter() - t0
        print(
            f"packed {src.n_samples} samples x {written} variants "
            f"({src.n_samples * written / 4 / 1e6:.1f} MB 2-bit) -> "
            f"{job.output_path} in {dt:.1f}s"
        )
        return 0
    elif args.command == "ingest":
        import time as _time

        from spark_examples_tpu.store import compact, origin_from_ingest

        if not job.output_path:
            parser.error("ingest requires --output-path (the store "
                         "directory to compact into)")
        src = build_source(job.ingest)
        t0 = _time.perf_counter()
        manifest = compact(job.output_path, src,
                           chunk_variants=args.chunk_variants,
                           workers=job.ingest.ingest_workers,
                           codec=job.ingest.store_codec,
                           origin=origin_from_ingest(job.ingest,
                                                     args.chunk_variants))
        dt = _time.perf_counter() - t0
        dense_mb = manifest.n_samples * manifest.n_variants / 1e6
        n = manifest.n_samples
        raw_b = sum(c.payload_size(n) for c in manifest.chunks)
        stored_b = sum(c.disk_size(n) for c in manifest.chunks)
        print(
            f"compacted {manifest.n_samples} samples x "
            f"{manifest.n_variants} variants into {len(manifest.chunks)} "
            f"content-addressed chunks ({dense_mb / 4:.1f} MB 2-bit -> "
            f"{stored_b / 1e6:.1f} MB stored, "
            f"{raw_b / max(stored_b, 1):.2f}x {job.ingest.store_codec}) "
            f"-> {job.output_path} in {dt:.1f}s "
            f"({dense_mb / max(dt, 1e-9):.0f} MB/s dense-equivalent, "
            f"{stored_b / 1e6 / max(dt, 1e-9):.0f} MB/s written, "
            f"{job.ingest.ingest_workers} workers); "
            f"read it back with --source store:{job.output_path}"
        )
        return 0
    else:  # pragma: no cover
        parser.error(f"unknown command {args.command}")

    if args.timings:
        print(json.dumps(timer.report(), sort_keys=True), file=sys.stderr)
    return 0


def _run_neighbors(args, parser, job, build_source) -> int:
    """The `neighbors` subcommand. Cohort mode runs the full
    MinHash/LSH/exact-eval pipeline (neighbors/engine.py); query mode
    (--model) funnels through the SAME serve-engine pairwise batch and
    top-k reduction a fleet ``topk`` route uses, so the file written
    here is bit-identical to the served /neighbors answers."""
    import dataclasses as _dc

    from spark_examples_tpu.core.profiling import PhaseTimer
    from spark_examples_tpu.neighbors import TopKResult, save_result
    from spark_examples_tpu.neighbors.engine import neighbors_job

    timer = PhaseTimer()
    if args.model:
        from spark_examples_tpu.pipelines import project as P
        from spark_examples_tpu.serve import engine as E

        if args.maf > 0.0 or args.max_missing < 1.0 or args.ld_prune_r2 > 0:
            parser.error(
                "--maf/--max-missing/--ld-prune-r2 cannot apply during "
                "query-vs-panel neighbors (data-dependent masks would "
                "keep different variant subsets per cohort); filter "
                "both cohorts to the same sites beforehand"
            )
        if _needs_ref_path(args):
            parser.error("neighbors --model requires --ref-path (the "
                         "panel genotypes the model was fitted on)")
        try:
            ctx = E.ModelContext(P.load_model(args.model))
            E.check_topkable(ctx.model)
        except ValueError as e:
            parser.error(str(e))
        ref_cfg = _dc.replace(job.ingest, source=args.ref_source,
                              path=args.ref_path)
        src_ref = build_source(ref_cfg)
        P.check_reference_panel(ctx.model, src_ref)
        with timer.phase("stage"):
            blocks, n_variants, _nbytes = E.stage_blocks(
                src_ref, job.ingest.block_variants)
        q_cfg = job.ingest
        if q_cfg.source == "synthetic":
            q_cfg = _dc.replace(q_cfg, n_variants=n_variants)
        q_src = build_source(q_cfg)
        queries = np.concatenate(
            [b for b, _ in q_src.blocks(q_cfg.block_variants)], axis=1)
        if queries.shape[1] != n_variants:
            parser.error(
                f"query cohort carries {queries.shape[1]} variants but "
                f"the model's panel has {n_variants} — both cohorts "
                "must be genotyped at the panel's sites"
            )
        k = args.neighbors_k
        # Chunked through the padded-batch serving kernel: hom-ref
        # padding keeps every row's integer sums independent of the
        # chunk size, so any chunking matches the server bit for bit.
        batch = 8
        ids_rows, sim_rows = [], []
        with timer.phase("neighbors_eval"):
            for i in range(0, queries.shape[0], batch):
                ids, sims = E.batch_topk(
                    ctx, blocks, queries[i:i + batch], batch,
                    n_variants, k)
                ids_rows.append(ids)
                sim_rows.append(sims)
        res = TopKResult(
            ids=np.concatenate(ids_rows, axis=0),
            sims=np.concatenate(sim_rows, axis=0),
            sample_ids=tuple(q_src.sample_ids),
            metric=ctx.model.metric,
            k=int(ids_rows[0].shape[1]), n_variants=n_variants,
        )
        panel_ids = list(ctx.model.sample_ids)
    else:
        res = neighbors_job(job, timer=timer)
        panel_ids = list(res.sample_ids)

    if job.output_path:
        with timer.phase("write"):
            save_result(job.output_path, res)
    suffix = f" -> {job.output_path}" if job.output_path else ""
    if res.kind == "topk":
        print(
            f"neighbors[{res.metric}] top-{res.k} for "
            f"{len(res.sample_ids)} samples over {res.n_variants} "
            f"variants{suffix}"
        )
        for sid, ids, sims in list(zip(res.sample_ids, res.ids,
                                       res.sims))[:5]:
            cells = [
                f"{panel_ids[j]}={s:.4f}"
                for j, s in zip(ids.tolist(), sims.tolist()) if j >= 0
            ]
            print(sid + "\t" + "\t".join(cells[:5]))
    else:
        print(
            f"neighbors[{res.metric}] {len(res.pairs)} evaluated "
            f"candidate pairs among {len(res.sample_ids)} samples "
            f"over {res.n_variants} variants{suffix}"
        )
        order = np.argsort(-res.sims, kind="stable")[:5]
        for t in order:
            i, j = res.pairs[t]
            print(f"{res.sample_ids[i]}\t{res.sample_ids[j]}\t"
                  f"{res.sims[t]:.4f}")
    if args.timings:
        print(json.dumps(timer.report(), sort_keys=True),
              file=sys.stderr)
    return 0


def _write_port_file(path, port) -> None:
    """--port-file: atomically publish the bound port so a controller
    parent can discover an ephemeral (--port 0) child's address — the
    rename is the commit point, so the parent never reads a torn
    file."""
    if not path:
        return
    from spark_examples_tpu.core import telemetry as _tel

    _tel._atomic_write(path, json.dumps({"port": int(port)}))


def _run_serve(args, parser, job, build_source) -> int:
    """The `serve` subcommand: engine + server up, then either a local
    HTTP endpoint (default; Ctrl-C drains) or an in-process closed-loop
    loadgen run whose JSON report goes to stdout. Telemetry export (the
    --telemetry-dir exit-stack callback in main) fires after the drain,
    so the exported serve.* histograms cover the whole serving life."""
    import dataclasses as _dc

    from spark_examples_tpu.serve import (
        ProjectionEngine, ProjectionServer, run_loadgen,
    )

    if not args.fleet and not args.model:
        parser.error("serve needs --model MODEL.npz (single-model "
                     "mode) or --fleet fleet.json (multi-model mode)")
    if args.fleet and args.model:
        parser.error("--fleet and --model are mutually exclusive: the "
                     "fleet manifest names every route's model")
    if not args.fleet and _needs_ref_path(args):
        parser.error("serve requires --ref-path (the panel genotypes "
                     "the model was fitted on)")
    try:
        cfg = config.ServeConfig(
            model_path=args.model,
            max_batch=args.max_batch,
            max_linger_ms=args.max_linger_ms,
            max_queue=args.max_queue,
            cache_entries=args.cache_entries,
            deadline_ms=args.deadline_ms,
            host=args.host,
            port=args.port,
            fleet_manifest=args.fleet,
            fleet_budget_mb=args.fleet_budget_mb,
            queue_interactive=args.queue_interactive,
            queue_batch=args.queue_batch,
            deadline_interactive_ms=args.deadline_interactive_ms,
            deadline_batch_ms=args.deadline_batch_ms,
            drain_timeout_s=args.drain_timeout_s,
            loadgen_seed=args.loadgen_seed,
        )
    except ValueError as e:
        parser.error(str(e))
    if args.fleet:
        return _run_serve_fleet(args, parser, job, cfg, build_source)
    ref_cfg = _dc.replace(job.ingest, source=args.ref_source,
                          path=args.ref_path)
    engine = ProjectionEngine(
        cfg.model_path, build_source(ref_cfg),
        block_variants=job.ingest.block_variants,
        max_batch=cfg.max_batch,
    )
    server = ProjectionServer(
        engine,
        max_linger_s=cfg.max_linger_ms / 1e3,
        max_queue=cfg.max_queue,
        cache_entries=cfg.cache_entries,
        default_deadline_s=(cfg.deadline_ms / 1e3) or None,
        drain_timeout_s=cfg.drain_timeout_s,
    )
    server.start()
    try:
        if args.loadgen > 0:
            q_cfg = job.ingest
            if q_cfg.source == "synthetic":
                # The pool must carry the panel's variant set; for the
                # synthetic source that is a config knob, so align it.
                q_cfg = _dc.replace(q_cfg, n_variants=engine.n_variants)
            q_src = build_source(q_cfg)
            pool = np.concatenate(
                [b for b, _ in q_src.blocks(q_cfg.block_variants)],
                axis=1,
            )
            if pool.shape[1] != engine.n_variants:
                parser.error(
                    f"loadgen query pool carries {pool.shape[1]} "
                    f"variants but the model's panel has "
                    f"{engine.n_variants} — both cohorts must be "
                    "genotyped at the panel's sites"
                )
            report = run_loadgen(
                server, pool, clients=args.loadgen,
                requests_per_client=args.loadgen_requests,
                deadline_s=(cfg.deadline_ms / 1e3) or None,
            )
            print(json.dumps(report, sort_keys=True))
        else:
            import signal

            from spark_examples_tpu.serve.http import ProjectionHTTPServer

            http = ProjectionHTTPServer(server, host=cfg.host,
                                        port=cfg.port)
            _write_port_file(args.port_file, http.port)

            # SIGTERM (the orchestrator's stop signal — and the only
            # deliverable one when SIGINT was inherited ignored) must
            # drain, not kill: route it through the KeyboardInterrupt
            # path so admitted requests are answered before exit.
            def _sigterm(signum, frame):
                raise KeyboardInterrupt

            try:
                signal.signal(signal.SIGTERM, _sigterm)
            except ValueError:
                pass  # not the main thread (embedded use) — skip
            print(
                f"serving projections on http://{http.host}:{http.port} "
                f"(POST /project, GET /healthz, GET /stats; "
                f"{engine.n_variants} variants x "
                f"{engine.n_components} components; Ctrl-C drains)",
                file=sys.stderr,
            )
            try:
                http.serve_forever()
            except KeyboardInterrupt:
                print("draining...", file=sys.stderr)
            finally:
                http.shutdown()
    finally:
        server.close()
    return 0


def _run_serve_fleet(args, parser, job, cfg, build_source) -> int:
    """`serve --fleet`: manifest -> FleetRouter; then either the fleet
    HTTP front (Ctrl-C/SIGTERM drains) or a multi-tenant loadgen mix
    (per route: --loadgen interactive + --loadgen batch clients) whose
    JSON report goes to stdout."""
    import dataclasses as _dc

    from spark_examples_tpu.core.config import PRIORITY_CLASSES
    from spark_examples_tpu.serve import (
        FleetFormatError, FleetManifest, build_fleet, run_fleet_loadgen,
    )

    try:
        manifest = FleetManifest.load(cfg.fleet_manifest)
        fleet = build_fleet(manifest, cfg, ingest_defaults=job.ingest,
                            block_variants=job.ingest.block_variants)
    except (FleetFormatError, ValueError, OSError) as e:
        parser.error(str(e))
    fleet.start()
    try:
        if args.loadgen > 0:
            pools = {}
            for name, route in fleet.routes.items():
                q_cfg = job.ingest
                if q_cfg.source == "synthetic":
                    q_cfg = _dc.replace(
                        q_cfg, n_variants=route.n_variants
                        or q_cfg.n_variants)
                q_src = build_source(q_cfg)
                pools[name] = np.concatenate(
                    [b for b, _ in q_src.blocks(q_cfg.block_variants)],
                    axis=1,
                )
            mix = [(name, cls, args.loadgen)
                   for name in sorted(fleet.routes)
                   for cls in PRIORITY_CLASSES]
            report = run_fleet_loadgen(
                fleet, pools, mix,
                requests_per_client=args.loadgen_requests,
            )
            report["stats"] = fleet.stats_payload()
            print(json.dumps(report, sort_keys=True))
        else:
            import signal

            from spark_examples_tpu.serve.http import fleet_http_server

            http = fleet_http_server(fleet, host=cfg.host, port=cfg.port)
            _write_port_file(args.port_file, http.port)

            def _sigterm(signum, frame):
                raise KeyboardInterrupt

            try:
                signal.signal(signal.SIGTERM, _sigterm)
            except ValueError:
                pass  # not the main thread (embedded use) — skip
            print(
                f"serving fleet of {len(fleet.routes)} route(s) on "
                f"http://{http.host}:{http.port} (POST /project "
                "{'route': ..., 'genotypes': [...], 'priority': ...}, "
                "GET /routes, /healthz, /stats, /metrics; pool budget "
                f"{fleet.pool.budget_bytes / 1e6:.0f} MB; Ctrl-C "
                "drains)",
                file=sys.stderr,
            )
            try:
                http.serve_forever()
            except KeyboardInterrupt:
                print("draining...", file=sys.stderr)
            finally:
                http.shutdown()
    finally:
        fleet.close()
    return 0


def _run_telemetry_admin(args) -> int:
    """The ``telemetry`` maintenance subcommand (``stitch`` — single
    job or ``--fleet`` — and ``timeline``). Prints the report as JSON;
    exit 0 iff something was read."""
    from spark_examples_tpu.core.stitch import (
        StitchError,
        stitch,
        stitch_fleet,
    )

    if args.verb == "timeline":
        return _run_telemetry_timeline(args)
    if args.fleet:
        try:
            report = stitch_fleet(args.path, output=args.output)
        except StitchError as e:
            print(f"telemetry stitch --fleet: {e}", file=sys.stderr)
            return 1
        print(json.dumps(report, sort_keys=True))
        print(
            f"telemetry stitch --fleet: {report['events']} events "
            f"from {len(report['slots'])} replica slot(s), "
            f"{report['incident_markers']} incident marker(s) -> "
            f"{report['output']} (open in https://ui.perfetto.dev)",
            file=sys.stderr,
        )
        return 0
    try:
        report = stitch(args.path, output=args.output)
    except StitchError as e:
        print(f"telemetry stitch: {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, sort_keys=True))
    if report["mixed_run_ids"]:
        print(
            f"telemetry stitch: WARNING — {len(report['run_ids'])} "
            "distinct run_ids merged; this directory holds exports "
            "from more than one logical job",
            file=sys.stderr,
        )
    print(
        f"telemetry stitch: {report['events']} events from "
        f"{len(report['attempts'])} attempt(s) x "
        f"{len(report['ranks'])} rank(s), "
        f"{report['restart_markers']} restart marker(s) -> "
        f"{report['output']} (open in https://ui.perfetto.dev)",
        file=sys.stderr,
    )
    return 0


def _run_telemetry_timeline(args) -> int:
    """``telemetry timeline --path <dir|file>``: the fleet flight
    recorder's read side — route p99 / queue-depth / replica-count
    history from the controller's timeline.jsonl ring, incident and
    decision markers interleaved where they happened."""
    import os

    from spark_examples_tpu.fleet.timeline import read_timeline

    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "timeline.jsonl")
    records = read_timeline(path)
    if not records:
        print(f"telemetry timeline: no readable records in {path!r} "
              "(run the fleet controller with a ledger/timeline path)",
              file=sys.stderr)
        return 1
    rounds = [r for r in records if r.get("type") == "round"]
    markers = [r for r in records if r.get("type") == "marker"]
    routes: dict[str, dict] = {}
    for rec in rounds:
        for s in rec.get("slots", {}).values():
            if not s.get("present"):
                continue
            for name, r in s.get("routes", {}).items():
                agg = routes.setdefault(
                    name, {"p99_max_ms": 0.0, "p99_last_ms": 0.0})
                p99_ms = r.get("p99_s", 0.0) * 1e3
                agg["p99_max_ms"] = max(agg["p99_max_ms"], p99_ms)
                agg["p99_last_ms"] = p99_ms
    report = {
        "path": path,
        "rounds": len(rounds),
        "markers": len(markers),
        "replicas_last": rounds[-1]["replicas"] if rounds else 0,
        "ready_last": rounds[-1]["ready"] if rounds else 0,
        "routes": {k: {kk: round(vv, 3) for kk, vv in v.items()}
                   for k, v in sorted(routes.items())},
        "marker_kinds": sorted({m.get("kind", "?") for m in markers}),
    }
    print(json.dumps(report, sort_keys=True))
    t0 = records[0].get("t_unix", 0.0)
    tail = sorted(records, key=lambda r: r.get("seq", 0))[-args.last:]
    for rec in tail:
        dt = rec.get("t_unix", t0) - t0
        if rec.get("type") == "round":
            slots = [s for s in rec.get("slots", {}).values()
                     if s.get("present")]
            p99 = max((s.get("p99_s", 0.0) for s in slots), default=0.0)
            depth = sum(s.get("queue_interactive", 0)
                        + s.get("queue_batch", 0) for s in slots)
            shed = max((s.get("shed_rate", 0.0) for s in slots),
                       default=0.0)
            print(f"t+{dt:7.2f}s round {rec.get('round', 0):>4} "
                  f"replicas={rec.get('replicas', 0)} "
                  f"ready={rec.get('ready', 0)} "
                  f"p99={p99 * 1e3:8.1f}ms depth={depth:>3} "
                  f"shed={shed:6.1%}", file=sys.stderr)
        else:
            print(f"t+{dt:7.2f}s !! [{rec.get('kind', '?')}] "
                  f"{rec.get('who', '?')}: "
                  f"{str(rec.get('detail', ''))[:90]}",
                  file=sys.stderr)
    return 0


def _run_store_admin(args) -> int:
    """The ``store`` maintenance subcommand (currently: ``heal``).
    Prints the heal report as JSON; exit 0 iff nothing is left damaged."""
    from spark_examples_tpu.store.heal import heal

    report = heal(args.path, replicas=tuple(args.replica),
                  verify_all=args.verify_all)
    print(json.dumps(report, sort_keys=True))
    if report["failed"]:
        print(
            f"store heal: {len(report['failed'])} chunk(s) could not be "
            "healed (no replica holds them and the origin no longer "
            "reproduces them) — restore the files or re-run the "
            "compaction",
            file=sys.stderr,
        )
        return 1
    if report["healed"]:
        print(f"store heal: {len(report['healed'])} chunk(s) healed and "
              "re-verified; quarantine ledger cleared", file=sys.stderr)
    return 0


def _run_coverage(args) -> int:
    from spark_examples_tpu.ingest.reads import SamSource, SyntheticReadsSource
    from spark_examples_tpu.pipelines.coverage import coverage

    refs = [ReferenceRange.parse(r) for r in args.references]
    if args.reads_source == "sam":
        if not args.path:
            raise SystemExit("coverage --reads-source sam requires --path")
        src = SamSource(args.path, references=refs)
    else:
        if not refs:
            refs = [ReferenceRange("chr22", 16_050_000, 16_150_000)]
        src = SyntheticReadsSource(
            references=refs,
            reads_per_range=args.reads_per_range,
            read_length=args.read_length,
            seed=args.seed,
        )
    results = coverage(src)
    for r in results:
        h = [int(v) for v in r.histogram(10)]
        print(
            f"{r.reference}\treads={r.n_reads}\tmean_depth={r.mean:.2f}\t"
            f"depth_hist[0..10+]={h}"
        )
    if args.output_path:
        with open(args.output_path, "w") as f:
            f.write("contig\tposition\tdepth\n")
            for r in results:
                for i, d in enumerate(r.depth):
                    f.write(
                        f"{r.reference.contig}\t{r.reference.start + i}\t"
                        f"{int(d)}\n"
                    )
        print(f"depth table -> {args.output_path}")
    return 0


def _print_coords(out, job: JobConfig) -> None:
    k = out.coords.shape[1]
    print(
        f"{len(out.sample_ids)} samples x {k} components"
        + (f" -> {job.output_path}" if job.output_path else "")
    )
    vals = np.asarray(out.eigenvalues, float)
    if vals.size:
        line = "eigenvalues: " + " ".join(f"{v:.6g}" for v in vals[:10])
        prop = getattr(out, "proportion", None)
        if prop is not None:
            # true scree: share of TOTAL inertia (trace-based, from the
            # solver) — does not sum to 1 unless k captures everything
            line += "  (explained: " + " ".join(
                f"{p:.1%}" for p in np.asarray(prop, float)[:10]
            ) + ")"
        print(line)
    for sid, row in list(zip(out.sample_ids, out.coords))[:5]:
        print(sid + "\t" + "\t".join(f"{v:.4g}" for v in row[:4]))


if __name__ == "__main__":
    sys.exit(main())

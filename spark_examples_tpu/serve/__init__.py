"""Online projection serving — the long-lived counterpart of the
``project`` CLI.

The reference family's flagship workflow is *fit once on a reference
panel, project every new cohort into the same coordinates*; offline,
every projection pays a full cold start (model load, panel re-stream,
fresh jit compile). This package keeps all of that resident: the packed
reference blocks and centering statistics live on device, the compiled
programs are warmed once, and projection queries arrive through an
async micro-batching queue with a production envelope around it —
bounded admission with explicit load-shedding, per-request deadlines
and cancellation, an LRU result cache keyed by genotype digest, and
graceful drain. Served coordinates are bit-identical to the offline
``project`` CLI by construction (see serve/engine.py).

Layers:

- :class:`~spark_examples_tpu.serve.engine.ProjectionEngine` — the
  device-resident model + panel + compiled step (no queueing).
- :class:`~spark_examples_tpu.serve.server.ProjectionServer` — the
  async micro-batcher and admission envelope over one engine.
- :mod:`~spark_examples_tpu.serve.http` — a thin stdlib HTTP front.
- :mod:`~spark_examples_tpu.serve.loadgen` — the closed-loop load
  generator behind ``bench.py --serve`` and the ``serve --loadgen``
  CLI mode (offered vs sustained QPS, latency p50/p99).
"""

from spark_examples_tpu.serve.cache import ResultCache, genotype_digest
from spark_examples_tpu.serve.engine import ProjectionEngine
from spark_examples_tpu.serve.health import (
    DEGRADED,
    DRAINING,
    HEALTHY,
    CircuitBreaker,
)
from spark_examples_tpu.serve.loadgen import run_loadgen
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ProjectionServer,
    ServerClosed,
    ServerOverloaded,
)

__all__ = [
    "CircuitBreaker",
    "DEGRADED",
    "DRAINING",
    "DeadlineExceeded",
    "HEALTHY",
    "ProjectionEngine",
    "ProjectionServer",
    "ResultCache",
    "ServerClosed",
    "ServerOverloaded",
    "genotype_digest",
    "run_loadgen",
]

"""Online projection serving — the long-lived counterpart of the
``project`` CLI.

The reference family's flagship workflow is *fit once on a reference
panel, project every new cohort into the same coordinates*; offline,
every projection pays a full cold start (model load, panel re-stream,
fresh jit compile). This package keeps all of that resident: the packed
reference blocks and centering statistics live on device, the compiled
programs are warmed once, and projection queries arrive through an
async micro-batching queue with a production envelope around it —
bounded admission with explicit load-shedding, per-request deadlines
and cancellation, an LRU result cache keyed by genotype digest, and
graceful drain. Served coordinates are bit-identical to the offline
``project`` CLI by construction (see serve/engine.py).

Layers:

- :class:`~spark_examples_tpu.serve.engine.ProjectionEngine` — the
  device-resident model + panel + compiled step (no queueing).
- :class:`~spark_examples_tpu.serve.server.ProjectionServer` — the
  async micro-batcher and admission envelope over one engine.
- :mod:`~spark_examples_tpu.serve.http` — a thin stdlib HTTP front
  (single-model and fleet).
- :mod:`~spark_examples_tpu.serve.loadgen` — the closed-loop load
  generators behind ``bench.py --serve`` / ``--fleet`` and the
  ``serve --loadgen`` CLI mode (offered vs sustained QPS, latency
  p50/p99, the multi-tenant fleet mix, replica hedging).

Fleet serving (``serve --fleet fleet.json``) routes many named
(model, panel) pairs through ONE process:

- :mod:`~spark_examples_tpu.serve.pool` — the warm panel pool: staged
  panels under an explicit HBM/host-RAM budget with LRU eviction;
  evicted panels re-stage on demand through the content-addressed
  store (the shared cold tier across replica processes).
- :mod:`~spark_examples_tpu.serve.router` — priority-class admission
  (interactive preempts batch backfill) + the fleet batching worker.
- :mod:`~spark_examples_tpu.serve.fleet` — the manifest registry and
  fleet assembly.
"""

from spark_examples_tpu.serve.cache import ResultCache, genotype_digest
from spark_examples_tpu.serve.engine import ProjectionEngine
from spark_examples_tpu.serve.fleet import (
    FleetFormatError,
    FleetManifest,
    build_fleet,
)
from spark_examples_tpu.serve.health import (
    DEGRADED,
    DRAINING,
    HEALTHY,
    CircuitBreaker,
)
from spark_examples_tpu.serve.loadgen import (
    BurstSchedule,
    run_fleet_loadgen,
    run_hedged_loadgen,
    run_loadgen,
)
from spark_examples_tpu.serve.pool import PanelPool, PanelUnavailable
from spark_examples_tpu.serve.router import FleetRouter, UnknownRoute
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ProjectionServer,
    ServerClosed,
    ServerOverloaded,
)

__all__ = [
    "BurstSchedule",
    "CircuitBreaker",
    "DEGRADED",
    "DRAINING",
    "DeadlineExceeded",
    "FleetFormatError",
    "FleetManifest",
    "FleetRouter",
    "HEALTHY",
    "PanelPool",
    "PanelUnavailable",
    "ProjectionEngine",
    "ProjectionServer",
    "ResultCache",
    "ServerClosed",
    "ServerOverloaded",
    "UnknownRoute",
    "build_fleet",
    "genotype_digest",
    "run_fleet_loadgen",
    "run_hedged_loadgen",
    "run_loadgen",
]

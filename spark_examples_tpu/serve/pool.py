"""The fleet's warm panel pool: staged reference panels under a budget.

A single-model server stages its panel once and keeps it forever; a
fleet process serves *many* (model, panel) routes, and the panels are
the expensive part — dense device-resident genotype blocks, megabytes
to gigabytes each. The pool is the explicit HBM/host-RAM discipline
over them (the serving-side analogue of the TPU memory budgets in
arXiv:2112.09017):

- **Lazy staging.** A route's panel is staged on first demand, through
  the ordinary store read path (readahead, decode cache, verify —
  whatever the route's IngestConfig arms), inside a ``fleet.stage``
  span with the ``fleet.stage`` fault site fired first.
- **Budget + LRU.** Staged bytes are charged against one explicit
  budget; staging a panel past it evicts least-recently-used panels
  (never the one just staged) until the pool fits — counted in
  ``fleet.evictions``. An evicted panel loses only warmth: the next
  request re-stages it from the store (the shared cold tier), counted
  in ``fleet.restage_total``.
- **Breaker-guarded.** Each stage runs through the route's
  :class:`~spark_examples_tpu.serve.health.CircuitBreaker`: repeated
  store failures trip it open and later acquires fail fast with
  :class:`PanelUnavailable` (the route degrades; others keep serving)
  until the half-open probe heals it.

Concurrency contract: **callers serialize staging** (the fleet's single
batching worker owns all device work, exactly like the single-model
server; route admin ops take the router's engine lock). The pool's own
lock only guards its bookkeeping — the staging IO/device work runs
outside it, so a slow stage can never block a concurrent metrics
scrape of the pool gauges.
"""

from __future__ import annotations

import threading
import warnings
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass

from spark_examples_tpu.core import faults, telemetry


class PanelUnavailable(RuntimeError):
    """The route's panel is not staged and cannot be right now: the
    stage failed, or the route's circuit breaker is open and the
    attempt was short-circuited. Requests waiting on it are failed
    explicitly with this (the fleet's analogue of cached-panel-only
    mode — with no cached panel, there is nothing to degrade to)."""


@dataclass
class StagedPanel:
    """One warm panel: the staged device blocks plus the accounting the
    budget charges."""

    route: str
    blocks: list
    n_variants: int
    nbytes: int


class PanelPool:
    """Budgeted LRU pool of staged reference panels, keyed by route."""

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError(
                f"panel pool budget must be positive, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, StagedPanel] = OrderedDict()
        # Shard-staged residency (router._sharded_blocks): bytes a
        # route is holding transiently while one shard of an
        # over-budget panel serves. Charged against the same budget
        # (they evict warm panels) but never evictable themselves —
        # evicting the shard being computed on would tear the batch.
        self._transient: dict[str, int] = {}
        self._ever_staged: set[str] = set()
        self._warned_oversize: set[str] = set()

    # -- bookkeeping reads -------------------------------------------------

    def resident_bytes(self) -> int:
        with self._lock:
            return (sum(e.nbytes for e in self._entries.values())
                    + sum(self._transient.values()))

    def pressure(self) -> float:
        """resident / budget (the autoscale signal)."""
        return self.resident_bytes() / self.budget_bytes

    def resident_routes(self) -> list[str]:
        """LRU -> MRU order."""
        with self._lock:
            return list(self._entries)

    def is_staged(self, route: str) -> bool:
        with self._lock:
            return route in self._entries

    def stats(self) -> dict:
        with self._lock:
            transient = sum(self._transient.values())
            resident = (sum(e.nbytes for e in self._entries.values())
                        + transient)
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": resident,
                "transient_bytes": transient,
                "pressure": resident / self.budget_bytes,
                "staged_routes": list(self._entries),
            }

    # -- the hot path ------------------------------------------------------

    def acquire(self, route: str, stage_fn, breaker=None) -> StagedPanel:
        """The warm panel for ``route``, staging it on a miss.

        ``stage_fn()`` -> ``(blocks, n_variants, nbytes)`` (typically
        :func:`serve.engine.stage_blocks` over a fresh source). A miss
        whose route was staged before counts ``fleet.restage_total`` —
        that is a cold start the budget traded away. Raises
        :class:`PanelUnavailable` when the breaker short-circuits, and
        re-raises (after feeding the breaker) whatever the stage
        itself raised."""
        with self._lock:
            entry = self._entries.get(route)
            if entry is not None:
                self._entries.move_to_end(route)
                return entry
        if breaker is not None and not breaker.allow():
            raise PanelUnavailable(
                f"route {route!r}: panel not staged and its store "
                f"breaker is {breaker.state} — re-stage attempts are "
                "short-circuited until the reset window's probe"
            )
        try:
            with telemetry.span("fleet.stage", cat="fleet", route=route):
                faults.fire("fleet.stage")
                blocks, n_variants, nbytes = stage_fn()
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        except BaseException:
            # SIGINT/SystemExit mid-stage says nothing about the store:
            # give the half-open probe slot back and let it propagate.
            if breaker is not None:
                breaker.release_probe()
            raise
        if breaker is not None:
            breaker.record_success()
        entry = StagedPanel(route=route, blocks=blocks,
                            n_variants=n_variants, nbytes=int(nbytes))
        with self._lock:
            if route in self._ever_staged:
                telemetry.count("fleet.restage_total")
            self._ever_staged.add(route)
            self._entries[route] = entry
            self._entries.move_to_end(route)
            self._evict_over_budget_locked(keep=route)
            self._publish_locked()
        return entry

    def _evict_over_budget_locked(self, keep: str) -> None:
        resident = (sum(e.nbytes for e in self._entries.values())
                    + sum(self._transient.values()))
        while resident > self.budget_bytes:
            victim = next((r for r in self._entries if r != keep), None)
            if victim is None:
                # Everything left is ``keep``'s own bytes (or transient
                # shard residency) and it still exceeds the budget:
                # serve anyway (evicting it would deadlock the route),
                # but say so once. Routes whose panel length is known
                # up front never land here — the router serves their
                # over-budget panels shard-staged (_sharded_blocks)
                # instead of staging them whole; only a length-blind
                # source or a direct acquire of an oversized panel can.
                if keep not in self._warned_oversize:
                    self._warned_oversize.add(keep)
                    warnings.warn(
                        f"route {keep!r}: its resident bytes alone "
                        f"({resident} B) exceed the pool budget "
                        f"({self.budget_bytes} B) — serving it "
                        "unevictable; raise --fleet-budget-mb (panels "
                        "with a known length serve shard-staged "
                        "instead)",
                        RuntimeWarning, stacklevel=3,
                    )
                return
            resident -= self._entries.pop(victim).nbytes
            telemetry.count("fleet.evictions")

    @contextmanager
    def transient(self, route: str, nbytes: int):
        """Charge ``nbytes`` of shard residency for ``route`` while the
        body runs: the shard counts against the budget exactly like a
        warm panel (entering may evict other routes' LRU panels) but is
        never an eviction candidate itself, and the charge is released
        when the shard is dropped — the accounting half of shard-staged
        serving (router._sharded_blocks owns the staging half)."""
        nbytes = int(nbytes)
        with self._lock:
            self._transient[route] = (
                self._transient.get(route, 0) + nbytes)
            self._evict_over_budget_locked(keep=route)
            self._publish_locked()
        try:
            yield
        finally:
            with self._lock:
                left = self._transient.get(route, 0) - nbytes
                if left > 0:
                    self._transient[route] = left
                else:
                    self._transient.pop(route, None)
                self._publish_locked()

    # -- admin -------------------------------------------------------------

    def evict(self, route: str) -> bool:
        """Drop a staged panel (it re-stages on next demand)."""
        with self._lock:
            entry = self._entries.pop(route, None)
            if entry is not None:
                telemetry.count("fleet.evictions")
            self._publish_locked()
            return entry is not None

    def remove(self, route: str) -> bool:
        """Forget a route entirely (unload): its panel AND its
        staged-before history, so a later reload of the same name is a
        first stage again, not a 'restage'."""
        with self._lock:
            entry = self._entries.pop(route, None)
            self._ever_staged.discard(route)
            self._warned_oversize.discard(route)
            self._publish_locked()
            return entry is not None

    def _publish_locked(self) -> None:
        resident = (sum(e.nbytes for e in self._entries.values())
                    + sum(self._transient.values()))
        telemetry.gauge_set("fleet.pool_bytes", float(resident))
        telemetry.gauge_set("fleet.pool_pressure",
                            resident / self.budget_bytes)

"""Device-resident projection engine: model + reference panel staged
once, one compiled shape for every micro-batch.

Offline, ``project`` streams the reference panel from disk for every
cohort and pays a fresh jit compile per process. The engine instead
stages the panel's genotype blocks into device memory **once** at
startup, together with the model's eigenvectors and centering
statistics, and answers micro-batches through two compiled programs
warmed at init:

- the batched cross-statistics update — the query batch is padded to a
  fixed ``(max_batch, V)`` shape with hom-ref rows, so ONE jit cache
  entry per staged block width serves every batch size (padding rows
  cost matmul FLOPs but their outputs are discarded);
- the per-row finalize at shape ``(1, N_ref)`` — the SAME jitted
  ``_project`` / ``_project_pca`` the offline single-query path runs,
  at the same shape.

**Bit-identity with the offline CLI is by construction, not luck**: the
cross statistics are int32 sums of int8 matmul products, exact for any
block partition and any batch shape (padding contributes rows that are
simply never read), so each live row of the padded accumulator equals
the offline single-query accumulator bit for bit; the finalize then
runs the identical compiled program on identical inputs. Tests pin
this for batch sizes 1, 3, max, and max+1 on both model kinds.

The engine is intentionally queue-free and NOT thread-safe: the server
(serve/server.py) owns one engine and serializes all device work
through its single batching worker.
"""

from __future__ import annotations

import warnings

import numpy as np

import jax
import jax.numpy as jnp

from spark_examples_tpu import kernels
from spark_examples_tpu.pipelines import project as P
from spark_examples_tpu.serve.health import CircuitBreaker


class ModelContext:
    """A loaded, validated model installed on device: the projectable
    stats, the f32-cast eigen/centering statistics, and the per-row
    finalize. ONE implementation shared by the single-model
    :class:`ProjectionEngine` and the fleet's per-route serving path
    (serve/router.py), so served bit-identity with the offline CLI has
    a single anchor instead of two copies that could drift."""

    def __init__(self, model):
        if isinstance(model, (str, bytes)):
            model = P.load_model(model)
        self.stats = P.check_projectable(model)
        self.model = model
        # Factorized (sketch-ladder) models project family-wise; dense
        # models' kind IS their family. The pcoa family of a factorized
        # model additionally needs the query denominator diagonal
        # (qden) accumulated alongside the cross statistics.
        self.family = getattr(model, "family", model.kind)
        self.needs_qden = (model.kind == "factorized"
                           and self.family == "pcoa")
        # f32 casts at the device boundary — exactly what the offline
        # path does with the freshly np.load-ed f64 arrays.
        self._eigvecs = jax.device_put(
            np.asarray(model.eigvecs, np.float32))
        self._eigvals = jax.device_put(
            np.asarray(model.eigvals, np.float32))
        self._colmean = jax.device_put(
            np.asarray(model.colmean, np.float32))
        self._grand = jnp.float32(model.grand)
        if self.needs_qden:
            self._scale = jax.device_put(
                np.asarray(model.scale, np.float32))
            self._scale_floor = jnp.float32(model.scale_floor)

    @property
    def n_ref(self) -> int:
        return self.model.n_ref

    @property
    def n_components(self) -> int:
        return self.model.n_components

    def finalize_row(self, acc, i: int, qden=None):
        """One live row at shape (1, N_ref) through the SAME compiled
        finalize as the offline single-query path — the bit-identity
        anchor. ``qden`` is the (max_batch,) query denominator diagonal
        a factorized-pcoa batch accumulated; unused otherwise."""
        if self.family == "pca":
            return P._project_pca(
                acc["s"][i:i + 1], self._colmean, self._grand,
                self._eigvecs,
            )
        if self.needs_qden:
            return P._project_factorized_dual(
                {k: v[i:i + 1] for k, v in acc.items()}, qden[i:i + 1],
                self._scale, self._scale_floor, self._colmean,
                self._grand, self._eigvecs, self._eigvals,
                metric=self.model.metric,
            )
        return P._project(
            {k: v[i:i + 1] for k, v in acc.items()}, self._colmean,
            self._grand, self._eigvecs, self._eigvals,
            metric=self.model.metric,
        )


def batch_coords(ctx: ModelContext, ref_blocks, genotypes: np.ndarray,
                 max_batch: int, n_variants: int) -> np.ndarray:
    """(b, V) int8 query genotypes -> (b, k) f32 coordinates through
    the padded-batch serving math: hom-ref padding to ``max_batch`` (one
    jit entry per staged block width serves every batch size), int32
    cross statistics against the staged reference blocks, the per-row
    finalize at (1, N_ref). Bit-identical per row to the offline
    single-query ``pcoa_project_job`` (module docstring)."""
    g = np.ascontiguousarray(genotypes, dtype=np.int8)
    if g.ndim != 2 or g.shape[1] != n_variants:
        raise ValueError(
            f"query batch must be (b, {n_variants}) int8 dosages, "
            f"got {g.shape}"
        )
    b = g.shape[0]
    if not 1 <= b <= max_batch:
        raise ValueError(
            f"batch of {b} rows outside [1, {max_batch}]"
        )
    if b < max_batch:
        # Hom-ref padding rows: any valid dosage works — their
        # accumulator rows are computed and never read.
        g = np.concatenate(
            [g, np.zeros((max_batch - b, n_variants), np.int8)], axis=0)
    acc = {
        k: jnp.zeros((max_batch, ctx.n_ref), jnp.int32)
        for k in ctx.stats
    }
    qden = (jnp.zeros((max_batch,), jnp.float32)
            if ctx.needs_qden else None)
    for ref_dev, meta in ref_blocks:
        q = jax.device_put(
            np.ascontiguousarray(g[:, meta.start:meta.stop]))
        acc = P._update_cross(acc, q, ref_dev)
        if qden is not None:
            # The SAME jitted accumulation the offline factorized path
            # runs (padding rows get a qden that is never read).
            qden = P._den_diag(qden, q, metric=ctx.model.metric)
    rows = [np.asarray(ctx.finalize_row(acc, i, qden))
            for i in range(b)]
    return np.concatenate(rows, axis=0)


def check_topkable(model) -> "kernels.PairSpec":
    """The gate for the ``topk`` route capability: the model's metric
    must carry a pairwise finalize (kernels.PairSpec — jaccard/ibs/
    king). PCA models have no similarity metric at all; projectable
    metrics without a PairSpec can project but not rank neighbors."""
    metric = getattr(model, "metric", None)
    # Family-aware: a factorized pcoa model ranks neighbors exactly as
    # a dense one does (pairwise similarity is model-independent), so
    # only the pca FAMILY is metric-less, whichever artifact carries it.
    family = getattr(model, "family", model.kind)
    if family == "pca" or not metric:
        raise ValueError(
            "topk serving needs a metric-bearing (pcoa) model — PCA "
            "models carry no pairwise similarity to rank neighbors by"
        )
    spec = kernels.get(metric).pair
    if spec is None:
        raise ValueError(
            f"metric {metric!r} has no pairwise finalize — topk routes "
            f"support: {', '.join(kernels.pairable_names())}"
        )
    return spec


def batch_pair_sims(ctx: ModelContext, ref_blocks,
                    genotypes: np.ndarray, max_batch: int,
                    n_variants: int) -> np.ndarray:
    """(b, V) int8 query genotypes -> (b, N_ref) float64 EXACT pairwise
    similarities against the staged panel, through the same padded-batch
    cross-statistics accumulation as :func:`batch_coords` — int32 sums
    of int8 products, exact for any block partition and batch shape, so
    each live row equals the offline query-vs-panel accumulator bit for
    bit; the host-side PairSpec finalize then runs on identical
    integers. The offline ``neighbors`` CLI query mode calls THIS
    function, which is what makes served answers bit-identical to it by
    construction rather than by test luck."""
    spec = check_topkable(ctx.model)
    g = np.ascontiguousarray(genotypes, dtype=np.int8)
    if g.ndim != 2 or g.shape[1] != n_variants:
        raise ValueError(
            f"query batch must be (b, {n_variants}) int8 dosages, "
            f"got {g.shape}"
        )
    b = g.shape[0]
    if not 1 <= b <= max_batch:
        raise ValueError(
            f"batch of {b} rows outside [1, {max_batch}]"
        )
    if b < max_batch:
        g = np.concatenate(
            [g, np.zeros((max_batch - b, n_variants), np.int8)], axis=0)
    acc = {
        k: jnp.zeros((max_batch, ctx.n_ref), jnp.int32)
        for k in spec.stats
    }
    for ref_dev, meta in ref_blocks:
        q = jax.device_put(
            np.ascontiguousarray(g[:, meta.start:meta.stop]))
        acc = P._update_cross(acc, q, ref_dev)
    # int64 on the host — same integer values as the int32 device sums
    # (the budget guard bounds them), and the same dtype the offline
    # cohort engine accumulates in, so the float64 finalize is bitwise
    # the same arithmetic.
    host = {k: np.asarray(v[:b]).astype(np.int64)
            for k, v in acc.items()}
    return np.asarray(spec.sim(host), np.float64)


def batch_topk(ctx: ModelContext, ref_blocks, genotypes: np.ndarray,
               max_batch: int, n_variants: int,
               k: int) -> tuple[np.ndarray, np.ndarray]:
    """(b, V) queries -> ``(ids, sims)`` of shape (b, min(k, N_ref)):
    each query's k nearest panel samples by exact similarity,
    descending, ties by ascending panel index — the serving twin of the
    offline top-k reduction (neighbors/engine.py ``topk_rows``)."""
    from spark_examples_tpu.neighbors.engine import topk_rows

    sims = batch_pair_sims(ctx, ref_blocks, genotypes, max_batch,
                           n_variants)
    return topk_rows(sims, k)


def stage_blocks(source_ref, block_variants: int) -> tuple[list, int, int]:
    """Stage a reference panel's dense int8 blocks device-resident:
    ``(blocks, n_variants, nbytes)``. Shared by the engine's startup
    staging and the fleet warm pool (serve/pool.py) — the byte count is
    what the pool's budget charges."""
    blocks = []
    n_variants = 0
    nbytes = 0
    for block, meta in source_ref.blocks(block_variants):
        blocks.append((jax.device_put(block), meta))
        n_variants = meta.stop
        nbytes += int(block.nbytes)
    if n_variants == 0:
        raise ValueError("reference source yielded no variants")
    return blocks, n_variants, nbytes


def shard_stream(source_ref, block_variants: int, max_shard_bytes: int):
    """Shard-staged panel feed: group a panel's dense int8 blocks into
    consecutive shards of at most ``max_shard_bytes`` device bytes and
    yield ``(blocks, nbytes)`` per shard, device-putting each shard's
    blocks only at yield time. The serving loop (router._sharded_blocks)
    serves one shard and drops it before pulling the next, so peak
    device residency is ONE shard — the mechanism that lets a fleet
    route serve a panel larger than the whole pool budget. A shard
    always carries at least one block (a single block wider than the
    budget still streams, it just cannot be split); while shard k is
    being served the generator holds at most one pending HOST block of
    shard k+1 (host RAM, not HBM). Block partitioning is unchanged, so
    the cross accumulation — integer sums, partition-invariant — is
    bit-identical to whole-panel staging."""
    pending: list = []
    nbytes = 0
    for block, meta in source_ref.blocks(block_variants):
        b = int(block.nbytes)
        if pending and nbytes + b > max_shard_bytes:
            yield [(jax.device_put(h), m) for h, m in pending], nbytes
            pending, nbytes = [], 0
        pending.append((block, meta))
        nbytes += b
    if pending:
        yield [(jax.device_put(h), m) for h, m in pending], nbytes


def _store_cache_of(source):
    """The DecodeCache behind a (possibly wrapped) store-backed source,
    or None — serve's /stats endpoint reports its accounting when the
    reference panel was staged from ``store:<dir>``."""
    seen = 0
    while source is not None and seen < 8:  # bounded unwrap
        cache = getattr(source, "cache", None)
        if cache is not None and hasattr(cache, "stats"):
            return cache
        source = getattr(source, "inner", None) or getattr(
            source, "store", None)
        seen += 1
    return None


class ProjectionEngine:
    """A loaded model + staged reference panel + compiled batch step.

    ``model`` is a :class:`~spark_examples_tpu.pipelines.project.
    ProjectionModel` or a path to a saved ``.npz``; ``source_ref`` must
    be the panel the model was fitted on (validated by sample ids, the
    same guard as the offline job). ``block_variants`` is the staging
    granularity — it does not need to match the width the model was
    fitted with (integer accumulation is partition-invariant).
    """

    def __init__(self, model, source_ref, block_variants: int = 8192,
                 max_batch: int = 8, warm: bool = True):
        if isinstance(model, (str, bytes)):
            model = P.load_model(model)
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.block_variants = int(block_variants)
        self._install_model(model)
        P.check_reference_panel(model, source_ref)  # before any staging
        self._panel_ids = list(source_ref.sample_ids)
        # Staging from a dataset store rides its tiered read path (and,
        # when armed, the readahead pool). Keep a handle on the decode
        # cache so /stats can report the staging hit/miss/eviction
        # accounting (the serve-cold-start story in numbers).
        self._panel_cache = _store_cache_of(source_ref)
        self._source_ref = source_ref
        # Circuit breaker on the panel's store read path: re-staging
        # (hot panel refresh after a store heal, a replica catching up)
        # runs through it, and repeated store failures trip it open —
        # the server then keeps serving the already-staged panel
        # (cached-panel-only mode) instead of dying on a broken store.
        self.breaker = CircuitBreaker()
        # Stage the panel once: dense int8 blocks, device-resident for
        # the life of the server (the whole point — no per-request
        # panel re-stream). Block shapes are fixed across requests, so
        # the compiled update's cache stays at one entry per distinct
        # staged width (full + ragged tail). Init staging is NOT
        # breaker-guarded: with no cached panel yet there is nothing to
        # degrade to, so a failure here is correctly fatal.
        self._ref_blocks, self.n_variants = self._stage_panel(source_ref)
        if warm:
            self.warmup()

    def _stage_panel(self, source_ref) -> tuple[list, int]:
        blocks, n_variants, _nbytes = stage_blocks(
            source_ref, self.block_variants)
        return blocks, n_variants

    def restage(self, source_ref=None) -> bool:
        """Refresh the staged panel from its source through the
        circuit breaker — the hot path for "the store healed / the
        replica caught up, pick up the repaired bytes without a
        restart". Returns True when the panel was re-staged; False in
        **cached-panel-only mode**: the breaker is open (or this
        attempt failed and fed it), and the server keeps answering
        from the panel already on device. The swap is all-or-nothing
        and identity-checked — a source streaming a different variant
        count can never replace the panel the model was fitted on."""
        src = source_ref if source_ref is not None else self._source_ref
        if not self.breaker.allow():
            return False
        try:
            # Identity BEFORE bytes: the panel is the cohort the model
            # was fitted on, so the sample ids must match exactly — a
            # different cohort that happens to stream the same variant
            # count must never be swapped under the model.
            if list(src.sample_ids) != self._panel_ids:
                raise ValueError(
                    "re-staged source carries different sample ids than "
                    "the panel the model was fitted on — refusing the "
                    "swap (fit a new model for a changed panel)"
                )
            blocks, n_variants = self._stage_panel(src)
            if n_variants != self.n_variants:
                raise ValueError(
                    f"re-staged panel streams {n_variants} variants, "
                    f"the staged panel has {self.n_variants} — refusing "
                    "the swap (fit a new model for a changed panel)"
                )
        except Exception as e:
            self.breaker.record_failure()
            warnings.warn(
                f"panel re-stage failed ({e!r}) — serving continues "
                f"from the cached panel (breaker "
                f"{self.breaker.state})",
                RuntimeWarning, stacklevel=2,
            )
            return False
        except BaseException:
            # SIGINT/SystemExit mid-probe says nothing about the
            # store: give the half-open probe slot back (else the
            # breaker wedges open forever) and let it propagate.
            self.breaker.release_probe()
            raise
        self.breaker.record_success()
        self._ref_blocks = blocks
        if source_ref is not None:
            self._source_ref = source_ref
            self._panel_cache = _store_cache_of(source_ref)
        return True

    @property
    def panel_mode(self) -> str:
        """"staged" (breaker closed) or "cached-only" (the breaker is
        routing around a failing store — the panel still serves, but
        re-stages are short-circuited)."""
        return "staged" if self.breaker.state == "closed" else "cached-only"

    def _install_model(self, model: "P.ProjectionModel") -> None:
        """Validate + move a model's statistics to device (init and
        hot-reload share this) — one :class:`ModelContext`."""
        self._ctx = ModelContext(model)

    @property
    def model(self):
        return self._ctx.model

    @property
    def stats(self) -> tuple[str, ...]:
        return self._ctx.stats

    def store_cache_stats(self) -> dict | None:
        """DecodeCache accounting of the staged panel's store (hits/
        misses/evictions/bytes), or None when the panel did not come
        from a dataset store."""
        if self._panel_cache is None:
            return None
        return self._panel_cache.stats()

    @property
    def n_ref(self) -> int:
        return self.model.n_ref

    @property
    def n_components(self) -> int:
        return self.model.n_components

    def warmup(self) -> None:
        """Run one padded batch end to end so no request ever pays the
        compile (the cold start the server exists to amortize)."""
        self.project_batch(
            np.zeros((1, self.n_variants), np.int8))

    def reload_model(self, model) -> None:
        """Hot-swap the served model (same panel), dropping the compiled
        -closure caches the old model may pin (project.clear_caches —
        the satellite this PR's clearable cache exists for). The panel
        must match the new model's sample ids; the staged blocks are
        reused as-is. Commit is all-or-nothing: a failure anywhere
        (including the warmup compile) restores the old model, so the
        caller's 'old model still serving' contract holds."""
        if isinstance(model, (str, bytes)):
            model = P.load_model(model)
        P.check_projectable(model)
        if model.sample_ids != self._panel_ids:
            raise ValueError(
                "hot-reload refused: the new model was fitted on a "
                "different reference panel than the one staged on "
                "device — restart the server against the right panel"
            )
        old_ctx = self._ctx
        P.clear_caches()
        try:
            self._install_model(model)
            self.warmup()
        except BaseException:
            self._ctx = old_ctx
            raise

    def project_batch(self, genotypes: np.ndarray) -> np.ndarray:
        """(b, V) int8 query genotypes -> (b, k) f32 coordinates,
        b <= max_batch. Bit-identical per row to the offline
        single-query ``pcoa_project_job`` (see module docstring) —
        the math lives in :func:`batch_coords`, shared with the fleet
        serving path."""
        return batch_coords(self._ctx, self._ref_blocks, genotypes,
                            self.max_batch, self.n_variants)

"""Fleet manifest + assembly: ``serve --fleet fleet.json``.

The manifest is the route registry — one JSON file mapping route names
to model paths and panel sources, plus the pool budget the warm panels
share::

    {
      "budget_mb": 256,                 // warm panel pool budget
      "max_batch": 8,                   // optional, ServeConfig default
      "block_variants": 8192,           // optional staging granularity
      "routes": [
        {"name": "eur-panel", "model": "eur.npz",
         "source": "store:/data/eur.store"},
        {"name": "afr-panel", "model": "afr.npz",
         "source": "packed", "path": "/data/afr_packed",
         "block_variants": 4096, "topk": true}
      ]
    }

A route with ``"topk": true`` additionally answers ``POST /neighbors``
(exact query-vs-panel nearest neighbors through the model metric's
pairwise finalize) — validated at load: the model must carry a
pairable metric (kernels.PairSpec), so a capability the model cannot
honor dies at startup, not on the first request.

``source`` takes the same spellings as the CLI ``--source`` family
(``store:<dir>`` shorthand included — IngestConfig normalizes it);
panels stage lazily through whatever read path the source arms (store
readahead, decode cache, verified reads). Replica groups run one fleet
process per host against the SAME content-addressed store directories
— the store is the shared cold tier, and client-side request hedging
between replicas lives in serve/loadgen.py.

Malformed manifests die as :class:`FleetFormatError` with the offending
route/field named (the load_model/StoreFormatError convention) — a
fleet process must refuse a half-valid registry at startup, not 404 on
its first unlucky request.
"""

from __future__ import annotations

import dataclasses
import json

from spark_examples_tpu.core.config import (
    PRIORITY_CLASSES,
    IngestConfig,
    ServeConfig,
)
from spark_examples_tpu.serve import engine as E
from spark_examples_tpu.serve.pool import PanelPool
from spark_examples_tpu.serve.router import FleetRouter, Route


class FleetFormatError(ValueError):
    """A fleet manifest that cannot be safely interpreted — always with
    the offending route/field named."""


@dataclasses.dataclass(frozen=True)
class RouteSpec:
    """One validated manifest route entry."""

    name: str
    model: str
    source: str
    path: str | None = None
    block_variants: int | None = None
    topk: bool = False


@dataclasses.dataclass(frozen=True)
class FleetManifest:
    routes: tuple[RouteSpec, ...]
    budget_mb: float | None = None
    max_batch: int | None = None
    block_variants: int | None = None
    # Declared objectives (fleet/slo.py SLOSpec): the controller
    # burn-rate-evaluates these over the fleet timeline every round.
    slos: tuple = ()

    @classmethod
    def parse(cls, doc: dict, origin: str = "<manifest>") -> "FleetManifest":
        if not isinstance(doc, dict):
            raise FleetFormatError(
                f"fleet manifest {origin}: expected a JSON object, got "
                f"{type(doc).__name__}"
            )
        raw_routes = doc.get("routes")
        if not isinstance(raw_routes, list) or not raw_routes:
            raise FleetFormatError(
                f"fleet manifest {origin}: 'routes' must be a non-empty "
                "list of route objects"
            )
        specs = []
        seen: set[str] = set()
        for i, r in enumerate(raw_routes):
            if not isinstance(r, dict):
                raise FleetFormatError(
                    f"fleet manifest {origin}: routes[{i}] is not an "
                    "object"
                )
            for field in ("name", "model", "source"):
                if not isinstance(r.get(field), str) or not r[field]:
                    raise FleetFormatError(
                        f"fleet manifest {origin}: routes[{i}] is "
                        f"missing required string field {field!r} "
                        "(name = the route's address, model = the "
                        ".npz from pcoa/pca --save-model, source = the "
                        "panel source, e.g. store:<dir>)"
                    )
            if r["name"] in seen:
                raise FleetFormatError(
                    f"fleet manifest {origin}: duplicate route name "
                    f"{r['name']!r}"
                )
            seen.add(r["name"])
            unknown = set(r) - {"name", "model", "source", "path",
                                "block_variants", "topk"}
            if unknown:
                raise FleetFormatError(
                    f"fleet manifest {origin}: routes[{i}] "
                    f"({r['name']!r}) has unknown field(s) "
                    f"{sorted(unknown)}"
                )
            if not isinstance(r.get("topk", False), bool):
                raise FleetFormatError(
                    f"fleet manifest {origin}: routes[{i}] "
                    f"({r['name']!r}) topk={r['topk']!r} — expected "
                    "true/false"
                )
            specs.append(RouteSpec(
                name=r["name"], model=r["model"], source=r["source"],
                path=r.get("path"),
                block_variants=r.get("block_variants"),
                topk=r.get("topk", False),
            ))
        unknown_top = set(doc) - {"routes", "budget_mb", "max_batch",
                                  "block_variants", "slos"}
        if unknown_top:
            raise FleetFormatError(
                f"fleet manifest {origin}: unknown top-level field(s) "
                f"{sorted(unknown_top)}"
            )
        # Scalar fields type-checked HERE: a string budget must die as
        # the promised FleetFormatError at load, not as a TypeError
        # from deep inside pool construction.
        for field, kind, lo in (("budget_mb", (int, float), 0.0),
                                ("max_batch", (int,), 1),
                                ("block_variants", (int,), 1)):
            value = doc.get(field)
            if value is None:
                continue
            if (isinstance(value, bool) or not isinstance(value, kind)
                    or value < lo):
                raise FleetFormatError(
                    f"fleet manifest {origin}: {field}={value!r} — "
                    f"expected a number >= {lo}"
                )
        for i, spec in enumerate(specs):
            bv = spec.block_variants
            if bv is not None and (isinstance(bv, bool)
                                   or not isinstance(bv, int) or bv < 1):
                raise FleetFormatError(
                    f"fleet manifest {origin}: routes[{i}] "
                    f"({spec.name!r}) block_variants={bv!r} — expected "
                    "an integer >= 1"
                )
        slos: tuple = ()
        if doc.get("slos") is not None:
            from spark_examples_tpu.fleet import slo as SLO

            def _err(msg: str) -> FleetFormatError:
                return FleetFormatError(
                    f"fleet manifest {origin}: {msg}")

            slos = SLO.parse_slos(doc["slos"], seen, error=_err)
        return cls(
            routes=tuple(specs),
            budget_mb=doc.get("budget_mb"),
            max_batch=doc.get("max_batch"),
            block_variants=doc.get("block_variants"),
            slos=slos,
        )

    @classmethod
    def load(cls, path: str) -> "FleetManifest":
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise FleetFormatError(
                f"fleet manifest {path!r} is not readable JSON ({e})"
            ) from None
        return cls.parse(doc, origin=repr(path))


def build_route(spec: RouteSpec, ingest_defaults: IngestConfig,
                default_block_variants: int) -> Route:
    """One manifest entry -> a loaded :class:`Route`: model loaded and
    validated, panel identity checked against a freshly built source
    (cheap for store-backed panels — the manifest answers without
    touching chunks), n_variants probed when the source knows it."""
    from spark_examples_tpu.pipelines import project as P
    from spark_examples_tpu.pipelines.runner import build_source
    from spark_examples_tpu.serve.router import _close_source

    ctx = E.ModelContext(P.load_model(spec.model))
    if spec.topk:
        try:
            E.check_topkable(ctx.model)
        except ValueError as e:
            raise FleetFormatError(
                f"fleet manifest: route {spec.name!r} declares the "
                f"'topk' capability its model cannot honor — {e}"
            ) from None
    panel_cfg = dataclasses.replace(
        ingest_defaults, source=spec.source, path=spec.path,
        block_variants=(spec.block_variants or default_block_variants),
    )

    def panel_source_fn():
        return build_source(panel_cfg)

    src = panel_source_fn()
    try:
        P.check_reference_panel(ctx.model, src)
        n_variants = getattr(src, "n_variants", None)
        n_variants = int(n_variants) if n_variants else None
    finally:
        _close_source(src)
    return Route(
        name=spec.name,
        ctx=ctx,
        panel_source_fn=panel_source_fn,
        block_variants=panel_cfg.block_variants,
        n_variants=n_variants,
        topk=spec.topk,
    )


def build_fleet(manifest: FleetManifest, cfg: ServeConfig,
                ingest_defaults: IngestConfig | None = None,
                block_variants: int | None = None) -> FleetRouter:
    """Manifest + ServeConfig -> a ready (not yet started) router.

    Precedence for shared knobs: manifest value, else ServeConfig /
    the caller's ingest defaults. The pool budget is
    ``manifest.budget_mb`` or ``cfg.fleet_budget_mb``."""
    ingest_defaults = ingest_defaults or IngestConfig()
    budget_mb = (manifest.budget_mb if manifest.budget_mb is not None
                 else cfg.fleet_budget_mb)
    default_bv = (manifest.block_variants or block_variants
                  or ingest_defaults.block_variants)
    router = FleetRouter(
        pool=PanelPool(int(budget_mb * 1e6)),
        max_batch=manifest.max_batch or cfg.max_batch,
        max_linger_s=cfg.max_linger_ms / 1e3,
        cache_entries=cfg.cache_entries,
        queue_bounds={
            PRIORITY_CLASSES[0]: cfg.queue_interactive,
            PRIORITY_CLASSES[1]: cfg.queue_batch,
        },
        class_deadlines_s={
            PRIORITY_CLASSES[0]: cfg.deadline_interactive_ms / 1e3,
            PRIORITY_CLASSES[1]: cfg.deadline_batch_ms / 1e3,
        },
        drain_timeout_s=cfg.drain_timeout_s,
    )
    for spec in manifest.routes:
        router.add_route(
            build_route(spec, ingest_defaults, default_bv))
    return router

"""Closed-loop load generator for the projection server.

``clients`` threads each submit queries back-to-back (a new request the
moment the previous one resolves — classic closed-loop load), drawing
striped rows from a query pool. Because the loop is closed, *offered*
load is what the clients actually managed to attempt (including sheds)
and *sustained* is what the server completed; under overload the two
diverge and the gap is the shed/error count, never silent queueing.

Latency percentiles are read from the telemetry registry's
``serve.latency_s`` histogram — the same numbers ``--telemetry-dir``
exports — so the report and the export cannot disagree.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from spark_examples_tpu.core import telemetry
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ProjectionServer,
    ServerOverloaded,
)


@dataclass
class _ClientTally:
    attempts: int = 0
    ok: int = 0
    shed: int = 0
    deadline: int = 0
    errors: int = 0


def run_loadgen(server: ProjectionServer, pool: np.ndarray,
                clients: int = 4, requests_per_client: int = 50,
                deadline_s: float | None = None,
                result_timeout_s: float = 30.0) -> dict:
    """Drive ``server`` with ``clients`` concurrent closed-loop clients
    and return the serving report (offered vs sustained QPS, latency
    p50/p99 from the telemetry export, shed/error accounting).

    ``pool`` is a (Q, V) int8 query-genotype pool; client ``c`` cycles
    through rows ``c, c+clients, c+2*clients, ...`` so concurrent
    clients never submit the same row at the same step (a pool smaller
    than the result cache turns the run into a cache benchmark — size
    the pool accordingly for device numbers).
    """
    pool = np.ascontiguousarray(pool, dtype=np.int8)
    if pool.ndim != 2 or not len(pool):
        raise ValueError(f"query pool must be (Q, V) int8, got {pool.shape}")
    tallies = [_ClientTally() for _ in range(clients)]
    start = threading.Barrier(clients + 1)

    def client(c: int) -> None:
        tally = tallies[c]
        start.wait()
        for k in range(requests_per_client):
            q = pool[(c + k * clients) % len(pool)]
            tally.attempts += 1
            try:
                server.project(q, timeout=result_timeout_s,
                               deadline_s=deadline_s)
                tally.ok += 1
            except ServerOverloaded:
                tally.shed += 1
            except DeadlineExceeded:
                tally.deadline += 1
            except Exception:
                tally.errors += 1

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True,
                         name=f"loadgen-client-{c}")
        for c in range(clients)
    ]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    duration = max(time.perf_counter() - t0, 1e-9)

    attempts = sum(t.attempts for t in tallies)
    ok = sum(t.ok for t in tallies)
    lat = telemetry.metrics_snapshot()["histograms"].get(
        "serve.latency_s", {})
    return {
        "clients": clients,
        "requests_per_client": requests_per_client,
        "duration_s": round(duration, 4),
        "offered_qps": round(attempts / duration, 2),
        "sustained_qps": round(ok / duration, 2),
        "completed": ok,
        "shed": sum(t.shed for t in tallies),
        "deadline_expired": sum(t.deadline for t in tallies),
        "errors": sum(t.errors for t in tallies),
        "latency_p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "latency_p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
        "latency_max_ms": round(lat.get("max", 0.0) * 1e3, 3),
        "server": server.stats.snapshot(),
    }

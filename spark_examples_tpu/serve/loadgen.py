"""Closed-loop load generators: single-model, multi-tenant fleet mix,
and replica hedging.

``clients`` threads each submit queries back-to-back (a new request the
moment the previous one resolves — classic closed-loop load), drawing
striped rows from a query pool. Because the loop is closed, *offered*
load is what the clients actually managed to attempt (including sheds)
and *sustained* is what the server completed; under overload the two
diverge and the gap is the shed/error count, never silent queueing.

Latency percentiles are read from the telemetry registry's
``serve.latency_s`` histogram — the same numbers ``--telemetry-dir``
exports — so the report and the export cannot disagree.

Fleet additions:

- :func:`run_fleet_loadgen` — a multi-tenant traffic mix over a
  :class:`~spark_examples_tpu.serve.router.FleetRouter`: each mix entry
  is (route, priority class, clients), latencies tracked client-side
  per (route, class), and the report carries the per-class aggregate
  p50/p99 the priority contract is judged on (interactive p99 below
  batch p99 under mixed load).
- :func:`run_hedged_loadgen` — client-side request hedging between
  replica processes sharing the content-addressed store as their cold
  tier: a client sends to its primary, waits a **p95-derived hedge
  delay** (the rolling p95 of its own completed primaries; the classic
  tail-at-scale recipe), then sends the same query to a second replica
  — first answer wins, the loser is cancelled. ``fleet.hedge_launched``
  / ``fleet.hedge_wins`` count the relief.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, wait
from dataclasses import dataclass

import numpy as np

from spark_examples_tpu.core import telemetry
from spark_examples_tpu.core.config import DEFAULT_PRIORITY
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ProjectionServer,
    ServerClosed,
    ServerOverloaded,
)


@dataclass
class _ClientTally:
    attempts: int = 0
    ok: int = 0
    shed: int = 0
    deadline: int = 0
    errors: int = 0


# Client-side error records carry the server's run_id (and the request's
# trace_id where one exists) so a client log line joins the server-side
# trace export without guessing which run produced it.
_ERROR_RECORDS_MAX = 50


class _ErrorLog:
    def __init__(self):
        self._lock = threading.Lock()
        self.records: list[dict] = []
        self.dropped = 0

    def record(self, **fields) -> None:
        rec = {"run_id": telemetry.run_id(), **fields}
        with self._lock:
            if len(self.records) < _ERROR_RECORDS_MAX:
                self.records.append(rec)
            else:
                self.dropped += 1

    def report(self) -> list[dict]:
        with self._lock:
            return list(self.records)


def run_loadgen(server: ProjectionServer, pool: np.ndarray,
                clients: int = 4, requests_per_client: int = 50,
                deadline_s: float | None = None,
                result_timeout_s: float = 30.0) -> dict:
    """Drive ``server`` with ``clients`` concurrent closed-loop clients
    and return the serving report (offered vs sustained QPS, latency
    p50/p99 from the telemetry export, shed/error accounting).

    ``pool`` is a (Q, V) int8 query-genotype pool; client ``c`` cycles
    through rows ``c, c+clients, c+2*clients, ...`` so concurrent
    clients never submit the same row at the same step (a pool smaller
    than the result cache turns the run into a cache benchmark — size
    the pool accordingly for device numbers).
    """
    pool = np.ascontiguousarray(pool, dtype=np.int8)
    if pool.ndim != 2 or not len(pool):
        raise ValueError(f"query pool must be (Q, V) int8, got {pool.shape}")
    tallies = [_ClientTally() for _ in range(clients)]
    errlog = _ErrorLog()
    start = threading.Barrier(clients + 1)

    def client(c: int) -> None:
        tally = tallies[c]
        start.wait()
        for k in range(requests_per_client):
            q = pool[(c + k * clients) % len(pool)]
            tally.attempts += 1
            try:
                server.project(q, timeout=result_timeout_s,
                               deadline_s=deadline_s)
                tally.ok += 1
            except ServerOverloaded:
                tally.shed += 1
            except DeadlineExceeded:
                tally.deadline += 1
            except Exception as e:
                tally.errors += 1
                errlog.record(client=c, error=repr(e))

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True,
                         name=f"loadgen-client-{c}")
        for c in range(clients)
    ]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    duration = max(time.perf_counter() - t0, 1e-9)

    attempts = sum(t.attempts for t in tallies)
    ok = sum(t.ok for t in tallies)
    lat = telemetry.metrics_snapshot()["histograms"].get(
        "serve.latency_s", {})
    return {
        "clients": clients,
        "requests_per_client": requests_per_client,
        "duration_s": round(duration, 4),
        "offered_qps": round(attempts / duration, 2),
        "sustained_qps": round(ok / duration, 2),
        "completed": ok,
        "shed": sum(t.shed for t in tallies),
        "deadline_expired": sum(t.deadline for t in tallies),
        "errors": sum(t.errors for t in tallies),
        "latency_p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "latency_p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
        "latency_max_ms": round(lat.get("max", 0.0) * 1e3, 3),
        "error_records": errlog.report(),
        "server": server.stats.snapshot(),
    }


# --------------------------------------------------------------- fleet mix


def run_fleet_loadgen(fleet, pools: dict[str, np.ndarray],
                      mix: list[tuple[str, str, int]],
                      requests_per_client: int = 50,
                      deadline_s: float | None = None,
                      result_timeout_s: float = 60.0) -> dict:
    """Multi-tenant closed-loop mix against a fleet router.

    ``pools`` maps route name -> (Q, V_route) int8 query pool; ``mix``
    is the tenant table — one ``(route, priority_class, clients)``
    entry per traffic source. Latencies are measured CLIENT-side per
    (route, class) so the per-class percentiles include queueing (the
    thing priorities exist to shape), and the report's
    ``p99_interactive_s`` / ``p99_batch_s`` pair is the priority
    contract's acceptance number."""
    tenants = []  # (route, cls, tally, hist) per client thread
    for route, cls, clients in mix:
        if route not in pools:
            raise ValueError(
                f"mix names route {route!r} but pools has no query "
                f"pool for it (pools: {sorted(pools)})"
            )
        for _ in range(max(0, int(clients))):
            tenants.append((route, cls, _ClientTally(),
                            telemetry.Histogram()))
    if not tenants:
        raise ValueError("empty mix — nothing to offer")
    start = threading.Barrier(len(tenants) + 1)

    errlog = _ErrorLog()

    def client(idx: int) -> None:
        route, cls, tally, hist = tenants[idx]
        pool = pools[route]
        stride = max(1, len(tenants))
        start.wait()
        for k in range(requests_per_client):
            q = pool[(idx + k * stride) % len(pool)]
            tally.attempts += 1
            t0 = time.perf_counter()
            try:
                fleet.project(route, q, timeout=result_timeout_s,
                              priority=cls, deadline_s=deadline_s)
                tally.ok += 1
                hist.record(time.perf_counter() - t0)
            except ServerOverloaded:
                tally.shed += 1
            except DeadlineExceeded:
                tally.deadline += 1
            except Exception as e:
                tally.errors += 1
                errlog.record(route=route, cls=cls, error=repr(e))

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"loadgen-client-{i}")
        for i in range(len(tenants))
    ]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    duration = max(time.perf_counter() - t0, 1e-9)

    def _merge(selector) -> telemetry.Histogram:
        merged = telemetry.Histogram()
        for route, cls, _tally, hist in tenants:
            if selector(route, cls):
                merged.merge(hist)
        return merged

    per_class = {}
    for cls in sorted({c for _r, c, _t, _h in tenants}):
        h = _merge(lambda _r, c, cls=cls: c == cls)
        tallies = [t for _r, c, t, _h in tenants if c == cls]
        per_class[cls] = {
            "clients": len(tallies),
            "completed": sum(t.ok for t in tallies),
            "shed": sum(t.shed for t in tallies),
            "deadline_expired": sum(t.deadline for t in tallies),
            "errors": sum(t.errors for t in tallies),
            "p50_s": round(h.quantile(0.5), 6),
            "p99_s": round(h.quantile(0.99), 6),
        }
    per_route = {}
    for route in sorted({r for r, _c, _t, _h in tenants}):
        h = _merge(lambda r, _c, route=route: r == route)
        tallies = [t for r, _c, t, _h in tenants if r == route]
        per_route[route] = {
            "completed": sum(t.ok for t in tallies),
            "shed": sum(t.shed for t in tallies),
            "errors": sum(t.errors for t in tallies),
            "p99_s": round(h.quantile(0.99), 6),
        }
    attempts = sum(t.attempts for _r, _c, t, _h in tenants)
    ok = sum(t.ok for _r, _c, t, _h in tenants)
    return {
        "clients": len(tenants),
        "requests_per_client": requests_per_client,
        "duration_s": round(duration, 4),
        "offered_qps": round(attempts / duration, 2),
        "sustained_qps": round(ok / duration, 2),
        "completed": ok,
        "shed": sum(t.shed for _r, _c, t, _h in tenants),
        "errors": sum(t.errors for _r, _c, t, _h in tenants),
        "error_records": errlog.report(),
        "per_class": per_class,
        "per_route": per_route,
    }


# ---------------------------------------------------------------- hedging


class _HedgeDelay:
    """Rolling p95 of completed primary latencies (shared by all
    clients of one hedged run) — the hedge trigger. Until enough
    samples exist the caller's floor delay applies; passing ``seed``
    pre-charges the ring with a deterministic floor-scale prior so the
    first hedge decisions replay identically run to run (SOAK-REPRO)
    instead of depending on which client's warmup sample lands
    first."""

    def __init__(self, floor_s: float, window: int = 256,
                 min_samples: int = 20, seed: int | None = None):
        self.floor_s = float(floor_s)
        self._ring: deque[float] = deque(maxlen=window)
        self._min = int(min_samples)
        self._lock = threading.Lock()
        if seed is not None:
            rng = np.random.default_rng(int(seed))
            for x in rng.uniform(0.8, 1.5, size=self._min):
                self._ring.append(self.floor_s * float(x))

    def record(self, latency_s: float) -> None:
        with self._lock:
            self._ring.append(latency_s)

    def delay_s(self) -> float:
        with self._lock:
            if len(self._ring) < self._min:
                return self.floor_s
            ordered = sorted(self._ring)
            p95 = ordered[min(len(ordered) - 1,
                              int(0.95 * len(ordered)))]
        return max(self.floor_s, p95)


class BurstSchedule:
    """Seeded diurnal/bursty arrival schedule — the controller bench's
    traffic shape, deterministic under ``--loadgen-seed``.

    The instantaneous offered rate is a diurnal sinusoid over
    ``duration_s`` (one full day compressed into the run) times a
    ``burst_factor`` inside ``n_bursts`` seeded burst windows — the
    scale-up trigger the controller must answer. ``arrivals()``
    realises it as a sorted tuple of request-start offsets via a
    seeded non-homogeneous Poisson draw, so two runs with the same
    seed offer bit-identical traffic (the SOAK-REPRO contract's
    precondition for pinning served coordinates across a recovery)."""

    def __init__(self, duration_s: float, base_qps: float,
                 seed: int = 0, diurnal_amplitude: float = 0.3,
                 n_bursts: int = 2, burst_factor: float = 6.0,
                 burst_len_s: float | None = None):
        def _check(flag, value, lo, hi, why):
            if not (isinstance(value, (int, float))
                    and lo <= value <= hi):
                raise ValueError(
                    f"bad burst schedule: {flag}={value!r} — expected "
                    f"a number in [{lo}, {hi}] ({why})")

        _check("duration_s", duration_s, 1e-3, 86_400.0,
               "the run's wall-clock span")
        _check("base_qps", base_qps, 1e-6, 1e9,
               "the diurnal baseline offered rate")
        _check("diurnal_amplitude", diurnal_amplitude, 0.0, 0.99,
               "sinusoid swing around the baseline")
        _check("n_bursts", n_bursts, 0, 1000,
               "seeded burst windows inside the run")
        _check("burst_factor", burst_factor, 1.0, 1e6,
               "rate multiplier inside a burst window")
        self.duration_s = float(duration_s)
        self.base_qps = float(base_qps)
        self.seed = int(seed)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.burst_factor = float(burst_factor)
        self.burst_len_s = float(
            burst_len_s if burst_len_s is not None
            else self.duration_s / 10.0)
        rng = np.random.default_rng(self.seed)
        starts = np.sort(rng.uniform(
            0.0, max(1e-9, self.duration_s - self.burst_len_s),
            size=int(n_bursts)))
        self.bursts = tuple(
            (float(s), float(s + self.burst_len_s)) for s in starts)

    def rate_at(self, t: float) -> float:
        rate = self.base_qps * (
            1.0 + self.diurnal_amplitude
            * np.sin(2.0 * np.pi * t / self.duration_s))
        for lo, hi in self.bursts:
            if lo <= t < hi:
                rate *= self.burst_factor
                break
        return float(max(rate, 1e-9))

    def arrivals(self) -> tuple[float, ...]:
        """The realised offsets: thinning-free sequential draw — each
        gap is exponential at the rate where the previous request
        landed. Deterministic for a given (seed, shape)."""
        rng = np.random.default_rng(self.seed + 1)
        out, t = [], 0.0
        while True:
            t += float(rng.exponential(1.0 / self.rate_at(t)))
            if t >= self.duration_s:
                return tuple(out)
            out.append(t)


def run_hedged_loadgen(replicas, pool: np.ndarray,
                       clients: int = 4, requests_per_client: int = 50,
                       route: str | None = None,
                       priority: str = DEFAULT_PRIORITY,
                       hedge_floor_s: float = 0.01,
                       deadline_s: float | None = None,
                       result_timeout_s: float = 60.0,
                       seed: int | None = None) -> dict:
    """Closed-loop load with client-side request hedging between two
    (or more) replicas. ``replicas[0]`` is every client's primary; a
    request unanswered after the p95-derived hedge delay is re-sent to
    the next replica round-robin — first answer wins, the loser future
    is cancelled (a queued loser is dropped at batch pickup; one
    already running finishes and is ignored). ``route`` switches the
    submit surface to the fleet router's; None drives single-model
    ProjectionServers.

    Replica processes share the content-addressed store as their cold
    tier, so a hedge landing on a cold replica pays at worst one
    re-stage — which is exactly the tail the hedge exists to cut.

    The zero-loss contract (the controller's chaos proof leans on it):
    a replica lost mid-traffic costs latency, never an answer — a
    request refused or failed with :class:`ServerClosed` (the loss/
    drain signal) is re-admitted on the client's hedge partner and
    counted in ``failovers``/``fleet.failovers``, not in ``errors``."""
    if len(replicas) < 2:
        raise ValueError("hedging needs >= 2 replicas")
    pool = np.ascontiguousarray(pool, dtype=np.int8)

    def _submit(replica, q, trace=None):
        if route is None:
            # Single-model ProjectionServer surface: no fleet trace
            # plumbing (the fleet router owns phase write-back).
            return replica.submit(q, deadline_s=deadline_s)
        return replica.submit(route, q, priority=priority,
                              deadline_s=deadline_s, trace=trace)

    def _leg_trace(trace_id: str, sampled: bool, leg: str) -> dict:
        # Both legs of one logical request share ONE trace_id (the
        # waterfall key) with distinct span ids per leg.
        return {"trace_id": trace_id,
                "span_id": telemetry.new_span_id(),
                "sampled": sampled, "leg": leg, "phases": {}}

    tallies = [_ClientTally() for _ in range(clients)]
    hists = [telemetry.Histogram() for _ in range(clients)]
    errlog = _ErrorLog()
    hedges = [[0, 0] for _ in range(clients)]  # [launched, wins]
    failovers = [0] * clients
    delay = _HedgeDelay(hedge_floor_s, seed=seed)
    start = threading.Barrier(clients + 1)

    def client(c: int) -> None:
        tally, hist = tallies[c], hists[c]
        backup_replica = replicas[1 + (c % (len(replicas) - 1))]
        start.wait()
        for k in range(requests_per_client):
            q = pool[(c + k * clients) % len(pool)]
            tally.attempts += 1
            t0 = time.perf_counter()
            tid = telemetry.new_trace_id()
            sampled = telemetry.should_sample(tid)

            def _finish() -> None:
                dt = time.perf_counter() - t0
                tally.ok += 1
                hist.record(dt)
                delay.record(dt)

            def _failover() -> None:
                # The primary was lost/drained: re-admit on the hedge
                # partner — latency, never a lost admitted request.
                failovers[c] += 1
                telemetry.count("fleet.failovers")
                try:
                    fut = _submit(backup_replica, q,
                                  _leg_trace(tid, sampled, "failover"))
                    fut.result(timeout=result_timeout_s)
                except Exception as e:
                    tally.errors += 1
                    errlog.record(client=c, trace_id=tid,
                                  leg="failover", error=repr(e))
                    return
                _finish()

            try:
                primary = _submit(replicas[0], q,
                                  _leg_trace(tid, sampled, "primary"))
            except ServerClosed:
                _failover()
                continue
            except Exception as e:
                tally.errors += 1
                errlog.record(client=c, trace_id=tid, leg="primary",
                              error=repr(e))
                continue
            hedge_after = delay.delay_s()
            try:
                primary.result(timeout=hedge_after)
                _finish()
                continue
            except ServerClosed:
                # Admitted, then the replica died out from under it
                # (kill/preempt mid-flight): the survivor still owes
                # the answer.
                _failover()
                continue
            except Exception as e:
                # done-with-exception = a real failure (shed, deadline,
                # fault) — NOT a hedge trigger. Only an unanswered
                # primary past the delay hedges (the wait timed out and
                # the future is still pending/running).
                if primary.done():
                    tally.errors += 1
                    errlog.record(client=c, trace_id=tid,
                                  leg="primary", error=repr(e))
                    continue
            # Primary is the straggler: hedge to the next replica.
            hedges[c][0] += 1
            telemetry.count("fleet.hedge_launched")
            try:
                hedge = _submit(backup_replica, q,
                                _leg_trace(tid, sampled, "hedge"))
            except Exception:
                hedge = None
            futs = [f for f in (primary, hedge) if f is not None]
            done, _pending = wait(futs, timeout=result_timeout_s,
                                  return_when=FIRST_COMPLETED)
            # wait(FIRST_COMPLETED) returns EVERY future already done,
            # not just the first — when both landed in the window,
            # crediting the hedge would inflate the win rate, so the
            # primary takes attribution ties (wins are undercounted,
            # never overcounted).
            winner = None
            if primary in done:
                winner = primary
            elif hedge is not None and hedge in done:
                winner = hedge
            if winner is None:
                tally.errors += 1
                errlog.record(client=c, trace_id=tid, leg="hedged",
                              error="no leg answered in time")
                continue
            loser = primary if winner is hedge else hedge
            try:
                winner.result(timeout=result_timeout_s)
            except ServerClosed:
                # The winning leg was on a dying replica. The other
                # leg (if any) may still answer; else re-admit.
                salvaged = False
                if loser is not None:
                    try:
                        loser.result(timeout=result_timeout_s)
                        salvaged = True
                    except Exception:
                        salvaged = False
                if salvaged:
                    if loser is hedge:
                        hedges[c][1] += 1
                        telemetry.count("fleet.hedge_wins")
                    telemetry.event(
                        "trace.hedge", trace_id=tid,
                        winner="hedge" if loser is hedge else "primary",
                        loser="cancelled_by_replica_loss",
                        salvaged=True)
                    _finish()
                else:
                    _failover()
                continue
            except Exception as e:
                if loser is not None:
                    loser.cancel()
                tally.errors += 1
                errlog.record(client=c, trace_id=tid, leg="winner",
                              error=repr(e))
                continue
            # Cancelled only AFTER the winner resolved: a queued loser
            # drops at batch pickup; one already running finishes and
            # is ignored — but it stays claimable while the winner
            # could still turn out to sit on a dying replica.
            if loser is not None:
                loser.cancel()
            if winner is hedge:
                hedges[c][1] += 1
                telemetry.count("fleet.hedge_wins")
            telemetry.event(
                "trace.hedge", trace_id=tid,
                winner="hedge" if winner is hedge else "primary",
                loser="cancelled" if loser is not None else "none")
            # The hedged request's end-to-end latency feeds the p95 too
            # — a systematically slow primary keeps the trigger honest.
            _finish()

    threads = [
        threading.Thread(target=client, args=(c,), daemon=True,
                         name=f"loadgen-client-{c}")
        for c in range(clients)
    ]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    duration = max(time.perf_counter() - t0, 1e-9)
    merged = telemetry.Histogram()
    for h in hists:
        merged.merge(h)
    launched = sum(h[0] for h in hedges)
    wins = sum(h[1] for h in hedges)
    ok = sum(t.ok for t in tallies)
    return {
        "clients": clients,
        "requests_per_client": requests_per_client,
        "duration_s": round(duration, 4),
        "completed": ok,
        "errors": sum(t.errors for t in tallies),
        "error_records": errlog.report(),
        "sustained_qps": round(ok / duration, 2),
        "failovers": sum(failovers),
        "hedge_launched": launched,
        "hedge_wins": wins,
        "hedge_win_frac": round(wins / launched, 4) if launched else 0.0,
        "p50_s": round(merged.quantile(0.5), 6),
        "p99_s": round(merged.quantile(0.99), 6),
    }

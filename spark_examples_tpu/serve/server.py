"""The async micro-batching projection server.

One background worker owns the engine (and therefore all device work);
clients talk to it through :meth:`ProjectionServer.submit`, which
returns a ``concurrent.futures.Future``. The production envelope:

- **Admission control / load-shedding.** The request queue is bounded
  (``max_queue``); a full queue rejects the submit with an explicit
  :class:`ServerOverloaded` instead of letting latency grow without
  bound. Shedding is counted (``serve.shed``) — an overloaded server is
  *visibly* overloaded.
- **Micro-batching.** The worker takes the first waiting request, then
  lingers up to ``max_linger_s`` for more, up to the engine's
  ``max_batch``; the batch is padded to the engine's fixed compiled
  shape, so one jit cache entry serves every batch size.
- **Deadlines / cancellation.** A request whose deadline passed before
  batch pickup is answered with :class:`DeadlineExceeded` rather than
  occupying device time; a Future cancelled by its client is dropped at
  pickup. Both are counted.
- **Result cache.** Hits by genotype digest (namespaced by the model
  fingerprint) are answered at submit — no queue slot, no device work.
- **Graceful drain.** :meth:`drain` closes admission, waits for every
  in-flight request to resolve, and joins the worker; anything still
  unanswered after the timeout is failed explicitly with
  :class:`ServerClosed` — no silent drops, no hang.
- **Chaos.** Every request crosses the ``serve.request`` fault site
  (core/faults.py) in the worker's assembly sweep: an ``io_error``
  fails exactly that request, a ``delay`` stalls the worker so the
  bounded queue must shed, a ``kill`` simulates preemption.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.serve import health as H
from spark_examples_tpu.serve.cache import ResultCache, genotype_digest
from spark_examples_tpu.serve.engine import ProjectionEngine


class ServerOverloaded(RuntimeError):
    """Admission rejected: the bounded request queue is full. The
    explicit alternative to unbounded queueing latency — clients back
    off or retry elsewhere."""


class ServerClosed(RuntimeError):
    """Submit after drain/close (or a request stranded by shutdown)."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before the server got to it."""


@dataclass
class _Pending:
    genotypes: np.ndarray  # (V,) int8, contiguous
    future: Future
    digest: str | None
    t_submit: float  # perf_counter at admission
    deadline: float | None  # perf_counter deadline, None = none
    finished: bool = False  # guards double in-flight decrement

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


@dataclass
class ServerStats:
    """Point-in-time request accounting (monotonic counters; the same
    numbers flow into the telemetry registry under ``serve.*``)."""

    admitted: int = 0
    completed: int = 0
    shed: int = 0
    cache_hits: int = 0
    errors: int = 0
    deadline_expired: int = 0
    cancelled: int = 0
    batches: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock,
                                 repr=False)

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "admitted": self.admitted,
                "completed": self.completed,
                "shed": self.shed,
                "cache_hits": self.cache_hits,
                "errors": self.errors,
                "deadline_expired": self.deadline_expired,
                "cancelled": self.cancelled,
                "batches": self.batches,
            }


class ProjectionServer:
    """Async micro-batching front over one :class:`ProjectionEngine`."""

    def __init__(self, engine: ProjectionEngine,
                 max_linger_s: float = 0.002,
                 max_queue: int = 64,
                 cache_entries: int = 256,
                 default_deadline_s: float | None = None,
                 drain_timeout_s: float = 60.0):
        self.engine = engine
        self.max_batch = engine.max_batch
        self.max_linger_s = float(max_linger_s)
        self.default_deadline_s = default_deadline_s
        self.drain_timeout_s = float(drain_timeout_s)
        self._q: queue.Queue[_Pending] = queue.Queue(
            maxsize=max(1, int(max_queue)))
        self._cache = ResultCache(cache_entries)
        self._cache_ns = engine.model.digest()
        self.stats = ServerStats()
        self._closed = False
        self._drained = False
        self._drain_clean = True
        self._stop = threading.Event()
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        # Serializes admission against the drain transition: drain flips
        # _closed under this lock, so a submit has either completed its
        # enqueue BEFORE the flip (drain then waits it out via
        # in_flight) or observes _closed — a request can never slip into
        # the queue after drain's backstop sweep and hang its Future.
        self._admission_lock = threading.Lock()
        # Serializes device work (the worker's batch step) against model
        # hot-reload — a reload must never tear a batch mid-flight.
        self._engine_lock = threading.Lock()
        self._idle = threading.Event()  # set while in_flight == 0
        self._idle.set()
        self._worker: threading.Thread | None = None
        # Worker supervision: recoveries are counted and time-stamped;
        # the health state machine reports degraded for a cooloff
        # window after each one (serve/health.py).
        self._worker_restarts = 0
        self._last_recovery = 0.0  # monotonic; 0 = never

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ProjectionServer":
        if self._worker is not None:
            raise RuntimeError("server already started")
        self._worker = threading.Thread(
            target=self._run, name="projection-serve-worker", daemon=True)
        self._worker.start()
        # Publish the backlog gauge BEFORE any request exists: a
        # supervised server's idle exemption reads it from the
        # heartbeat, and an unpublished gauge would leave a
        # pre-first-request idle server looking like a stalled batch
        # job to the watchdog.
        telemetry.gauge_set("serve.in_flight", 0)
        self._publish_health()
        return self

    # -- health state machine ----------------------------------------------

    def _publish_health(self) -> None:
        """Explicit transition-point publication (start, recovery,
        restage, drain) — the property also republishes on reads, but
        an explicit call is not mistakable for a dead statement."""
        H.publish(self._health_state())

    def _health_state(self) -> str:
        if self._closed:
            return H.DRAINING
        breaker = getattr(self.engine, "breaker", None)
        if breaker is not None and breaker.state != "closed":
            return H.DEGRADED
        if (self._last_recovery
                and time.monotonic() - self._last_recovery
                < H.DEGRADED_COOLOFF_S):
            return H.DEGRADED
        return H.HEALTHY

    @property
    def health(self) -> str:
        """healthy | degraded | draining (serve/health.py). Degraded =
        the batching worker recovered within the cooloff window, or the
        panel's store-read circuit breaker is open (cached-panel-only
        mode) — still serving either way. Every read republishes the
        ``serve.health`` gauge: several transitions are TIME-driven
        (cooloff expiry, the breaker's reset window) with no event to
        hook, so observation is what keeps the exported gauge from
        reading 'degraded' forever after a long-recovered incident."""
        state = self._health_state()
        H.publish(state)
        return state

    def health_info(self) -> dict:
        """The /healthz payload beyond the bare state string."""
        breaker = getattr(self.engine, "breaker", None)
        return {
            "status": self.health,
            "in_flight": self.in_flight,
            "worker_restarts": self._worker_restarts,
            "worker_alive": (self._worker is not None
                             and self._worker.is_alive()),
            "panel": getattr(self.engine, "panel_mode", "staged"),
            "breaker": (breaker.snapshot() if breaker is not None
                        else None),
        }

    def ready_info(self) -> dict:
        """Readiness (vs /healthz liveness): the single-model server is
        ready once its batching worker is alive and it is not draining
        — the engine's panel was staged before construction, so there
        is no warmup window beyond worker start. Degraded-but-serving
        is still ready."""
        alive = self._worker is not None and self._worker.is_alive()
        return {
            "ready": H.readiness(alive, self._closed),
            "worker_alive": alive,
            "draining": self._closed,
        }

    def stats_payload(self) -> dict:
        """The ``/stats`` payload — ONE coherent schema (documented in
        README "Serving"):

        - request accounting, flat (``admitted``/``completed``/
          ``shed``/``cache_hits``/``errors``/``deadline_expired``/
          ``cancelled``/``batches``),
        - ``latency_p50_ms``/``latency_p99_ms``/``batch_rows_mean``
          from the live telemetry histograms,
        - ``health`` — the full health-machine view
          (:meth:`health_info`: status string, worker restarts +
          liveness, panel mode, circuit-breaker snapshot), previously
          scattered between /healthz and ad-hoc /stats fields,
        - ``store_cache`` — the staged panel's decode-cache accounting
          (absent for non-store panels).
        """
        hists = telemetry.metrics_snapshot()["histograms"]
        lat = hists.get("serve.latency_s", {})
        rows = hists.get("serve.batch_rows", {})
        payload = {
            **self.stats.snapshot(),
            "latency_p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
            "latency_p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
            "batch_rows_mean": round(rows.get("mean", 0.0), 2),
            "health": self.health_info(),
        }
        store_cache = self.engine.store_cache_stats()
        if store_cache is not None:
            payload["store_cache"] = store_cache
        return payload

    def _note_recovery(self, reason: str) -> None:
        self._worker_restarts += 1
        self._last_recovery = time.monotonic()
        telemetry.count("serve.worker_restarts")
        self._publish_health()
        import warnings

        warnings.warn(
            f"projection server worker recovered ({reason}) — admitted "
            "requests were NOT dropped; health degrades for "
            f"{H.DEGRADED_COOLOFF_S:.0f}s",
            RuntimeWarning, stacklevel=3,
        )

    def _ensure_worker(self) -> None:
        """Supervision at admission: a worker thread that died
        unexpectedly (anything the in-loop recovery net could not
        catch) is replaced before the request queues — the queue's
        contents survive, so nothing admitted is dropped. The
        check-and-start runs under the admission lock: concurrent
        submits (the HTTP front is one handler thread per request)
        must not each observe the dead worker and start duplicate
        replacements — an orphaned extra loop would split batches and
        survive drain's single join."""
        w = self._worker
        if w is None or w.is_alive():
            return  # cheap unlocked fast path for the healthy case
        with self._admission_lock:
            w = self._worker
            if (w is None or w.is_alive() or self._stop.is_set()
                    or self._closed):
                return
            self._worker = threading.Thread(
                target=self._run, name="projection-serve-worker",
                daemon=True)
            self._worker.start()
        self._note_recovery("worker thread found dead at admission")

    def restage_panel(self, source_ref=None) -> bool:
        """Refresh the staged panel through the engine's circuit
        breaker (serialized against in-flight batches). False =
        cached-panel-only mode; health reports degraded while the
        breaker is open."""
        with self._engine_lock:
            ok = self.engine.restage(source_ref)
        self._publish_health()
        return ok

    def __enter__(self) -> "ProjectionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def drain(self, timeout: float | None = None) -> bool:
        """Close admission and wait for every in-flight request to
        resolve, then stop the worker. Returns True on a clean drain;
        on timeout (or a dead worker) the stragglers are failed with
        ServerClosed and counted as ``serve.drain_abandoned`` — an
        admitted request is ALWAYS answered, and the final telemetry
        flush tells the supervising parent how many hit the deadline.
        ``timeout=None`` uses the configured ``--drain-timeout-s``.
        Idempotent: a second drain (e.g. close() after drain()) returns
        the first one's verdict without re-walking the shutdown."""
        if timeout is None:
            timeout = self.drain_timeout_s
        with self._admission_lock:
            if self._drained:
                return self._drain_clean
            self._closed = True
        self._publish_health()  # -> draining
        clean = True
        with telemetry.span("serve.drain", cat="serve"):
            deadline = time.perf_counter() + timeout
            while not self._idle.wait(timeout=0.05):
                alive = self._worker is not None and self._worker.is_alive()
                if time.perf_counter() > deadline or not alive:
                    clean = False
                    break
            self._stop.set()
            if self._worker is not None:
                self._worker.join(timeout=max(1.0, timeout / 2))
                clean = clean and not self._worker.is_alive()
            # Backstop: anything the worker never picked up (it died, or
            # the drain timed out) is failed loudly, never dropped.
            abandoned = 0
            while True:
                try:
                    p = self._q.get_nowait()
                except queue.Empty:
                    break
                abandoned += 1
                self._fail(p, ServerClosed("server drained before this "
                                           "request was processed"))
            if abandoned:
                telemetry.count("serve.drain_abandoned", abandoned)
        self._drained = True
        self._drain_clean = clean
        return clean

    def close(self) -> None:
        if self._worker is None:
            self._closed = True
            return
        self.drain()  # idempotent: a no-op after an explicit drain()

    @property
    def in_flight(self) -> int:
        with self._in_flight_lock:
            return self._in_flight

    def reload_model(self, model) -> None:
        """Hot-swap the served model (same reference panel) without
        restarting: waits out any in-flight batch (the engine lock), then
        swaps + re-warms the engine and clears/re-namespaces the result
        cache so a stale coordinate can never be served."""
        if isinstance(model, (str, bytes)):
            from spark_examples_tpu.pipelines.project import load_model

            model = load_model(model)
        with self._engine_lock:
            # Re-namespace + clear BEFORE the engine swap: a submit
            # racing the reload either hits the old cache while the old
            # model is still installed (consistent), or — once the
            # namespace flips — misses and queues behind the engine
            # lock, to be served by (and cached under) the new model.
            # The inverse order had a window serving old-model cache
            # entries after the new model was live.
            old_ns = self._cache_ns
            self._cache_ns = model.digest()
            self._cache.clear()
            try:
                self.engine.reload_model(model)
            except BaseException:
                # Rejected reload (e.g. wrong panel): the old model is
                # still serving — restore its namespace (the cache is
                # already empty, so nothing stale can ever match).
                self._cache_ns = old_ns
                raise

    # -- client surface ----------------------------------------------------

    def submit(self, genotypes: np.ndarray,
               deadline_s: float | None = None) -> Future:
        """Admit one single-sample query; returns a Future resolving to
        its (1, k) coordinates. Raises ServerOverloaded when the bounded
        queue is full, ServerClosed after drain, ValueError on a
        malformed query."""
        if self._closed:
            raise ServerClosed("server is draining/closed")
        self._ensure_worker()
        g = np.ascontiguousarray(genotypes, dtype=np.int8)
        if g.ndim == 2 and g.shape[0] == 1:
            g = g[0]
        if g.ndim != 1 or g.shape[0] != self.engine.n_variants:
            raise ValueError(
                f"a query is one sample's ({self.engine.n_variants},) "
                f"int8 dosage vector, got shape {g.shape}"
            )
        t0 = time.perf_counter()
        digest = None
        if self._cache.capacity:
            digest = genotype_digest(g)
            hit = self._cache.get(digest, namespace=self._cache_ns)
            if hit is not None:
                telemetry.count("serve.cache_hits")
                telemetry.observe("serve.latency_s",
                                  time.perf_counter() - t0)
                with self.stats.lock:
                    self.stats.cache_hits += 1
                    self.stats.completed += 1
                fut: Future = Future()
                # Copy (k floats): a hit hands out the same writable
                # result a miss does, never the cache's frozen storage.
                fut.set_result(np.array(hit))
                return fut
            telemetry.count("serve.cache_misses")
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        pending = _Pending(
            genotypes=g,
            future=Future(),
            digest=digest,
            t_submit=t0,
            deadline=(t0 + deadline_s) if deadline_s else None,
        )
        # Admission happens under the drain-transition lock (see
        # __init__), and in_flight is raised BEFORE the put: the worker
        # may finish the request between put and a late increment, and
        # drain would then see a phantom in-flight forever.
        with self._admission_lock:
            if self._closed:
                raise ServerClosed("server is draining/closed")
            self._track(+1)
            try:
                self._q.put_nowait(pending)
            except queue.Full:
                self._track(-1)
                telemetry.count("serve.shed")
                with self.stats.lock:
                    self.stats.shed += 1
                raise ServerOverloaded(
                    f"admission queue full ({self._q.maxsize} waiting); "
                    "retry with backoff"
                ) from None
        telemetry.count("serve.requests")
        with self.stats.lock:
            self.stats.admitted += 1
        return pending.future

    def project(self, genotypes: np.ndarray,
                timeout: float | None = None,
                deadline_s: float | None = None) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(genotypes, deadline_s=deadline_s).result(
            timeout=timeout)

    # -- worker ------------------------------------------------------------

    def _track(self, delta: int) -> None:
        with self._in_flight_lock:
            self._in_flight += delta
            n = self._in_flight
            # The idle event flips INSIDE the lock: set/clear outside it
            # can interleave inverted (0->1 clears after 1->0 set) and
            # mark an occupied server idle — drain would then stop the
            # worker under a live request.
            if n == 0:
                self._idle.set()
            else:
                self._idle.clear()
            # Gauge published inside the lock for the same reason the
            # event flips inside it: out-of-order publishes would leave
            # the exported backlog reading stale/inverted.
            telemetry.gauge_set("serve.in_flight", n)

    def _finish(self, p: _Pending) -> None:
        if not p.finished:
            p.finished = True
            self._track(-1)

    def _fail(self, p: _Pending, exc: BaseException) -> None:
        if not p.future.done():
            p.future.set_exception(exc)
        self._finish(p)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self._collect()
                if batch:
                    try:
                        self._process(batch)
                    except BaseException as e:  # backstop: answer, don't die
                        for p in batch:
                            self._fail(p, e)
            except BaseException as e:
                # The supervision net around the loop body itself: a
                # failure in _collect (or in the failure handling
                # above) must not silently end the serving thread —
                # recover in place, leave the queue intact, degrade.
                if self._stop.is_set():
                    return
                self._note_recovery(f"worker loop error: {e!r}")
                time.sleep(0.005)  # never a hot crash loop

    def _collect(self) -> list[_Pending]:
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        linger_until = time.perf_counter() + self.max_linger_s
        while len(batch) < self.max_batch:
            remaining = linger_until - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _process(self, batch: list[_Pending]) -> None:
        with telemetry.span("serve.assemble", cat="serve"):
            live: list[_Pending] = []
            for p in batch:
                now = time.perf_counter()
                telemetry.observe("serve.enqueue_wait_s", now - p.t_submit)
                try:
                    # Chaos site: per admitted request (see module doc).
                    faults.fire("serve.request")
                except BaseException as e:
                    telemetry.count("serve.errors")
                    with self.stats.lock:
                        self.stats.errors += 1
                    self._fail(p, e)
                    continue
                if p.expired(now):
                    telemetry.count("serve.deadline_expired")
                    with self.stats.lock:
                        self.stats.deadline_expired += 1
                    self._fail(p, DeadlineExceeded(
                        "deadline passed before batch pickup"))
                    continue
                if not p.future.set_running_or_notify_cancel():
                    telemetry.count("serve.cancelled")
                    with self.stats.lock:
                        self.stats.cancelled += 1
                    self._finish(p)
                    continue
                live.append(p)
            if not live:
                return
            g = np.stack([p.genotypes for p in live])
        with telemetry.span("serve.device_step", cat="serve",
                            rows=len(live)):
            try:
                with self._engine_lock:
                    coords = self.engine.project_batch(g)
            except BaseException as e:
                telemetry.count("serve.errors", len(live))
                with self.stats.lock:
                    self.stats.errors += len(live)
                for p in live:
                    self._fail(p, e)
                return
        telemetry.observe("serve.batch_rows", len(live))
        with self.stats.lock:
            self.stats.batches += 1
        now = time.perf_counter()
        for p, row in zip(live, coords):
            result = row[None, :]
            if p.digest is not None:
                # Namespace read HERE, not at submit: a request that
                # raced a hot-reload was computed by the NEW model
                # (behind the engine lock), so its row must land under
                # the new namespace.
                self._cache.put(p.digest, result,
                                namespace=self._cache_ns)
            p.future.set_result(result)
            telemetry.observe("serve.latency_s", now - p.t_submit)
            with self.stats.lock:
                self.stats.completed += 1
            self._finish(p)

"""Multi-model request router: priority admission + the fleet worker.

One :class:`FleetRouter` is a whole serving fleet in one process: many
named routes (each a (model, panel) pair — serve/fleet.py builds them
from a manifest), one warm panel pool under an explicit budget
(serve/pool.py), one shared result cache namespaced by model
fingerprint, and ONE batching worker owning all device work — the same
single-writer discipline the single-model server proved, so panel
eviction/re-staging can never tear an in-flight batch.

Admission is class-aware (core/config.py ``PRIORITY_CLASSES``):
``interactive`` requests drain strictly before ``batch`` backfill, each
class has its own bounded queue (its shed threshold) and default
deadline, and batch-class coalescing yields early when interactive work
arrives. Served coordinates ride the exact single-model math
(:func:`serve.engine.batch_coords`), so every route is bit-identical to
its own single-model server and to the offline ``project`` CLI —
including immediately after an LRU eviction + re-stage of its panel
(pinned by tests/test_fleet.py).
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from spark_examples_tpu.core import faults, telemetry
from spark_examples_tpu.core.config import DEFAULT_PRIORITY, PRIORITY_CLASSES
from spark_examples_tpu.serve import engine as E
from spark_examples_tpu.serve import health as H
from spark_examples_tpu.serve.cache import ResultCache, genotype_digest
from spark_examples_tpu.serve.health import CircuitBreaker
from spark_examples_tpu.serve.pool import PanelPool, PanelUnavailable
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ServerClosed,
    ServerOverloaded,
)

# Literal gauge names per class (the graftlint telemetry-name rule
# wants literal declarations; the class picks WHICH literal at run
# time). Keys are the PRIORITY_CLASSES members.
_DEPTH_GAUGES = {
    PRIORITY_CLASSES[0]: "serve.priority.depth_interactive",
    PRIORITY_CLASSES[1]: "serve.priority.depth_batch",
}
_SHED_COUNTERS = {
    PRIORITY_CLASSES[0]: "serve.priority.shed_interactive",
    PRIORITY_CLASSES[1]: "serve.priority.shed_batch",
}


class UnknownRoute(ValueError):
    """Request names a route the fleet does not serve."""


def _copy_result(value):
    """Defensive copy of a cached/served result — (1, k) coords for
    project requests, an (ids, sims) tuple for topk — so no caller can
    mutate the cache's arrays in place."""
    if isinstance(value, tuple):
        return tuple(np.array(v) for v in value)
    return np.array(value)


@dataclass
class Route:
    """One servable (model, panel) pair, by name.

    ``panel_source_fn`` builds a FRESH panel source per stage (store
    readahead threads and memmaps live only for the stage's duration);
    ``n_variants`` is probed at load when the source knows its length
    (a store manifest does) and pinned by the first stage either way.
    """

    name: str
    ctx: E.ModelContext
    panel_source_fn: object  # () -> GenotypeSource
    block_variants: int
    n_variants: int | None = None
    # Manifest capability: this route also answers /neighbors (exact
    # query-vs-panel top-k through the model metric's PairSpec).
    topk: bool = False
    breaker: CircuitBreaker = field(default_factory=CircuitBreaker)
    # Per-class client-visible latency histograms (autoscale p99) and
    # request tallies — route-local, beside the process-wide serve.*
    # registry series.
    lat: dict = field(default_factory=dict)
    tally: dict = field(default_factory=dict)
    tally_lock: threading.Lock = field(default_factory=threading.Lock,
                                       repr=False)

    def __post_init__(self):
        for cls in PRIORITY_CLASSES:
            self.lat.setdefault(cls, telemetry.Histogram())
        self.tally.setdefault("admitted", 0)
        self.tally.setdefault("completed", 0)
        self.tally.setdefault("shed", 0)
        self.tally.setdefault("errors", 0)
        self.tally.setdefault("deadline_expired", 0)
        self.tally.setdefault("cancelled", 0)
        self.tally.setdefault("cache_hits", 0)
        self.tally.setdefault("stages", 0)
        self.tally.setdefault("topk_requests", 0)

    @property
    def cache_ns(self) -> str:
        return self.ctx.model.digest()

    @property
    def panel_bytes_hint(self) -> int | None:
        """Projected dense panel residency (n_ref x n_variants int8
        bytes), or None before the panel length is known. The router
        compares it to the pool budget to choose whole-panel staging
        vs shard-staged serving BEFORE any bytes move."""
        if self.n_variants is None:
            return None
        return int(self.ctx.n_ref) * int(self.n_variants)

    def bump(self, key: str, n: int = 1) -> None:
        with self.tally_lock:
            self.tally[key] += n

    def stage(self):
        """One panel stage: fresh source, identity-checked against the
        model, closed afterwards (readahead pools must not outlive the
        stage)."""
        src = self.panel_source_fn()
        try:
            from spark_examples_tpu.pipelines import project as P

            P.check_reference_panel(self.ctx.model, src)
            blocks, n_variants, nbytes = E.stage_blocks(
                src, self.block_variants)
            if self.n_variants is not None and n_variants != self.n_variants:
                raise ValueError(
                    f"route {self.name!r}: panel streamed {n_variants} "
                    f"variants, expected {self.n_variants} — the panel "
                    "changed under the model; refit it"
                )
        finally:
            _close_source(src)
        self.n_variants = n_variants
        self.bump("stages")
        return blocks, n_variants, nbytes


def _close_source(src) -> None:
    for obj in (src, getattr(src, "inner", None),
                getattr(src, "store", None)):
        close = getattr(obj, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass  # a close failure must not mask the stage outcome


@dataclass
class _Pending:
    route: str
    cls: str
    genotypes: np.ndarray  # (V,) int8, contiguous
    future: Future
    digest: str | None
    t_submit: float
    deadline: float | None
    # "project" -> (1, k) coordinates; "topk" -> ((1, k) neighbor
    # indices, (1, k) exact similarities). Batches coalesce only within
    # one kind — the two kinds run different compiled programs.
    kind: str = "project"
    k: int = 0  # topk only: neighbors requested
    finished: bool = False
    # Request-scoped trace context (serve/http.py mints it): the worker
    # writes phase timings into trace["phases"] BEFORE resolving the
    # future, so the handler reads them with happens-before for free.
    trace: dict | None = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class _PriorityQueues:
    """Bounded two-class admission: strict class order on take, per-
    class shed thresholds on put, same-route coalescing for the
    batcher. One condition guards both deques."""

    def __init__(self, bounds: dict[str, int]):
        self._bounds = dict(bounds)
        self._cond = threading.Condition()
        self._q: dict[str, deque] = {cls: deque()
                                     for cls in PRIORITY_CLASSES}
        self._route_depth: dict[str, int] = {}

    def put(self, p: _Pending) -> None:
        with self._cond:
            if len(self._q[p.cls]) >= self._bounds[p.cls]:
                telemetry.count(_SHED_COUNTERS[p.cls])
                raise ServerOverloaded(
                    f"{p.cls} admission queue full "
                    f"({self._bounds[p.cls]} waiting); retry with "
                    "backoff"
                )
            self._q[p.cls].append(p)
            self._route_depth[p.route] = \
                self._route_depth.get(p.route, 0) + 1
            telemetry.gauge_set(_DEPTH_GAUGES[p.cls],
                                float(len(self._q[p.cls])))
            self._cond.notify()

    def _first_class_locked(self) -> str | None:
        for cls in PRIORITY_CLASSES:
            if self._q[cls]:
                return cls
        return None

    def _pop_locked(self, cls: str) -> _Pending:
        p = self._q[cls].popleft()
        self._route_depth[p.route] = \
            max(0, self._route_depth.get(p.route, 1) - 1)
        telemetry.gauge_set(_DEPTH_GAUGES[cls],
                            float(len(self._q[cls])))
        return p

    def take_batch(self, max_batch: int, linger_s: float,
                   timeout: float = 0.05) -> list[_Pending]:
        """Up to ``max_batch`` same-route, same-class requests;
        interactive strictly first. Batch-class coalescing stops
        lingering the moment interactive work arrives (the preemption
        half of the priority contract)."""
        with self._cond:
            cls = self._first_class_locked()
            if cls is None:
                self._cond.wait(timeout)
                cls = self._first_class_locked()
                if cls is None:
                    return []
            if cls == PRIORITY_CLASSES[0]:
                head = self._q[cls][0]
                if any(self._q[other] and
                       self._q[other][0].t_submit < head.t_submit
                       for other in PRIORITY_CLASSES[1:]):
                    telemetry.count("serve.priority.preemptions")
            first = self._pop_locked(cls)
            batch = [first]
            linger_until = time.perf_counter() + linger_s

            def _same(p: _Pending) -> bool:
                # Same route AND same kind: project and topk rows run
                # different compiled programs, so a mixed batch cannot
                # share a device step.
                return p.route == first.route and p.kind == first.kind

            while len(batch) < max_batch:
                q = self._q[cls]
                while q and _same(q[0]) and len(batch) < max_batch:
                    batch.append(self._pop_locked(cls))
                if len(batch) >= max_batch:
                    break
                if q and not _same(q[0]):
                    break  # different route/kind waiting — serve it next
                if (cls != PRIORITY_CLASSES[0]
                        and self._q[PRIORITY_CLASSES[0]]):
                    break  # interactive arrived: stop padding batch work
                remaining = linger_until - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return batch

    def drain_all(self) -> list[_Pending]:
        with self._cond:
            out = []
            for cls in PRIORITY_CLASSES:
                out.extend(self._q[cls])
                self._q[cls].clear()
                telemetry.gauge_set(_DEPTH_GAUGES[cls], 0.0)
            self._route_depth.clear()
            return out

    def depths(self) -> dict[str, int]:
        with self._cond:
            return {cls: len(self._q[cls]) for cls in PRIORITY_CLASSES}

    def route_depth(self, route: str) -> int:
        with self._cond:
            return self._route_depth.get(route, 0)


class FleetRouter:
    """The multi-model server: routes + pool + priority admission +
    one batching worker. Build one from a manifest with
    :func:`serve.fleet.build_fleet` (or hand it routes directly)."""

    def __init__(self, pool: PanelPool,
                 max_batch: int = 8,
                 max_linger_s: float = 0.002,
                 cache_entries: int = 256,
                 queue_bounds: dict[str, int] | None = None,
                 class_deadlines_s: dict[str, float] | None = None,
                 drain_timeout_s: float = 60.0):
        self.routes: dict[str, Route] = {}
        self.pool = pool
        self.max_batch = int(max_batch)
        self.max_linger_s = float(max_linger_s)
        self._queues = _PriorityQueues(
            queue_bounds
            or {cls: 64 for cls in PRIORITY_CLASSES})
        self._class_deadlines_s = dict(class_deadlines_s or {})
        self.drain_timeout_s = float(drain_timeout_s)
        self._cache = ResultCache(cache_entries)
        self._closed = False
        self._drained = False
        self._drain_clean = True
        self._stop = threading.Event()
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        self._admission_lock = threading.Lock()
        # Serializes device/pool work (the worker's batch step) against
        # route admin (load/unload, explicit evictions).
        self._engine_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        self._worker: threading.Thread | None = None
        self._worker_restarts = 0
        self._last_recovery = 0.0
        # Routes explicitly warmed (controller placement / startup):
        # readiness means every one of THESE is staged right now.
        # Lazily-staged routes don't count — LRU churn on cold routes
        # must not flap /readyz.
        self._warmed: set[str] = set()

    # -- route admin -------------------------------------------------------

    def add_route(self, route: Route) -> None:
        with self._engine_lock:
            if route.name in self.routes:
                raise ValueError(
                    f"route {route.name!r} is already loaded")
            self.routes[route.name] = route
            telemetry.gauge_set("fleet.routes", float(len(self.routes)))

    def unload_route(self, name: str) -> bool:
        """Drop a route: its panel leaves the pool and its result-cache
        namespace is evicted whole (the lifecycle fix — entries of a
        gone model must not squat in the LRU until pressure happens to
        push them out)."""
        with self._engine_lock:
            route = self.routes.pop(name, None)
            if route is None:
                return False
            self._warmed.discard(name)
            self.pool.remove(name)
            evicted = self._cache.evict_namespace(route.cache_ns)
            if evicted:
                telemetry.count("fleet.cache_namespace_evictions",
                                evicted)
            telemetry.gauge_set("fleet.routes", float(len(self.routes)))
        self.publish_autoscale()
        return True

    def warm_route(self, name: str) -> None:
        """Stage a route's panel now (startup warming) instead of on
        first demand. Over-budget routes (panel_bytes_hint exceeds the
        pool budget) have no warm state to pre-stage — they serve
        shard-staged per request — so warming them is a no-op with a
        warning, not a budget violation."""
        route = self._route(name)
        hint = route.panel_bytes_hint
        if hint is not None and hint > self.pool.budget_bytes:
            warnings.warn(
                f"route {name!r}: its panel (~{hint} B) exceeds the "
                f"pool budget ({self.pool.budget_bytes} B), so it "
                "serves shard-staged per request and cannot be kept "
                "warm — raise --fleet-budget-mb to warm it",
                RuntimeWarning, stacklevel=2,
            )
            return
        with self._engine_lock:
            self.pool.acquire(route.name, route.stage,
                              breaker=route.breaker)
            self._warmed.add(route.name)
        self.publish_autoscale()

    def _route(self, name: str) -> Route:
        route = self.routes.get(name)
        if route is None:
            raise UnknownRoute(
                f"unknown route {name!r}; loaded routes: "
                f"{sorted(self.routes) or '(none)'}"
            )
        return route

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetRouter":
        if self._worker is not None:
            raise RuntimeError("fleet router already started")
        self._worker = threading.Thread(
            target=self._run, name="fleet-serve-worker", daemon=True)
        self._worker.start()
        telemetry.gauge_set("serve.in_flight", 0)
        telemetry.gauge_set("fleet.routes", float(len(self.routes)))
        H.publish(self.health)
        return self

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def health(self) -> str:
        """Fleet health: draining once closed; else the worst member
        state (health.worst) over every route's breaker and the
        worker's recovery cooloff — one route serving cached-only
        degrades the whole process's /healthz."""
        if self._closed:
            return H.DRAINING
        states = [
            H.DEGRADED if r.breaker.state != "closed" else H.HEALTHY
            for r in list(self.routes.values())  # snapshot: routes
            # mutate under the engine lock while scrapes read freely
        ]
        states.append(
            H.DEGRADED
            if (self._last_recovery
                and time.monotonic() - self._last_recovery
                < H.DEGRADED_COOLOFF_S)
            else H.HEALTHY)
        return H.worst(states)

    def health_info(self) -> dict:
        state = self.health
        H.publish(state)
        return {
            "status": state,
            "in_flight": self.in_flight,
            "worker_restarts": self._worker_restarts,
            "worker_alive": (self._worker is not None
                             and self._worker.is_alive()),
            "routes": {
                name: {
                    "staged": self.pool.is_staged(name),
                    "breaker": r.breaker.snapshot(),
                }
                for name, r in sorted(list(self.routes.items()))
            },
            "pool": self.pool.stats(),
        }

    def ready_info(self) -> dict:
        """Readiness (vs /healthz liveness): a replica is READY when
        its worker is alive, it is not draining, and every explicitly
        warmed route is staged in the pool right now — the controller
        gates admission on this so hedges never land on a replica
        still staging its warm set. A degraded-but-serving replica is
        ready; a warming one is not."""
        alive = self._worker is not None and self._worker.is_alive()
        warmed = sorted(self._warmed)
        missing = [n for n in warmed if not self.pool.is_staged(n)]
        return {
            "ready": H.readiness(alive, self._closed, missing),
            "worker_alive": alive,
            "draining": self._closed,
            "warmed_routes": warmed,
            "unstaged_routes": missing,
        }

    @property
    def in_flight(self) -> int:
        with self._in_flight_lock:
            return self._in_flight

    def drain(self, timeout: float | None = None) -> bool:
        """Close admission, answer everything admitted, stop the
        worker; stragglers are failed loudly (ServerClosed) and
        counted as ``serve.drain_abandoned``, never dropped.
        ``timeout=None`` uses the configured ``--drain-timeout-s``.
        Idempotent."""
        if timeout is None:
            timeout = self.drain_timeout_s
        with self._admission_lock:
            if self._drained:
                return self._drain_clean
            self._closed = True
        H.publish(self.health)  # -> draining
        clean = True
        with telemetry.span("serve.drain", cat="serve"):
            deadline = time.perf_counter() + timeout
            while not self._idle.wait(timeout=0.05):
                alive = (self._worker is not None
                         and self._worker.is_alive())
                if time.perf_counter() > deadline or not alive:
                    clean = False
                    break
            self._stop.set()
            if self._worker is not None:
                self._worker.join(timeout=max(1.0, timeout / 2))
                clean = clean and not self._worker.is_alive()
            abandoned = 0
            for p in self._queues.drain_all():
                abandoned += 1
                self._fail(p, ServerClosed(
                    "fleet drained before this request was processed"))
            if abandoned:
                # The supervising parent reads this from the final
                # telemetry flush: how many admitted requests hit the
                # drain deadline unanswered (failed loudly, not lost).
                telemetry.count("serve.drain_abandoned", abandoned)
        self._drained = True
        self._drain_clean = clean
        return clean

    def close(self) -> None:
        if self._worker is None:
            self._closed = True
            return
        self.drain()

    # -- client surface ----------------------------------------------------

    def submit(self, route_name: str, genotypes: np.ndarray,
               priority: str = DEFAULT_PRIORITY,
               deadline_s: float | None = None,
               trace: dict | None = None,
               kind: str = "project", k: int = 0) -> Future:
        """Admit one single-sample query against ``route_name``;
        returns a Future resolving to its (1, k) coordinates — or, for
        ``kind="topk"``, to an ``(ids, sims)`` pair of (1, k) arrays
        (exact nearest panel neighbors). Raises :class:`UnknownRoute`,
        :class:`ServerOverloaded` (the class's bounded queue is full),
        :class:`ServerClosed` after drain, or ValueError on a malformed
        query / unknown priority class / a topk request against a route
        without the capability."""
        if self._closed:
            raise ServerClosed("fleet is draining/closed")
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {priority!r}; classes: "
                f"{' | '.join(PRIORITY_CLASSES)}"
            )
        route = self._route(route_name)
        if kind not in ("project", "topk"):
            raise ValueError(f"unknown request kind {kind!r}")
        if kind == "topk":
            if not route.topk:
                raise ValueError(
                    f"route {route_name!r} does not declare the 'topk' "
                    "capability — add \"topk\": true to its manifest "
                    "entry"
                )
            if not 1 <= int(k) <= 65536:
                raise ValueError(
                    f"topk request needs 1 <= k <= 65536, got {k!r}")
            k = int(k)
            route.bump("topk_requests")
            telemetry.count("neighbors.requests")
        g = np.ascontiguousarray(genotypes, dtype=np.int8)
        if g.ndim == 2 and g.shape[0] == 1:
            g = g[0]
        if g.ndim != 1 or (route.n_variants is not None
                           and g.shape[0] != route.n_variants):
            raise ValueError(
                f"a query is one sample's ({route.n_variants},) int8 "
                f"dosage vector for route {route_name!r}, got shape "
                f"{g.shape}"
            )
        t0 = time.perf_counter()
        digest = None
        if self._cache.capacity:
            # topk results live beside project results in the same
            # model-fingerprint namespace; the digest's namespace arg
            # keys the KIND (and k), so the two can never answer each
            # other — while unload_route still evicts both at once.
            digest = genotype_digest(
                g, namespace=f"topk:{k}" if kind == "topk" else "")
            hit = self._cache.get(digest, namespace=route.cache_ns)
            if hit is not None:
                telemetry.count("serve.cache_hits")
                telemetry.observe("serve.latency_s",
                                  time.perf_counter() - t0)
                route.bump("cache_hits")
                route.bump("completed")
                route.lat[priority].record(time.perf_counter() - t0)
                if trace is not None:
                    trace["cache_hit"] = True
                    trace.setdefault("phases", {})["cache"] = \
                        time.perf_counter() - t0
                fut: Future = Future()
                fut.set_result(_copy_result(hit))
                return fut
            telemetry.count("serve.cache_misses")
        if deadline_s is None:
            deadline_s = self._class_deadlines_s.get(priority) or None
        pending = _Pending(
            route=route_name,
            cls=priority,
            genotypes=g,
            future=Future(),
            digest=digest,
            t_submit=t0,
            deadline=(t0 + deadline_s) if deadline_s else None,
            kind=kind,
            k=k,
            trace=trace,
        )
        with self._admission_lock:
            if self._closed:
                raise ServerClosed("fleet is draining/closed")
            self._track(+1)
            try:
                self._queues.put(pending)
            except ServerOverloaded:
                self._track(-1)
                telemetry.count("serve.shed")
                route.bump("shed")
                raise
        telemetry.count("serve.requests")
        route.bump("admitted")
        return pending.future

    def project(self, route_name: str, genotypes: np.ndarray,
                timeout: float | None = None,
                priority: str = DEFAULT_PRIORITY,
                deadline_s: float | None = None,
                trace: dict | None = None) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(route_name, genotypes, priority=priority,
                           deadline_s=deadline_s,
                           trace=trace).result(timeout=timeout)

    def topk(self, route_name: str, genotypes: np.ndarray, k: int,
             timeout: float | None = None,
             priority: str = DEFAULT_PRIORITY,
             deadline_s: float | None = None,
             trace: dict | None = None) -> tuple[np.ndarray, np.ndarray]:
        """Synchronous topk convenience: ``(ids, sims)`` (1, k) arrays
        of the query's exact nearest panel neighbors."""
        return self.submit(route_name, genotypes, priority=priority,
                           deadline_s=deadline_s, trace=trace,
                           kind="topk", k=k).result(timeout=timeout)

    # -- introspection -----------------------------------------------------

    def queue_depths(self) -> dict[str, int]:
        return self._queues.depths()

    def publish_autoscale(self) -> None:
        """Per-route autoscale gauges onto the live telemetry plane
        (scraped via GET /metrics): queue depth, served p99, shed rate,
        panel residency — recomputed at publish time, so a scrape
        always reads the current truth."""
        for name, route in list(self.routes.items()):
            prefix = "fleet.route." + name
            telemetry.gauge_set(prefix + ".queue_depth",
                                float(self._queues.route_depth(name)))
            p99 = max(route.lat[cls].quantile(0.99)
                      for cls in PRIORITY_CLASSES)
            telemetry.gauge_set(prefix + ".p99_s", p99)
            with route.tally_lock:
                shed = route.tally["shed"]
                offered = route.tally["admitted"] + shed
            telemetry.gauge_set(
                prefix + ".shed_rate", shed / offered if offered else 0.0)
            telemetry.gauge_set(
                prefix + ".staged",
                1.0 if self.pool.is_staged(name) else 0.0)
            with route.tally_lock:
                topk_reqs = route.tally["topk_requests"]
            # The topk path is first-class autoscale input: a route
            # whose load is mostly /neighbors must scale on it too.
            telemetry.gauge_set(prefix + ".topk_requests",
                                float(topk_reqs))
        telemetry.gauge_set("fleet.routes", float(len(self.routes)))
        telemetry.gauge_set("fleet.pool_bytes",
                            float(self.pool.resident_bytes()))
        telemetry.gauge_set("fleet.pool_pressure", self.pool.pressure())

    def stats_payload(self) -> dict:
        """The fleet /stats payload: pool + per-route accounting with
        per-class latency digests (README 'Fleet serving')."""
        self.publish_autoscale()
        per_route = {}
        for name, route in sorted(list(self.routes.items())):
            with route.tally_lock:
                tally = dict(route.tally)
            per_route[name] = {
                **tally,
                "staged": self.pool.is_staged(name),
                "topk": route.topk,
                "n_variants": route.n_variants,
                "queue_depth": self._queues.route_depth(name),
                "breaker": route.breaker.snapshot(),
                "latency_ms": {
                    cls: {
                        "p50": round(
                            route.lat[cls].quantile(0.5) * 1e3, 3),
                        "p99": round(
                            route.lat[cls].quantile(0.99) * 1e3, 3),
                        "count": route.lat[cls].count,
                    }
                    for cls in PRIORITY_CLASSES
                },
            }
        return {
            "health": self.health_info(),
            "queues": self.queue_depths(),
            "pool": self.pool.stats(),
            "result_cache": self._cache.stats(),
            "routes": per_route,
        }

    # -- worker ------------------------------------------------------------

    def _track(self, delta: int) -> None:
        with self._in_flight_lock:
            self._in_flight += delta
            n = self._in_flight
            if n == 0:
                self._idle.set()
            else:
                self._idle.clear()
            telemetry.gauge_set("serve.in_flight", n)

    def _finish(self, p: _Pending) -> None:
        if not p.finished:
            p.finished = True
            self._track(-1)

    def _fail(self, p: _Pending, exc: BaseException) -> None:
        if not p.future.done():
            p.future.set_exception(exc)
        self._finish(p)

    def _note_recovery(self, reason: str) -> None:
        self._worker_restarts += 1
        self._last_recovery = time.monotonic()
        telemetry.count("serve.worker_restarts")
        warnings.warn(
            f"fleet worker recovered ({reason}) — admitted requests "
            "were NOT dropped; health degrades for "
            f"{H.DEGRADED_COOLOFF_S:.0f}s",
            RuntimeWarning, stacklevel=2,
        )

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self._queues.take_batch(
                    self.max_batch, self.max_linger_s)
                if batch:
                    try:
                        self._process(batch)
                    except BaseException as e:
                        for p in batch:
                            self._fail(p, e)
            except BaseException as e:
                if self._stop.is_set():
                    return
                self._note_recovery(f"worker loop error: {e!r}")
                time.sleep(0.005)

    def _sharded_blocks(self, route: Route):
        """Shard-staged panel feed for a route whose panel exceeds the
        pool budget: a generator of ``(device_block, meta)`` pairs that
        stages the panel as a SEQUENCE of store-fed shards (engine.
        shard_stream), each at most one budget's worth of bytes. Every
        shard stage runs the same protocol as a pooled stage — breaker
        admission (PanelUnavailable when open; the first shard of the
        next request is the half-open probe), a ``fleet.stage`` span
        with the ``fleet.stage`` fault site fired first, breaker
        feedback — and charges the pool as transient residency
        (evicting other routes' warm panels, never evictable itself)
        for exactly as long as its blocks are being consumed. The
        consumer is the UNCHANGED batch_coords/batch_pair_sims loop:
        integer cross accumulation is partition-invariant, so sharded
        answers are bit-identical to whole-panel ones by construction.
        Runs under the engine lock (only the worker consumes it)."""
        src = route.panel_source_fn()
        try:
            from spark_examples_tpu.pipelines import project as P

            P.check_reference_panel(route.ctx.model, src)
            it = E.shard_stream(src, route.block_variants,
                                self.pool.budget_bytes)
            shard = 0
            stop = 0
            while True:
                if not route.breaker.allow():
                    raise PanelUnavailable(
                        f"route {route.name!r}: shard {shard} cannot "
                        f"stage — the store breaker is "
                        f"{route.breaker.state}; attempts are short-"
                        "circuited until the reset window's probe"
                    )
                try:
                    with telemetry.span("fleet.stage", cat="fleet",
                                        route=route.name, shard=shard):
                        faults.fire("fleet.stage")
                        item = next(it, None)
                except Exception:
                    route.breaker.record_failure()
                    raise
                except BaseException:
                    # SIGINT/SystemExit mid-stage says nothing about
                    # the store: give the half-open probe slot back.
                    route.breaker.release_probe()
                    raise
                route.breaker.record_success()
                if item is None:
                    break
                blocks, nbytes = item
                telemetry.count("fleet.shard_stages")
                shard += 1
                with self.pool.transient(route.name, nbytes):
                    yield from blocks
                stop = blocks[-1][1].stop
                del blocks  # free the shard before staging the next
            if stop != route.n_variants:
                raise ValueError(
                    f"route {route.name!r}: sharded panel streamed "
                    f"{stop} variants, expected {route.n_variants} — "
                    "the panel changed under the model; refit it"
                )
            route.bump("stages")
        finally:
            _close_source(src)

    def _process(self, batch: list[_Pending]) -> None:
        route = self.routes.get(batch[0].route)
        with telemetry.span("serve.assemble", cat="serve"):
            live: list[_Pending] = []
            for p in batch:
                now = time.perf_counter()
                telemetry.observe("serve.enqueue_wait_s",
                                  now - p.t_submit)
                if p.trace is not None:
                    p.trace.setdefault("phases", {})["queue"] = \
                        now - p.t_submit
                try:
                    faults.fire("serve.request")
                except BaseException as e:
                    telemetry.count("serve.errors")
                    if route is not None:
                        route.bump("errors")
                    self._fail(p, e)
                    continue
                if route is None:
                    # Unloaded between admission and pickup: answered,
                    # never dropped.
                    self._fail(p, UnknownRoute(
                        f"route {p.route!r} was unloaded while this "
                        "request waited"))
                    continue
                if p.expired(now):
                    telemetry.count("serve.deadline_expired")
                    route.bump("deadline_expired")
                    self._fail(p, DeadlineExceeded(
                        "deadline passed before batch pickup"))
                    continue
                if not p.future.set_running_or_notify_cancel():
                    telemetry.count("serve.cancelled")
                    route.bump("cancelled")
                    self._finish(p)
                    continue
                live.append(p)
            if live and route.n_variants is None:
                # Pre-first-stage a route built over a length-blind
                # source admits any query length; a mixed batch would
                # blow up np.stack and fail EVERYONE with an error
                # about someone else's query. Fail only the rows that
                # disagree with the batch head — the stage itself then
                # validates the survivors against the real panel.
                want = live[0].genotypes.shape[0]
                kept = []
                for p in live:
                    if p.genotypes.shape[0] != want:
                        telemetry.count("serve.errors")
                        route.bump("errors")
                        self._fail(p, ValueError(
                            f"query carries {p.genotypes.shape[0]} "
                            f"variants but this batch's head carries "
                            f"{want} (route {route.name!r} has not "
                            "staged its panel yet)"))
                    else:
                        kept.append(p)
                live = kept
            if not live:
                return
            g = np.stack([p.genotypes for p in live])
        t_device = time.perf_counter()
        cold = not self.pool.is_staged(route.name)
        stage_s = 0.0
        kind = live[0].kind  # take_batch coalesces within one kind
        with telemetry.span("serve.device_step", cat="serve",
                            rows=len(live), route=route.name):
            hint = route.panel_bytes_hint
            sharded = hint is not None and hint > self.pool.budget_bytes
            try:
                with self._engine_lock:
                    if sharded:
                        # The panel cannot fit warm: feed the SAME
                        # batch loop a shard-staged block stream
                        # instead of a pooled panel. Staging overlaps
                        # compute, so stage_s stays 0 and every
                        # request is honestly cold.
                        telemetry.gauge_set(
                            "fleet.panel_over_budget_x",
                            hint / self.pool.budget_bytes)
                        blocks = self._sharded_blocks(route)
                        n_variants = route.n_variants
                        t_compute = time.perf_counter()
                    else:
                        panel = self.pool.acquire(
                            route.name, route.stage,
                            breaker=route.breaker)
                        blocks = panel.blocks
                        n_variants = panel.n_variants
                        t_compute = time.perf_counter()
                        if cold:
                            stage_s = t_compute - t_device
                    if kind == "topk":
                        sims = E.batch_pair_sims(
                            route.ctx, blocks, g, self.max_batch,
                            n_variants)
                    else:
                        coords = E.batch_coords(
                            route.ctx, blocks, g, self.max_batch,
                            n_variants)
            except BaseException as e:  # incl. PanelUnavailable
                telemetry.count("serve.errors", len(live))
                route.bump("errors", len(live))
                for p in live:
                    self._fail(p, e)
                return
        compute_s = time.perf_counter() - t_compute
        telemetry.observe("serve.batch_rows", len(live))
        if kind == "topk":
            # Per-row reduction on the host: each request may ask a
            # different k, and the reduction is the SAME topk_rows the
            # offline CLI runs — bit-identity by shared code.
            from spark_examples_tpu.neighbors.engine import topk_rows

            results = [
                (p, topk_rows(sims[i:i + 1], p.k))
                for i, p in enumerate(live)
            ]
        else:
            results = [(p, row[None, :]) for p, row in zip(live, coords)]
        if self._cache.capacity:
            # Cache puts under the engine lock: unload_route (same
            # lock) may have raced batch completion, and entries put
            # AFTER its namespace eviction would squat unreclaimable
            # in the LRU — the exact leak evict_namespace exists to
            # close. Still loaded -> put; gone -> skip.
            with self._engine_lock:
                if self.routes.get(route.name) is route:
                    for p, result in results:
                        if p.digest is not None:
                            self._cache.put(p.digest, result,
                                            namespace=route.cache_ns)
        now = time.perf_counter()
        for p, result in results:
            if p.trace is not None:
                # Phase write-back BEFORE set_result: the HTTP handler
                # reads trace["phases"] after .result() returns, so
                # future resolution is the happens-before edge.
                ph = p.trace.setdefault("phases", {})
                if stage_s:
                    ph["stage"] = stage_s
                ph["compute"] = compute_s
                p.trace["cold_start"] = cold
                if p.trace.get("sampled"):
                    ids = {"trace_id": p.trace.get("trace_id", ""),
                           "span_id": p.trace.get("span_id", "")}
                    telemetry.span_at(
                        "trace.queue", p.t_submit,
                        ph.get("queue", 0.0),
                        route=p.route, cls=p.cls, **ids)
                    telemetry.span_at(
                        "trace.compute", t_device, now - t_device,
                        route=p.route, cls=p.cls, rows=len(results),
                        cold_start=cold,
                        stage_s=round(stage_s, 6), **ids)
            p.future.set_result(result)
            dt = now - p.t_submit
            telemetry.observe("serve.latency_s", dt)
            route.lat[p.cls].record(dt)
            route.bump("completed")
            self._finish(p)

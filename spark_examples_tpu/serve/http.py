"""Thin stdlib HTTP front for the projection server.

One process, no dependencies: ``ThreadingHTTPServer`` handlers block on
the projection Future while the batching worker coalesces concurrent
requests — HTTP concurrency IS the micro-batch source. Endpoints:

- ``POST /project`` — body ``{"genotypes": [<V int8 dosages>],
  "deadline_ms": <optional>}``; answers ``{"coords": [[...]]}``.
  Errors map onto status codes the envelope semantics imply: 429
  overloaded (shed), 503 draining, 504 deadline, 400 malformed.
- ``GET /healthz`` — liveness + in-flight/backlog counts.
- ``GET /stats`` — the coherent operator payload
  (:meth:`ProjectionServer.stats_payload`): request accounting,
  latency digest, the full health-machine view (status, breaker
  snapshot, worker restarts), and the staged panel's store-cache
  accounting.
- ``GET /metrics`` — the live telemetry registry as Prometheus
  exposition text (core/live.py — the same renderer the ``--live-port``
  batch sidecar uses, so serving and batch jobs scrape identically).
- ``GET /debug/telemetry`` — the full ``telemetry.live_snapshot()``
  JSON: every counter/gauge/histogram plus a rolling ring of recent
  trace events and the run_id/attempt/rank identity.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from spark_examples_tpu.core import live as live_view
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ProjectionServer,
    ServerClosed,
    ServerOverloaded,
)


def _make_handler(pserver: ProjectionServer):
    class Handler(BaseHTTPRequestHandler):
        # Silence the default per-request stderr lines (telemetry is the
        # observability surface, not the access log).
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib API)
            if self.path == "/healthz":
                # The health state machine (serve/health.py): healthy |
                # degraded (worker recovered recently, or the store
                # breaker is open and the panel is cached-only) |
                # draining — plus the evidence behind the verdict.
                self._reply(200, {
                    **pserver.health_info(),
                    "n_variants": pserver.engine.n_variants,
                    "n_components": pserver.engine.n_components,
                    "max_batch": pserver.max_batch,
                })
                return
            if self.path == "/stats":
                self._reply(200, pserver.stats_payload())
                return
            if self.path == "/metrics":
                live_view.reply_metrics(self)
                return
            if self.path == "/debug/telemetry":
                live_view.reply_debug_telemetry(self)
                return
            self._reply(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802 (stdlib API)
            if self.path != "/project":
                self._reply(404, {"error": f"unknown path {self.path!r}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                raw = np.asarray(req["genotypes"])
                if raw.dtype.kind not in "iu":
                    raise ValueError(
                        "genotypes must be integer dosages "
                        f"(got {raw.dtype} values)")
                # dtype= on the original list (not .astype, which wraps
                # silently): an out-of-int8-range dosage raises here and
                # becomes a 400, never a dropped socket.
                genotypes = np.asarray(req["genotypes"], dtype=np.int8)
                deadline_ms = req.get("deadline_ms")
                # Converted HERE so a non-numeric deadline is a 400
                # (client error), not a 500 from deep in the submit.
                deadline_s = (
                    float(deadline_ms) / 1e3 if deadline_ms else None)
            except (ValueError, KeyError, TypeError, OverflowError) as e:
                self._reply(400, {"error": f"bad request body: {e}"})
                return
            try:
                coords = pserver.project(genotypes, deadline_s=deadline_s)
            except ServerOverloaded as e:
                self._reply(429, {"error": str(e)})
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e)})
            except ServerClosed as e:
                self._reply(503, {"error": str(e)})
            except ValueError as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # answered, never a dropped socket
                self._reply(500, {"error": repr(e)})
            else:
                self._reply(200, {"coords": coords.tolist()})

    return Handler


class ProjectionHTTPServer:
    """Lifecycle wrapper: bind (port 0 = ephemeral), serve in a daemon
    thread or in the foreground, shut down idempotently."""

    def __init__(self, pserver: ProjectionServer,
                 host: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer(
            (host, port), _make_handler(pserver))
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def serve_in_thread(self) -> "ProjectionHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="projection-http",
            daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def start_http_server(pserver: ProjectionServer, host: str = "127.0.0.1",
                      port: int = 0) -> ProjectionHTTPServer:
    """Bind + serve in a background thread; returns the wrapper (read
    ``.port`` for the ephemeral bind)."""
    return ProjectionHTTPServer(pserver, host=host, port=port).serve_in_thread()

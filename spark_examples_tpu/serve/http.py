"""Thin stdlib HTTP front for the projection server.

One process, no dependencies: ``ThreadingHTTPServer`` handlers block on
the projection Future while the batching worker coalesces concurrent
requests — HTTP concurrency IS the micro-batch source. Endpoints:

- ``POST /project`` — body ``{"genotypes": [<V int8 dosages>],
  "deadline_ms": <optional>}``; answers ``{"coords": [[...]]}``.
  Errors map onto status codes the envelope semantics imply: 429
  overloaded (shed), 503 draining, 504 deadline, 400 malformed.
- ``GET /healthz`` — liveness + in-flight/backlog counts.
- ``GET /readyz`` — readiness (the controller's admission gate): 200
  once the batching worker is alive, the server is not draining, and
  — on the fleet front — every explicitly warmed route is staged;
  503 with the evidence otherwise. Liveness and readiness diverge on
  purpose: a replica staging its warm set is alive but must not take
  hedged traffic yet.
- ``GET /stats`` — the coherent operator payload
  (:meth:`ProjectionServer.stats_payload`): request accounting,
  latency digest, the full health-machine view (status, breaker
  snapshot, worker restarts), and the staged panel's store-cache
  accounting.
- ``GET /metrics`` — the live telemetry registry as Prometheus
  exposition text (core/live.py — the same renderer the ``--live-port``
  batch sidecar uses, so serving and batch jobs scrape identically).
- ``GET /debug/telemetry`` — the full ``telemetry.live_snapshot()``
  JSON: every counter/gauge/histogram plus a rolling ring of recent
  trace events and the run_id/attempt/rank identity.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from spark_examples_tpu.core import live as live_view
from spark_examples_tpu.core import telemetry
from spark_examples_tpu.serve.server import (
    DeadlineExceeded,
    ProjectionServer,
    ServerClosed,
    ServerOverloaded,
)

_TRACE_ID_MAX = 64


def _request_trace_id(handler) -> str:
    """Accept the client's X-Trace-Id (sanitized: url-safe token chars,
    bounded length) or mint a fresh one — either way the id is echoed
    back, so client and server logs join on it without guessing."""
    raw = (handler.headers.get("X-Trace-Id") or "").strip()
    if (raw and len(raw) <= _TRACE_ID_MAX
            and all(c.isalnum() or c in "-_." for c in raw)):
        return raw
    return telemetry.new_trace_id()


def _server_timing(phases: dict) -> str:
    """The per-request phase breakdown as a Server-Timing header value
    (milliseconds, RFC 8941 shape: ``queue;dur=1.2, compute;dur=3.4``)."""
    return ", ".join(f"{k};dur={v * 1e3:.3f}"
                     for k, v in phases.items()
                     if isinstance(v, (int, float)))


def _reply_debug_requests(handler) -> None:
    """GET /debug/requests: the slowest-K request exemplar ring keyed
    by trace_id, plus the active sample rate."""
    body = json.dumps({
        "exemplars": telemetry.request_exemplars(),
        "trace_sample": telemetry.trace_sample(),
    }, default=str).encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.send_header("X-Run-Id", telemetry.run_id())
    handler.end_headers()
    handler.wfile.write(body)


def _parse_project_body(handler) -> tuple[np.ndarray, float | None, dict]:
    """Shared POST /project body decoding: (genotypes, deadline_s, raw
    request dict). Raises the body's problem for the caller's 400."""
    length = int(handler.headers.get("Content-Length", "0"))
    req = json.loads(handler.rfile.read(length) or b"{}")
    raw = np.asarray(req["genotypes"])
    if raw.dtype.kind not in "iu":
        raise ValueError(
            f"genotypes must be integer dosages (got {raw.dtype} values)")
    # dtype= on the original list (not .astype, which wraps silently):
    # an out-of-int8-range dosage raises here and becomes a 400, never
    # a dropped socket.
    genotypes = np.asarray(req["genotypes"], dtype=np.int8)
    deadline_ms = req.get("deadline_ms")
    # Converted HERE so a non-numeric deadline is a 400 (client error),
    # not a 500 from deep in the submit.
    deadline_s = float(deadline_ms) / 1e3 if deadline_ms else None
    return genotypes, deadline_s, req


def _make_handler(pserver: ProjectionServer):
    class Handler(BaseHTTPRequestHandler):
        # Silence the default per-request stderr lines (telemetry is the
        # observability surface, not the access log).
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            # Every answer names the serving run: client-side error
            # records join server-side traces on this id.
            self.send_header("X-Run-Id", telemetry.run_id())
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib API)
            if self.path == "/healthz":
                # The health state machine (serve/health.py): healthy |
                # degraded (worker recovered recently, or the store
                # breaker is open and the panel is cached-only) |
                # draining — plus the evidence behind the verdict.
                self._reply(200, {
                    **pserver.health_info(),
                    "n_variants": pserver.engine.n_variants,
                    "n_components": pserver.engine.n_components,
                    "max_batch": pserver.max_batch,
                })
                return
            if self.path == "/readyz":
                info = pserver.ready_info()
                self._reply(200 if info["ready"] else 503, info)
                return
            if self.path == "/stats":
                self._reply(200, pserver.stats_payload())
                return
            if self.path == "/metrics":
                live_view.reply_metrics(self)
                return
            if self.path == "/debug/telemetry":
                live_view.reply_debug_telemetry(self)
                return
            if self.path == "/debug/requests":
                _reply_debug_requests(self)
                return
            self._reply(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802 (stdlib API)
            if self.path != "/project":
                self._reply(404, {"error": f"unknown path {self.path!r}"})
                return
            try:
                genotypes, deadline_s, _req = _parse_project_body(self)
            except (ValueError, KeyError, TypeError, OverflowError) as e:
                self._reply(400, {"error": f"bad request body: {e}"})
                return
            tid = _request_trace_id(self)
            sampled = telemetry.should_sample(tid)
            t0 = time.perf_counter()
            code, payload = 200, None
            try:
                with telemetry.trace_scope(trace_id=tid):
                    coords = pserver.project(genotypes,
                                             deadline_s=deadline_s)
            except ServerOverloaded as e:
                code, payload = 429, {"error": str(e)}
            except DeadlineExceeded as e:
                code, payload = 504, {"error": str(e)}
            except ServerClosed as e:
                code, payload = 503, {"error": str(e)}
            except ValueError as e:
                code, payload = 400, {"error": str(e)}
            except Exception as e:  # answered, never a dropped socket
                code, payload = 500, {"error": repr(e)}
            else:
                payload = {"coords": coords.tolist()}
            total = time.perf_counter() - t0
            phases = {"total": total}
            if sampled:
                telemetry.count("trace.sampled")
                telemetry.span_at("trace.request", t0, total,
                                  trace_id=tid, route="", cls="",
                                  status=code)
                telemetry.record_request_exemplar(
                    tid, total, phases, route="", cls="", status=code)
            self._reply(code, payload, headers={
                "X-Trace-Id": tid,
                "Server-Timing": _server_timing(phases),
            })

    return Handler


def _make_fleet_handler(fleet):
    """The fleet front (serve --fleet): same endpoints as the
    single-model handler plus route addressing — ``POST /project``
    takes ``route`` (and optional ``priority``) in the body, or the
    route rides the path as ``POST /project/<route>``; ``POST
    /neighbors`` (or ``/neighbors/<route>``, body ``k`` optional,
    default 10) answers exact query-vs-panel top-k on routes declaring
    the manifest ``topk`` capability; ``GET /routes`` lists the
    registry with per-route stats; ``GET /warm/<route>`` stages a
    route's panel now (the controller's placement push)."""
    from spark_examples_tpu.serve.pool import PanelUnavailable
    from spark_examples_tpu.serve.router import UnknownRoute

    class FleetHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Run-Id", telemetry.run_id())
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib API)
            if self.path == "/healthz":
                self._reply(200, fleet.health_info())
                return
            if self.path == "/readyz":
                info = fleet.ready_info()
                self._reply(200 if info["ready"] else 503, info)
                return
            if self.path.startswith("/warm/"):
                # The controller's placement push: stage this route's
                # panel now so /readyz flips ready before traffic.
                name = self.path[len("/warm/"):]
                try:
                    fleet.warm_route(name)
                except UnknownRoute as e:
                    self._reply(404, {"error": str(e)})
                except PanelUnavailable as e:
                    self._reply(503, {"error": str(e)})
                except Exception as e:
                    self._reply(500, {"error": repr(e)})
                else:
                    self._reply(200, {"warmed": name})
                return
            if self.path == "/stats":
                self._reply(200, fleet.stats_payload())
                return
            if self.path == "/routes":
                self._reply(200, fleet.stats_payload()["routes"])
                return
            if self.path == "/metrics":
                # Autoscale gauges recomputed at scrape time: the
                # per-route series an autoscaler reads must be current,
                # not last-batch-stale.
                fleet.publish_autoscale()
                live_view.reply_metrics(self)
                return
            if self.path == "/debug/telemetry":
                live_view.reply_debug_telemetry(self)
                return
            if self.path == "/debug/requests":
                _reply_debug_requests(self)
                return
            self._reply(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):  # noqa: N802 (stdlib API)
            # Two verbs, one envelope: /project answers coordinates,
            # /neighbors answers exact query-vs-panel top-k (routes
            # declaring the manifest "topk" capability). Both take the
            # route in the body or on the path.
            verb = None
            for v in ("project", "neighbors"):
                if self.path == f"/{v}" or self.path.startswith(f"/{v}/"):
                    verb = v
                    break
            if verb is None:
                self._reply(404, {"error": f"unknown path {self.path!r}"})
                return
            try:
                genotypes, deadline_s, req = _parse_project_body(self)
                route = (self.path[len(f"/{verb}/"):]
                         if self.path.startswith(f"/{verb}/")
                         else req.get("route"))
                if not route:
                    raise ValueError(
                        "fleet request names no route (body 'route' "
                        f"field or POST /{verb}/<route>)")
                kwargs = {}
                if req.get("priority") is not None:
                    kwargs["priority"] = str(req["priority"])
                k = 0
                if verb == "neighbors":
                    k = int(req.get("k", 10))
            except (ValueError, KeyError, TypeError, OverflowError) as e:
                self._reply(400, {"error": f"bad request body: {e}"})
                return
            tid = _request_trace_id(self)
            sampled = telemetry.should_sample(tid)
            # The router writes the per-phase breakdown (queue wait,
            # cold-start stage share, compute share, cache hits) back
            # into this dict before resolving the request's future —
            # the Server-Timing header and the exemplar ring read it.
            trace = {"trace_id": tid, "span_id": telemetry.new_span_id(),
                     "sampled": sampled, "phases": {}}
            t0 = time.perf_counter()
            code = 200
            try:
                with telemetry.trace_scope(trace_id=tid,
                                           span_id=trace["span_id"]):
                    if verb == "neighbors":
                        ids, sims = fleet.topk(route, genotypes, k,
                                               deadline_s=deadline_s,
                                               trace=trace, **kwargs)
                        # Panel indices -> the model's sample ids: the
                        # client-facing identity, beside the raw
                        # indices for positional consumers.
                        panel_ids = fleet.routes[route].ctx.model \
                            .sample_ids
                        payload = {
                            "neighbor_ids": [
                                [panel_ids[j] for j in row]
                                for row in ids.tolist()
                            ],
                            "neighbor_indices": ids.tolist(),
                            "similarities": sims.tolist(),
                            "k": int(ids.shape[1]),
                        }
                    else:
                        coords = fleet.project(route, genotypes,
                                               deadline_s=deadline_s,
                                               trace=trace, **kwargs)
                        payload = {"coords": coords.tolist()}
            except UnknownRoute as e:
                code, payload = 404, {"error": str(e)}
            except ServerOverloaded as e:
                code, payload = 429, {"error": str(e)}
            except DeadlineExceeded as e:
                code, payload = 504, {"error": str(e)}
            except ServerClosed as e:
                code, payload = 503, {"error": str(e)}
            except PanelUnavailable as e:
                # The route's panel cannot stage right now (breaker
                # open / store down) — unavailable, not a client error.
                code, payload = 503, {"error": str(e)}
            except ValueError as e:
                code, payload = 400, {"error": str(e)}
            except Exception as e:  # answered, never a dropped socket
                code, payload = 500, {"error": repr(e)}
            total = time.perf_counter() - t0
            phases = {**trace["phases"], "total": total}
            cls = kwargs.get("priority", "")
            if sampled:
                telemetry.count("trace.sampled")
                telemetry.span_at(
                    "trace.request", t0, total, trace_id=tid,
                    span_id=trace["span_id"], route=route, cls=cls,
                    status=code,
                    cache_hit=bool(trace.get("cache_hit")))
                telemetry.record_request_exemplar(
                    tid, total, phases, route=route, cls=cls,
                    status=code)
            self._reply(code, payload, headers={
                "X-Trace-Id": tid,
                "Server-Timing": _server_timing(phases),
            })

    return FleetHandler


class ProjectionHTTPServer:
    """Lifecycle wrapper: bind (port 0 = ephemeral), serve in a daemon
    thread or in the foreground, shut down idempotently. ``handler``
    overrides the single-model handler (the fleet front passes its
    own)."""

    def __init__(self, pserver: ProjectionServer | None,
                 host: str = "127.0.0.1", port: int = 0,
                 handler=None):
        self._httpd = ThreadingHTTPServer(
            (host, port), handler or _make_handler(pserver))
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def serve_in_thread(self) -> "ProjectionHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="projection-http",
            daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def start_http_server(pserver: ProjectionServer, host: str = "127.0.0.1",
                      port: int = 0) -> ProjectionHTTPServer:
    """Bind + serve in a background thread; returns the wrapper (read
    ``.port`` for the ephemeral bind)."""
    return ProjectionHTTPServer(pserver, host=host, port=port).serve_in_thread()


def fleet_http_server(fleet, host: str = "127.0.0.1",
                      port: int = 0) -> ProjectionHTTPServer:
    """The fleet front, not yet serving (call ``serve_forever`` or
    ``serve_in_thread``)."""
    return ProjectionHTTPServer(None, host=host, port=port,
                                handler=_make_fleet_handler(fleet))


def start_fleet_http_server(fleet, host: str = "127.0.0.1",
                            port: int = 0) -> ProjectionHTTPServer:
    """Bind the fleet front + serve in a background thread."""
    return fleet_http_server(fleet, host=host, port=port).serve_in_thread()

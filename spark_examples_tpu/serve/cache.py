"""LRU result cache for the projection server.

Keyed by a digest of the query genotype block (plus the model's content
fingerprint as a namespace, so a hot-reloaded model can never serve a
stale result). Values are the final (1, k) coordinate rows — tiny next
to the cross-statistics work a miss costs, so a few hundred entries are
effectively free and absorb the classic serving pattern of repeated
identical queries (retries, duplicate submissions, shared panels).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from spark_examples_tpu.core.hashing import array_digest


def genotype_digest(genotypes: np.ndarray, namespace: str = "") -> str:
    """Content digest of one query's genotype block.

    Shape and dtype are folded in so a (V,) int8 query and some other
    buffer with the same bytes cannot collide; ``namespace`` carries the
    model fingerprint (ProjectionModel.digest()). Delegates to the
    shared encoding in core/hashing.py (the store and checkpoint layers
    hash with the same vocabulary)."""
    return array_digest(genotypes, namespace=namespace)


class ResultCache:
    """Thread-safe bounded LRU: get/put under one lock.

    Stored arrays are marked read-only and returned as-is (the server
    copies on the way out only if a caller asks to mutate); capacity 0
    disables storage entirely (every get misses)."""

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._data: OrderedDict[str, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get(self, key: str) -> np.ndarray | None:
        with self._lock:
            value = self._data.get(key)
            if value is not None:
                self._data.move_to_end(key)
            return value

    def put(self, key: str, value: np.ndarray) -> None:
        if self.capacity == 0:
            return
        # A genuine copy, not ascontiguousarray: freezing an alias of
        # the caller's array would make the Future result handed to the
        # client read-only whenever caching happens to be on.
        frozen = np.array(value)
        frozen.setflags(write=False)
        with self._lock:
            self._data[key] = frozen
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

"""LRU result cache for the projection servers (single-model + fleet).

Keyed by a digest of the query genotype block plus the serving model's
content fingerprint as an explicit **namespace** — a hot-reloaded model
(or a different fleet route) can never serve a stale result. Values are
the final (1, k) coordinate rows — tiny next to the cross-statistics
work a miss costs, so a few hundred entries are effectively free and
absorb the classic serving pattern of repeated identical queries
(retries, duplicate submissions, shared panels).

The namespace is a first-class index, not a hash ingredient: a
multi-model fleet unloads routes at runtime, and entries namespaced by
a gone model's fingerprint would otherwise sit in the LRU until
coincidental pressure evicted them — never matched, never reclaimed.
:meth:`ResultCache.evict_namespace` reclaims a route's entries whole on
unload (counted in ``fleet.cache_namespace_evictions``), and
:meth:`ResultCache.stats` exposes the entry/byte accounting the
lifecycle test pins flat across a load/unload loop.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from spark_examples_tpu.core.hashing import array_digest


def _nbytes(value) -> int:
    if isinstance(value, tuple):
        return sum(v.nbytes for v in value)
    return value.nbytes


def genotype_digest(genotypes: np.ndarray, namespace: str = "") -> str:
    """Content digest of one query's genotype block.

    Shape and dtype are folded in so a (V,) int8 query and some other
    buffer with the same bytes cannot collide; ``namespace`` optionally
    folds a model fingerprint into the digest itself (the pre-fleet
    spelling — the servers now pass the namespace to the cache
    explicitly so it stays evictable by route). Delegates to the shared
    encoding in core/hashing.py (the store and checkpoint layers hash
    with the same vocabulary)."""
    return array_digest(genotypes, namespace=namespace)


class ResultCache:
    """Thread-safe bounded LRU with namespace-indexed entries.

    Keys are ``(namespace, digest)`` pairs: the namespace carries the
    serving model's fingerprint, so equal queries against different
    models (fleet routes, pre/post hot-reload) can never collide, and a
    whole namespace is evictable in one call when its route unloads.
    Stored arrays are copied in and marked read-only; capacity 0
    disables storage entirely (every get misses)."""

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._data: OrderedDict[tuple[str, str], np.ndarray] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get(self, key: str, namespace: str = "") -> np.ndarray | None:
        with self._lock:
            value = self._data.get((namespace, key))
            if value is not None:
                self._data.move_to_end((namespace, key))
            return value

    def put(self, key: str, value,
            namespace: str = "") -> None:
        if self.capacity == 0:
            return
        # A genuine copy, not ascontiguousarray: freezing an alias of
        # the caller's array would make the Future result handed to the
        # client read-only whenever caching happens to be on. Values
        # are one array (projection rows) or a tuple of arrays (topk's
        # (ids, sims) — np.array over the tuple would STACK it into one
        # float64 block and silently destroy the ids' dtype).
        if isinstance(value, tuple):
            frozen = tuple(np.array(v) for v in value)
            for v in frozen:
                v.setflags(write=False)
        else:
            frozen = np.array(value)
            frozen.setflags(write=False)
        with self._lock:
            old = self._data.get((namespace, key))
            if old is not None:
                self._bytes -= _nbytes(old)
            self._data[(namespace, key)] = frozen
            self._bytes += _nbytes(frozen)
            self._data.move_to_end((namespace, key))
            while len(self._data) > self.capacity:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= _nbytes(evicted)

    def evict_namespace(self, namespace: str) -> int:
        """Drop every entry of ``namespace`` (a route's whole cache
        footprint on unload); returns the count evicted."""
        with self._lock:
            doomed = [k for k in self._data if k[0] == namespace]
            for k in doomed:
                self._bytes -= _nbytes(self._data.pop(k))
            return len(doomed)

    def stats(self) -> dict:
        """Entry/byte accounting (the lifecycle contract: bytes return
        to baseline after every namespace eviction)."""
        with self._lock:
            return {
                "entries": len(self._data),
                "bytes": int(self._bytes),
                "namespaces": len({k[0] for k in self._data}),
            }

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0

"""Serving health: the state machine and the store-read circuit breaker.

Availability hardening treats the server's condition as an explicit
three-state machine rather than a boolean:

- ``healthy`` — serving, all subsystems nominal.
- ``degraded`` — still serving, but something recovered or is being
  routed around: the batching worker was restarted within the cooloff
  window, or the store-read circuit breaker is open and the panel runs
  in cached-only mode. Load balancers should prefer other replicas;
  operators should look.
- ``draining`` — admission closed (SIGTERM / drain()); in-flight
  requests are being answered, new ones get 503.

The state is surfaced as a string in ``/healthz``, mirrored into the
``serve.health`` gauge (0/1/2) on every transition so the exported
timeline shows when and for how long the server was degraded.

:class:`CircuitBreaker` is the classic three-state breaker guarding the
panel's store read path: ``trip_after`` consecutive failures open it
(every re-stage attempt then short-circuits without touching the store
— cached-panel-only mode), and after ``reset_s`` one half-open probe is
let through; success closes it, failure re-opens the clock.
"""

from __future__ import annotations

import threading
import time

from spark_examples_tpu.core import telemetry

HEALTHY = "healthy"
DEGRADED = "degraded"
DRAINING = "draining"

HEALTH_CODE = {HEALTHY: 0, DEGRADED: 1, DRAINING: 2}

# How long a worker recovery keeps the server reporting degraded: long
# enough for a poller to observe it, short enough that one absorbed
# hiccup doesn't shadow a replica for minutes.
DEGRADED_COOLOFF_S = 30.0


def publish(state: str) -> None:
    """Mirror a state transition into the ``serve.health`` gauge."""
    telemetry.gauge_set("serve.health", float(HEALTH_CODE[state]))


def readiness(worker_alive: bool, draining: bool,
              unstaged=()) -> bool:
    """THE readiness rule, shared by both serve fronts (``/readyz``):
    ready iff the batching worker is alive, admission is open, and
    every explicitly warmed route is staged (``unstaged`` empty — the
    single-model server passes none; its panel staged before
    construction). Readiness is deliberately narrower than liveness:
    a degraded replica is still ready (it serves), a warming or
    draining one is not (the controller must not route hedges at
    it)."""
    return bool(worker_alive and not draining and not list(unstaged))


def worst(states) -> str:
    """The most severe of several member states — the fleet's health
    fold: one route serving cached-only (breaker open) degrades the
    whole process's /healthz, because a load balancer can only see the
    process. Empty input is healthy."""
    states = list(states)
    if not states:
        return HEALTHY
    return max(states, key=HEALTH_CODE.__getitem__)


class CircuitBreaker:
    """Three-state breaker: closed -> (trip_after consecutive
    failures) -> open -> (reset_s elapsed) -> half-open probe ->
    closed on success / open on failure. Thread-safe; time injectable
    for tests."""

    def __init__(self, trip_after: int = 3, reset_s: float = 30.0,
                 clock=time.monotonic):
        self.trip_after = max(1, int(trip_after))
        self.reset_s = float(reset_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: float | None = None
        self._probing = False

    def _state_locked(self) -> str:
        """THE transition rule — callers hold the lock. One copy, so
        /healthz's snapshot and the server's health logic can never
        disagree about what state the breaker is in."""
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.reset_s:
            return "half-open"
        return "open"

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def allow(self) -> bool:
        """May the protected operation run now? Open = no; half-open =
        one probe at a time (a second caller during a live probe is
        refused, so a slow probe can't stampede the failing store)."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._clock() - self._opened_at < self.reset_s:
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def release_probe(self) -> None:
        """Give back a half-open probe slot WITHOUT recording an
        outcome — for a probe aborted by something that says nothing
        about the store (SIGINT, SystemExit). Without this, an aborted
        probe would wedge the breaker: ``allow()`` refuses while a
        probe is live, and nothing else clears the flag."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            self._probing = False
            if self._opened_at is not None:
                # A failed half-open probe re-opens the clock.
                self._opened_at = self._clock()
                return
            self._failures += 1
            if self._failures >= self.trip_after:
                self._opened_at = self._clock()
                tripped = True
        if tripped:
            telemetry.count("serve.breaker_open")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state_locked(),
                "consecutive_failures": self._failures,
                "trip_after": self.trip_after,
                "reset_s": self.reset_s,
            }

// Native host-side data-loader kernels (C ABI, loaded via ctypes).
//
// The reference ran its ingest inner loops on the JVM (Genomics API JSON
// paging + case-class conversion, SURVEY.md §3.5); this framework's
// equivalent hot loops are host-side and feed the TPU's prefetch queue:
//
//   * 2-bit dosage packing   (ingest/bitpack.py pack_dosages)
//   * 2-bit unpack, host side (CPU oracle / cpu-reference backend)
//   * VCF GT-column parsing  (ingest/vcf.py _dosage / _records)
//
// They run in the producer thread, so every cycle spent here is a cycle
// the queue is not being filled. The NumPy implementations allocate
// several full-size temporaries per block (where/astype/concat plus a
// shift-or tree); these single-pass loops exist to keep the producer
// ahead of the chip. Python keeps byte-identical fallbacks — the
// library is an accelerator, never a semantic fork (tests pin native ==
// NumPy on the same inputs).
//
// Build: g++ -O3 -march=native -shared -fPIC codec.cpp -o libsparktpu.so
// (spark_examples_tpu/native/__init__.py builds lazily and caches).

#include <cstdint>
#include <cstring>

extern "C" {

// (n, v) int8 dosages {-1,0,1,2} -> (n, ceil(v/4)) uint8, 4 codes/byte.
// code 3 = missing; pad columns (v % 4) are filled with code 3, which
// downstream accumulation treats as absent. Returns 0, or 1 if any
// value falls outside [-1, 2] (caller raises — silent truncation would
// corrupt counts).
int pack_dosages_i8(const int8_t* g, int64_t n, int64_t v, uint8_t* out) {
    const int64_t w = (v + 3) / 4;           // packed bytes per row
    const int64_t v4 = v / 4 * 4;            // full-byte prefix
    int bad = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int8_t* row = g + i * v;
        uint8_t* orow = out + i * w;
        int64_t j = 0;
        for (; j < v4; j += 4) {
            uint8_t b = 0;
            for (int k = 0; k < 4; ++k) {
                int8_t x = row[j + k];
                bad |= (x < -1) | (x > 2);
                uint8_t code = (x < 0) ? 3u : (uint8_t)x;
                b |= code << (2 * k);
            }
            orow[j >> 2] = b;
        }
        if (j < v) {                          // ragged tail byte
            uint8_t b = 0;
            for (int k = 0; k < 4; ++k) {
                uint8_t code = 3u;            // pad = missing
                if (j + k < v) {
                    int8_t x = row[j + k];
                    bad |= (x < -1) | (x > 2);
                    code = (x < 0) ? 3u : (uint8_t)x;
                }
                b |= code << (2 * k);
            }
            orow[j >> 2] = b;
        }
    }
    return bad;
}

// (n, w) packed uint8 -> (n, 4*w) int8 dosages; code 3 -> -1.
void unpack_dosages_u8(const uint8_t* packed, int64_t n, int64_t w,
                       int8_t* out) {
    static const int8_t lut[4] = {0, 1, 2, -1};
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* row = packed + i * w;
        int8_t* orow = out + i * 4 * w;
        for (int64_t j = 0; j < w; ++j) {
            uint8_t b = row[j];
            orow[4 * j + 0] = lut[b & 3];
            orow[4 * j + 1] = lut[(b >> 2) & 3];
            orow[4 * j + 2] = lut[(b >> 4) & 3];
            orow[4 * j + 3] = lut[(b >> 6) & 3];
        }
    }
}

// Shared sample-column scan of one record: parse `n_samples` GT
// subfields starting at `p` (the first sample column). Returns samples
// parsed; < n_samples means a short record.
static int64_t parse_samples(const char* p, const char* end,
                             int64_t gt_index, int8_t* out,
                             int64_t n_samples) {
    int64_t s = 0;
    while (s < n_samples) {
        if (p > end) return s;
        const char* fend = p;
        while (fend < end && *fend != '\t') ++fend;
        // Select colon-subfield gt_index within [p, fend).
        const char* g = p;
        for (int64_t c = 0; c < gt_index; ++c) {
            while (g < fend && *g != ':') ++g;
            if (g >= fend) break;             // missing subfield -> empty GT
            ++g;
        }
        const char* gend = g;
        while (gend < fend && *gend != ':') ++gend;
        // Parse alleles.
        int dose = 0, seen = 0;
        const char* a = g;
        while (a <= gend) {
            const char* aend = a;
            while (aend < gend && *aend != '/' && *aend != '|') ++aend;
            int64_t alen = aend - a;
            if (alen > 0 && !(alen == 1 && a[0] == '.')) {
                seen = 1;
                if (!(alen == 1 && a[0] == '0')) ++dose;
            }
            if (aend >= gend) break;
            a = aend + 1;
        }
        out[s++] = seen ? (int8_t)(dose > 2 ? 2 : dose) : (int8_t)-1;
        if (fend >= end) break;
        p = fend + 1;
    }
    return s;
}

// One VCF record's sample columns -> int8 dosages.
//
// `line` spans the whole tab-separated record (no trailing newline
// required); parsing starts after `skip_fields` tabs (9 = the fixed VCF
// columns). Each sample field is split on ':', subfield `gt_index` is
// the GT string; alleles split on '/' or '|'. Semantics identical to
// ingest/vcf.py _dosage: any non-"0" called allele adds 1 (capped at
// 2), "." alleles are skipped, no called allele -> -1 (missing).
// Returns the number of samples parsed (== n_samples on success), or -1
// if the record has fewer sample columns than n_samples.
int64_t vcf_parse_gt(const char* line, int64_t len, int64_t skip_fields,
                     int64_t gt_index, int8_t* out, int64_t n_samples) {
    const char* p = line;
    const char* end = line + len;
    for (int64_t f = 0; f < skip_fields; ++f) {
        while (p < end && *p != '\t') ++p;
        if (p >= end) return -1;
        ++p;                                  // past the tab
    }
    return parse_samples(p, end, gt_index, out, n_samples);
}

// Batch parse: every VCF data line in buf[0, len) in ONE call — the
// whole-shard inner loop of the parallel ingest engine. A single-line
// call pays ctypes marshaling + Python line handling per RECORD (which
// also holds the GIL, so shard worker threads cannot scale); this
// parses a shard's worth per call with the GIL released throughout.
//
// Per accepted record r: out[r, :] = dosages, out_pos[r] = POS, and
// out_coff/out_clen[r] = the contig's byte span inside buf (the caller
// slices the strings; C never allocates). Skip semantics mirror
// ingest/vcf.py parse_record_lines exactly: '#' lines and lines with
// fewer than 10 tab-separated fields are skipped silently, records
// whose FORMAT lacks a GT token are skipped silently, and records with
// fewer than n_samples sample columns are skipped and counted into
// *n_short (the caller warns once). A POS field that is not a plain
// (optionally signed) integer sets *n_reject and aborts the batch —
// the caller falls back to the Python parser so malformed input raises
// exactly the error the serial path raises.
// Returns the number of accepted records (rows of `out` filled).
int64_t vcf_parse_block(const char* buf, int64_t len, int64_t n_samples,
                        int64_t max_records, int8_t* out, int64_t* out_pos,
                        int64_t* out_coff, int64_t* out_clen,
                        int64_t* n_short, int64_t* n_reject) {
    int64_t r = 0;
    const char* p = buf;
    const char* bend = buf + len;
    *n_short = 0;
    *n_reject = 0;
    while (p < bend && r < max_records) {
        const char* line = p;
        const char* nl = (const char*)memchr(p, '\n', bend - p);
        const char* lend = nl ? nl : bend;
        p = nl ? nl + 1 : bend;
        if (lend > line && lend[-1] == '\r') --lend;  // CRLF files raw
        if (lend == line) continue;                   // empty line
        if (line[0] == '#') continue;                 // header
        // Starts of the first 10 tab-separated fields.
        const char* f[10];
        f[0] = line;
        int nf = 1;
        for (const char* q = line; q < lend && nf < 10; ++q) {
            if (*q == '\t') f[nf++] = q + 1;
        }
        if (nf < 10) continue;                        // < 10 fields
        // POS (field 1) — a plain integer, or punt the whole batch.
        const char* d = f[1];
        const char* posend = f[2] - 1;                // the tab after it
        int64_t pos = 0;
        int neg = 0, any = 0;
        if (d < posend && (*d == '-' || *d == '+')) {
            neg = (*d == '-');
            ++d;
        }
        for (; d < posend && *d >= '0' && *d <= '9'; ++d) {
            pos = pos * 10 + (*d - '0');
            any = 1;
        }
        if (!any || d != posend) {
            *n_reject = 1;
            return r;
        }
        if (neg) pos = -pos;
        // FORMAT (field 8): locate the GT token among ':'-separated.
        const char* fm = f[8];
        const char* fmend = f[9] - 1;
        int64_t gt_index = -1, tok = 0;
        for (const char* t = fm; t <= fmend; ++tok) {
            const char* te = t;
            while (te < fmend && *te != ':') ++te;
            if (te - t == 2 && t[0] == 'G' && t[1] == 'T') {
                gt_index = tok;
                break;
            }
            if (te >= fmend) break;
            t = te + 1;
        }
        if (gt_index < 0) continue;                   // no genotypes
        int64_t got = parse_samples(f[9], lend, gt_index,
                                    out + r * n_samples, n_samples);
        if (got < n_samples) {
            ++*n_short;
            continue;
        }
        out_pos[r] = pos;
        out_coff[r] = f[0] - buf;
        out_clen[r] = (f[1] - 1) - f[0];
        ++r;
    }
    if (p < bend && r >= max_records) {
        // Caller under-sized the output (its bound assumes an accepted
        // record spans at least n_samples+9 bytes of tabs) — punt the
        // batch rather than silently dropping the tail records.
        *n_reject = 1;
    }
    return r;
}

}  // extern "C"

// Native host-side data-loader kernels (C ABI, loaded via ctypes).
//
// The reference ran its ingest inner loops on the JVM (Genomics API JSON
// paging + case-class conversion, SURVEY.md §3.5); this framework's
// equivalent hot loops are host-side and feed the TPU's prefetch queue:
//
//   * 2-bit dosage packing   (ingest/bitpack.py pack_dosages)
//   * 2-bit unpack, host side (CPU oracle / cpu-reference backend)
//   * VCF GT-column parsing  (ingest/vcf.py _dosage / _records)
//
// They run in the producer thread, so every cycle spent here is a cycle
// the queue is not being filled. The NumPy implementations allocate
// several full-size temporaries per block (where/astype/concat plus a
// shift-or tree); these single-pass loops exist to keep the producer
// ahead of the chip. Python keeps byte-identical fallbacks — the
// library is an accelerator, never a semantic fork (tests pin native ==
// NumPy on the same inputs).
//
// Build: g++ -O3 -march=native -shared -fPIC codec.cpp -lz -o
// libsparktpu.so (spark_examples_tpu/native/__init__.py builds lazily
// and caches; -lz serves the store's compressed-chunk decode).

#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <zlib.h>

extern "C" {

// (n, v) int8 dosages {-1,0,1,2} -> (n, ceil(v/4)) uint8, 4 codes/byte.
// code 3 = missing; pad columns (v % 4) are filled with code 3, which
// downstream accumulation treats as absent. Returns 0, or 1 if any
// value falls outside [-1, 2] (caller raises — silent truncation would
// corrupt counts).
int pack_dosages_i8(const int8_t* g, int64_t n, int64_t v, uint8_t* out) {
    const int64_t w = (v + 3) / 4;           // packed bytes per row
    const int64_t v4 = v / 4 * 4;            // full-byte prefix
    int bad = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int8_t* row = g + i * v;
        uint8_t* orow = out + i * w;
        int64_t j = 0;
        for (; j < v4; j += 4) {
            uint8_t b = 0;
            for (int k = 0; k < 4; ++k) {
                int8_t x = row[j + k];
                bad |= (x < -1) | (x > 2);
                uint8_t code = (x < 0) ? 3u : (uint8_t)x;
                b |= code << (2 * k);
            }
            orow[j >> 2] = b;
        }
        if (j < v) {                          // ragged tail byte
            uint8_t b = 0;
            for (int k = 0; k < 4; ++k) {
                uint8_t code = 3u;            // pad = missing
                if (j + k < v) {
                    int8_t x = row[j + k];
                    bad |= (x < -1) | (x > 2);
                    code = (x < 0) ? 3u : (uint8_t)x;
                }
                b |= code << (2 * k);
            }
            orow[j >> 2] = b;
        }
    }
    return bad;
}

// (n, w) packed uint8 -> (n, 4*w) int8 dosages; code 3 -> -1.
void unpack_dosages_u8(const uint8_t* packed, int64_t n, int64_t w,
                       int8_t* out);  // defined after unpack_clip below

// ---------------------------------------------------------------------------
// Store chunk decode-to-slab (spark_examples_tpu/store).
//
// One GIL-released call from a chunk file's STORED bytes to dense int8
// dosages written straight into a caller-provided buffer (a decode-
// cache entry, a read_range destination, or a prefetch staging-ring
// slab): inflate (when the chunk is compressed) + 2-bit unpack with
// variant clipping, at an arbitrary row stride and column offset —
// the zero-intermediate replacement for the Python hop chain
// (decompress -> bytes object -> full-width unpack -> slice -> copy).

// Unpack variants [v0, v1) of an (n, w)-byte packed payload into
// out[i * stride + (v - v0)]; code 3 -> -1. The aligned body expands a
// whole packed byte through a 256-entry -> 4-code table with one load
// and one 4-byte store (vs four shift+mask+LUT round trips), which is
// what keeps the decode memory-bound instead of ALU-bound.
static const int8_t lut4[4] = {0, 1, 2, -1};

static const uint32_t* byte_table() {
    // C++11 magic static: the guard synchronizes the first concurrent
    // GIL-released callers (the readahead pool's initial decodes race
    // here) — a plain `static bool ready` flag would let one thread
    // observe ready==true before another thread's table stores are
    // visible and expand bytes through a half-built table.
    struct Table {
        uint32_t tbl[256];
        Table() {
            for (int b = 0; b < 256; ++b) {
                int8_t q[4] = {lut4[b & 3], lut4[(b >> 2) & 3],
                               lut4[(b >> 4) & 3], lut4[(b >> 6) & 3]};
                memcpy(&tbl[b], q, 4);
            }
        }
    };
    static const Table t;
    return t.tbl;
}

static void unpack_clip(const uint8_t* packed, int64_t n, int64_t w,
                        int64_t v0, int64_t v1, int8_t* out,
                        int64_t stride) {
    const uint32_t* tbl = byte_table();
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* row = packed + i * w;
        int8_t* orow = out + i * stride - v0;
        int64_t j = v0;
        for (; j < v1 && (j & 3); ++j)
            orow[j] = lut4[(row[j >> 2] >> (2 * (j & 3))) & 3];
        for (; j + 4 <= v1; j += 4) {               // byte-aligned body
            uint32_t q = tbl[row[j >> 2]];
            memcpy(orow + j, &q, 4);
        }
        for (; j < v1; ++j)
            orow[j] = lut4[(row[j >> 2] >> (2 * (j & 3))) & 3];
    }
}

void unpack_dosages_u8(const uint8_t* packed, int64_t n, int64_t w,
                       int8_t* out) {
    unpack_clip(packed, n, w, 0, 4 * w, out, 4 * w);
}

// Inflate `stored_len` bytes into exactly `raw_size` bytes of `raw`,
// with an optional preset dictionary. Feeds <1 GiB windows (the
// z_stream counters are 32-bit); once the real buffer fills, a spare
// sink distinguishes "trailer still pending" (no further output) from
// genuine overflow. Returns 0 ok, 2 stream error / truncation,
// 3 size mismatch. Accepts (like the Python decompressobj reference
// path) trailing bytes after the stream end — the sha256 verify owns
// exact-byte integrity.
static int inflate_all(const uint8_t* stored, int64_t stored_len,
                       const uint8_t* dict, int64_t dict_len,
                       uint8_t* raw, int64_t raw_size) {
    z_stream strm;
    memset(&strm, 0, sizeof(strm));
    if (inflateInit2(&strm, 15) != Z_OK) return 2;
    const int64_t kWin = 1LL << 30;
    int64_t in_off = 0, out_done = 0;
    uint8_t spare[64];
    int ret = Z_OK;
    for (;;) {
        if (strm.avail_in == 0 && in_off < stored_len) {
            int64_t take = stored_len - in_off;
            if (take > kWin) take = kWin;
            strm.next_in = const_cast<Bytef*>(stored + in_off);
            strm.avail_in = (uInt)take;
            in_off += take;
        }
        int using_spare = 0;
        if (strm.avail_out == 0) {
            if (out_done < raw_size) {
                int64_t give = raw_size - out_done;
                if (give > kWin) give = kWin;
                strm.next_out = raw + out_done;
                strm.avail_out = (uInt)give;
            } else {
                strm.next_out = spare;
                strm.avail_out = (uInt)sizeof(spare);
                using_spare = 1;
            }
        } else if (out_done >= raw_size) {
            using_spare = 1;  // a previously-handed spare window
        }
        uInt before = strm.avail_out;
        ret = inflate(&strm, Z_NO_FLUSH);
        if (ret == Z_NEED_DICT) {
            if (!dict || dict_len <= 0 ||
                inflateSetDictionary(&strm, dict, (uInt)dict_len) != Z_OK) {
                inflateEnd(&strm);
                return 2;
            }
            ret = inflate(&strm, Z_NO_FLUSH);
        }
        uInt produced = before - strm.avail_out;
        if (using_spare) {
            if (produced > 0) {  // more output than the catalog says
                inflateEnd(&strm);
                return 3;
            }
        } else {
            out_done += produced;
        }
        if (ret == Z_STREAM_END) break;
        if (ret == Z_BUF_ERROR && strm.avail_in == 0 &&
            in_off >= stored_len) {
            inflateEnd(&strm);   // truncated stream: no input, no end
            return 2;
        }
        if (ret != Z_OK && ret != Z_BUF_ERROR) {
            inflateEnd(&strm);
            return 2;
        }
    }
    inflateEnd(&strm);
    return out_done == raw_size ? 0 : 3;
}

// Decode variants [v0, v1) of one stored chunk into `out` (row stride
// `out_stride` int8 elements; the caller points `out` at its target
// column). codec: 0 = raw (stored bytes ARE the (n, w_bytes) payload),
// 1 = zlib. Returns 0 ok, 1 unknown codec, 2 inflate/stream error,
// 3 size mismatch, 4 allocation failure.
int store_decode_chunk(const uint8_t* stored, int64_t stored_len,
                       int32_t codec, const uint8_t* dict,
                       int64_t dict_len, int64_t n, int64_t w_bytes,
                       int64_t v0, int64_t v1, int8_t* out,
                       int64_t out_stride) {
    if (codec == 0) {
        if (stored_len != n * w_bytes) return 3;
        unpack_clip(stored, n, w_bytes, v0, v1, out, out_stride);
        return 0;
    }
    if (codec != 1) return 1;
    uint8_t* raw = (uint8_t*)malloc((size_t)(n * w_bytes));
    if (!raw) return 4;
    int rc = inflate_all(stored, stored_len, dict, dict_len, raw,
                         n * w_bytes);
    if (rc == 0) unpack_clip(raw, n, w_bytes, v0, v1, out, out_stride);
    free(raw);
    return rc;
}

// Shared sample-column scan of one record: parse `n_samples` GT
// subfields starting at `p` (the first sample column). Returns samples
// parsed; < n_samples means a short record.
static int64_t parse_samples(const char* p, const char* end,
                             int64_t gt_index, int8_t* out,
                             int64_t n_samples) {
    int64_t s = 0;
    while (s < n_samples) {
        if (p > end) return s;
        const char* fend = p;
        while (fend < end && *fend != '\t') ++fend;
        // Select colon-subfield gt_index within [p, fend).
        const char* g = p;
        for (int64_t c = 0; c < gt_index; ++c) {
            while (g < fend && *g != ':') ++g;
            if (g >= fend) break;             // missing subfield -> empty GT
            ++g;
        }
        const char* gend = g;
        while (gend < fend && *gend != ':') ++gend;
        // Parse alleles.
        int dose = 0, seen = 0;
        const char* a = g;
        while (a <= gend) {
            const char* aend = a;
            while (aend < gend && *aend != '/' && *aend != '|') ++aend;
            int64_t alen = aend - a;
            if (alen > 0 && !(alen == 1 && a[0] == '.')) {
                seen = 1;
                if (!(alen == 1 && a[0] == '0')) ++dose;
            }
            if (aend >= gend) break;
            a = aend + 1;
        }
        out[s++] = seen ? (int8_t)(dose > 2 ? 2 : dose) : (int8_t)-1;
        if (fend >= end) break;
        p = fend + 1;
    }
    return s;
}

// One VCF record's sample columns -> int8 dosages.
//
// `line` spans the whole tab-separated record (no trailing newline
// required); parsing starts after `skip_fields` tabs (9 = the fixed VCF
// columns). Each sample field is split on ':', subfield `gt_index` is
// the GT string; alleles split on '/' or '|'. Semantics identical to
// ingest/vcf.py _dosage: any non-"0" called allele adds 1 (capped at
// 2), "." alleles are skipped, no called allele -> -1 (missing).
// Returns the number of samples parsed (== n_samples on success), or -1
// if the record has fewer sample columns than n_samples.
int64_t vcf_parse_gt(const char* line, int64_t len, int64_t skip_fields,
                     int64_t gt_index, int8_t* out, int64_t n_samples) {
    const char* p = line;
    const char* end = line + len;
    for (int64_t f = 0; f < skip_fields; ++f) {
        while (p < end && *p != '\t') ++p;
        if (p >= end) return -1;
        ++p;                                  // past the tab
    }
    return parse_samples(p, end, gt_index, out, n_samples);
}

// Batch parse: every VCF data line in buf[0, len) in ONE call — the
// whole-shard inner loop of the parallel ingest engine. A single-line
// call pays ctypes marshaling + Python line handling per RECORD (which
// also holds the GIL, so shard worker threads cannot scale); this
// parses a shard's worth per call with the GIL released throughout.
//
// Per accepted record r: out[r, :] = dosages, out_pos[r] = POS, and
// out_coff/out_clen[r] = the contig's byte span inside buf (the caller
// slices the strings; C never allocates). Skip semantics mirror
// ingest/vcf.py parse_record_lines exactly: '#' lines and lines with
// fewer than 10 tab-separated fields are skipped silently, records
// whose FORMAT lacks a GT token are skipped silently, and records with
// fewer than n_samples sample columns are skipped and counted into
// *n_short (the caller warns once). A POS field that is not a plain
// (optionally signed) integer sets *n_reject and aborts the batch —
// the caller falls back to the Python parser so malformed input raises
// exactly the error the serial path raises.
// Returns the number of accepted records (rows of `out` filled).
int64_t vcf_parse_block(const char* buf, int64_t len, int64_t n_samples,
                        int64_t max_records, int8_t* out, int64_t* out_pos,
                        int64_t* out_coff, int64_t* out_clen,
                        int64_t* n_short, int64_t* n_reject) {
    int64_t r = 0;
    const char* p = buf;
    const char* bend = buf + len;
    *n_short = 0;
    *n_reject = 0;
    while (p < bend && r < max_records) {
        const char* line = p;
        const char* nl = (const char*)memchr(p, '\n', bend - p);
        const char* lend = nl ? nl : bend;
        p = nl ? nl + 1 : bend;
        if (lend > line && lend[-1] == '\r') --lend;  // CRLF files raw
        if (lend == line) continue;                   // empty line
        if (line[0] == '#') continue;                 // header
        // Starts of the first 10 tab-separated fields.
        const char* f[10];
        f[0] = line;
        int nf = 1;
        for (const char* q = line; q < lend && nf < 10; ++q) {
            if (*q == '\t') f[nf++] = q + 1;
        }
        if (nf < 10) continue;                        // < 10 fields
        // POS (field 1) — a plain integer, or punt the whole batch.
        const char* d = f[1];
        const char* posend = f[2] - 1;                // the tab after it
        int64_t pos = 0;
        int neg = 0, any = 0;
        if (d < posend && (*d == '-' || *d == '+')) {
            neg = (*d == '-');
            ++d;
        }
        for (; d < posend && *d >= '0' && *d <= '9'; ++d) {
            pos = pos * 10 + (*d - '0');
            any = 1;
        }
        if (!any || d != posend) {
            *n_reject = 1;
            return r;
        }
        if (neg) pos = -pos;
        // FORMAT (field 8): locate the GT token among ':'-separated.
        const char* fm = f[8];
        const char* fmend = f[9] - 1;
        int64_t gt_index = -1, tok = 0;
        for (const char* t = fm; t <= fmend; ++tok) {
            const char* te = t;
            while (te < fmend && *te != ':') ++te;
            if (te - t == 2 && t[0] == 'G' && t[1] == 'T') {
                gt_index = tok;
                break;
            }
            if (te >= fmend) break;
            t = te + 1;
        }
        if (gt_index < 0) continue;                   // no genotypes
        int64_t got = parse_samples(f[9], lend, gt_index,
                                    out + r * n_samples, n_samples);
        if (got < n_samples) {
            ++*n_short;
            continue;
        }
        out_pos[r] = pos;
        out_coff[r] = f[0] - buf;
        out_clen[r] = (f[1] - 1) - f[0];
        ++r;
    }
    if (p < bend && r >= max_records) {
        // Caller under-sized the output (its bound assumes an accepted
        // record spans at least n_samples+9 bytes of tabs) — punt the
        // batch rather than silently dropping the tail records.
        *n_reject = 1;
    }
    return r;
}

}  // extern "C"

// Native host-side data-loader kernels (C ABI, loaded via ctypes).
//
// The reference ran its ingest inner loops on the JVM (Genomics API JSON
// paging + case-class conversion, SURVEY.md §3.5); this framework's
// equivalent hot loops are host-side and feed the TPU's prefetch queue:
//
//   * 2-bit dosage packing   (ingest/bitpack.py pack_dosages)
//   * 2-bit unpack, host side (CPU oracle / cpu-reference backend)
//   * VCF GT-column parsing  (ingest/vcf.py _dosage / _records)
//
// They run in the producer thread, so every cycle spent here is a cycle
// the queue is not being filled. The NumPy implementations allocate
// several full-size temporaries per block (where/astype/concat plus a
// shift-or tree); these single-pass loops exist to keep the producer
// ahead of the chip. Python keeps byte-identical fallbacks — the
// library is an accelerator, never a semantic fork (tests pin native ==
// NumPy on the same inputs).
//
// Build: g++ -O3 -march=native -shared -fPIC codec.cpp -o libsparktpu.so
// (spark_examples_tpu/native/__init__.py builds lazily and caches).

#include <cstdint>
#include <cstring>

extern "C" {

// (n, v) int8 dosages {-1,0,1,2} -> (n, ceil(v/4)) uint8, 4 codes/byte.
// code 3 = missing; pad columns (v % 4) are filled with code 3, which
// downstream accumulation treats as absent. Returns 0, or 1 if any
// value falls outside [-1, 2] (caller raises — silent truncation would
// corrupt counts).
int pack_dosages_i8(const int8_t* g, int64_t n, int64_t v, uint8_t* out) {
    const int64_t w = (v + 3) / 4;           // packed bytes per row
    const int64_t v4 = v / 4 * 4;            // full-byte prefix
    int bad = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int8_t* row = g + i * v;
        uint8_t* orow = out + i * w;
        int64_t j = 0;
        for (; j < v4; j += 4) {
            uint8_t b = 0;
            for (int k = 0; k < 4; ++k) {
                int8_t x = row[j + k];
                bad |= (x < -1) | (x > 2);
                uint8_t code = (x < 0) ? 3u : (uint8_t)x;
                b |= code << (2 * k);
            }
            orow[j >> 2] = b;
        }
        if (j < v) {                          // ragged tail byte
            uint8_t b = 0;
            for (int k = 0; k < 4; ++k) {
                uint8_t code = 3u;            // pad = missing
                if (j + k < v) {
                    int8_t x = row[j + k];
                    bad |= (x < -1) | (x > 2);
                    code = (x < 0) ? 3u : (uint8_t)x;
                }
                b |= code << (2 * k);
            }
            orow[j >> 2] = b;
        }
    }
    return bad;
}

// (n, w) packed uint8 -> (n, 4*w) int8 dosages; code 3 -> -1.
void unpack_dosages_u8(const uint8_t* packed, int64_t n, int64_t w,
                       int8_t* out) {
    static const int8_t lut[4] = {0, 1, 2, -1};
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* row = packed + i * w;
        int8_t* orow = out + i * 4 * w;
        for (int64_t j = 0; j < w; ++j) {
            uint8_t b = row[j];
            orow[4 * j + 0] = lut[b & 3];
            orow[4 * j + 1] = lut[(b >> 2) & 3];
            orow[4 * j + 2] = lut[(b >> 4) & 3];
            orow[4 * j + 3] = lut[(b >> 6) & 3];
        }
    }
}

// One VCF record's sample columns -> int8 dosages.
//
// `line` spans the whole tab-separated record (no trailing newline
// required); parsing starts after `skip_fields` tabs (9 = the fixed VCF
// columns). Each sample field is split on ':', subfield `gt_index` is
// the GT string; alleles split on '/' or '|'. Semantics identical to
// ingest/vcf.py _dosage: any non-"0" called allele adds 1 (capped at
// 2), "." alleles are skipped, no called allele -> -1 (missing).
// Returns the number of samples parsed (== n_samples on success), or -1
// if the record has fewer sample columns than n_samples.
int64_t vcf_parse_gt(const char* line, int64_t len, int64_t skip_fields,
                     int64_t gt_index, int8_t* out, int64_t n_samples) {
    const char* p = line;
    const char* end = line + len;
    for (int64_t f = 0; f < skip_fields; ++f) {
        while (p < end && *p != '\t') ++p;
        if (p >= end) return -1;
        ++p;                                  // past the tab
    }
    int64_t s = 0;
    while (s < n_samples) {
        if (p > end) return -1;
        const char* fend = p;
        while (fend < end && *fend != '\t') ++fend;
        // Select colon-subfield gt_index within [p, fend).
        const char* g = p;
        for (int64_t c = 0; c < gt_index; ++c) {
            while (g < fend && *g != ':') ++g;
            if (g >= fend) break;             // missing subfield -> empty GT
            ++g;
        }
        const char* gend = g;
        while (gend < fend && *gend != ':') ++gend;
        // Parse alleles.
        int dose = 0, seen = 0;
        const char* a = g;
        while (a <= gend) {
            const char* aend = a;
            while (aend < gend && *aend != '/' && *aend != '|') ++aend;
            int64_t alen = aend - a;
            if (alen > 0 && !(alen == 1 && a[0] == '.')) {
                seen = 1;
                if (!(alen == 1 && a[0] == '0')) ++dose;
            }
            if (aend >= gend) break;
            a = aend + 1;
        }
        out[s++] = seen ? (int8_t)(dose > 2 ? 2 : dose) : (int8_t)-1;
        if (fend >= end) break;
        p = fend + 1;
    }
    return s;
}

}  // extern "C"

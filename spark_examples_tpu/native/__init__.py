"""Native host-side codec: lazy-built C++ shared library (ctypes).

The compute path is JAX/XLA/Pallas on the chip; the *runtime around it*
— here, the data-loader inner loops that feed the prefetch queue — is
native C++ (SURVEY.md §2 note: the reference's only "native" code lived
in external JVM deps; the rebuild's loader is its honest successor).

Loading policy: build ``libsparktpu.so`` from ``codec.cpp`` with g++ on
first use (cached beside the source, rebuilt when the source is newer),
and fall back to the pure-NumPy/Python implementations on any failure —
the library is an accelerator, never a semantic fork. Set
``SPARK_TPU_NO_NATIVE=1`` to force the fallback (tests use this to pin
native == Python byte-for-byte).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.cpp")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _host_tag() -> str:
    """Cache key: host-ISA fingerprint + source content hash.

    - ISA half: the resolved ``-march=native`` target flags, so a
      library built on a wider-ISA machine is never loaded on a narrower
      one (shared/NFS package dirs) — a foreign-ISA .so would pass CDLL
      and then SIGILL mid-call, which no Python-level fallback can
      catch. A host with a different CPU resolves a different tag and
      rebuilds its own copy.
    - Source half: a hash of codec.cpp itself, so a cached build from an
      older package version can never load against newer ctypes wrappers
      (mtime comparisons lie under pip/sdist timestamp normalization).
    """
    with open(_SRC, "rb") as f:
        src = hashlib.sha1(f.read()).hexdigest()[:8]
    try:
        out = subprocess.run(
            ["g++", "-march=native", "-Q", "--help=target"],
            capture_output=True, timeout=30,
        ).stdout
        isa = hashlib.sha1(out).hexdigest()[:12]
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return f"portable-{src}"
    return f"{isa}-{src}"


def _lib_path(tag: str) -> str:
    """Cache location for the built .so: beside the source when the
    package dir is writable (dev checkouts), else a per-user cache dir —
    a root-owned site-packages install must not doom every process to a
    failing compile attempt."""
    if os.access(_DIR, os.W_OK):
        return os.path.join(_DIR, f"libsparktpu-{tag}.so")
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    d = os.path.join(base, "spark-examples-tpu")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"libsparktpu-{tag}.so")


def _build(lib_path: str, march_native: bool) -> bool:
    # -lz: the store's compressed-chunk decode (store_decode_chunk)
    # inflates with the same libz the Python zlib module wraps, so the
    # two paths accept exactly the same streams.
    # -Wl,--no-undefined: -shared happily links with unresolved symbols
    # and defers the failure to dlopen time — which would publish a
    # cached library that can never load; fail the BUILD instead.
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-Wl,--no-undefined",
           _SRC, "-lz"]
    if march_native:
        cmd.insert(1, "-march=native")
    # Unique temp per process: concurrent builders (two-process
    # jax.distributed launches, pytest-xdist) must not scribble into a
    # path another process just os.replace()d live.
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    try:
        subprocess.run(cmd + ["-o", tmp], check=True,
                       capture_output=True, timeout=120)
        os.replace(tmp, lib_path)  # atomic publish
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return False


def load() -> ctypes.CDLL | None:
    """The shared library, building it if needed; None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SPARK_TPU_NO_NATIVE"):
            return None
        try:
            tag = _host_tag()
            path = _lib_path(tag)
            # The tag embeds a source-content hash, so existence IS
            # freshness — no mtime comparison (archive-normalized
            # timestamps make those lie).
            if not os.path.exists(path) and not _build(
                path, march_native=not tag.startswith("portable")
            ):
                return None
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                # A cached library that no longer loads (e.g. published
                # by an older builder without -Wl,--no-undefined against
                # a since-removed dependency): rebuild once in place
                # rather than dooming every future process to the
                # Python fallback.
                os.unlink(path)
                if not _build(path,
                              march_native=not tag.startswith("portable")):
                    return None
                lib = ctypes.CDLL(path)
        except OSError:
            return None
        i64, i8p, u8p, cp = (
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ctypes.c_char_p,
        )
        try:
            lib.pack_dosages_i8.argtypes = [i8p, i64, i64, u8p]
            lib.pack_dosages_i8.restype = ctypes.c_int
            lib.unpack_dosages_u8.argtypes = [u8p, i64, i64, i8p]
            lib.unpack_dosages_u8.restype = None
            lib.vcf_parse_gt.argtypes = [cp, i64, i64, i64, i8p, i64]
            lib.vcf_parse_gt.restype = i64
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.vcf_parse_block.argtypes = [
                cp, i64, i64, i64, i8p, i64p, i64p, i64p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.vcf_parse_block.restype = i64
        except AttributeError:
            # A library missing a MANDATORY export (a bad hand-built
            # binary dropped into the cache path): the Python fallback,
            # not an ImportError for every consumer.
            return None
        try:
            # Raw pointers (c_void_p) rather than ndpointers: the
            # caller hands an interior pointer (slab base + column
            # offset) and a row stride, which ndpointer cannot express.
            lib.store_decode_chunk.argtypes = [
                ctypes.c_void_p, i64, ctypes.c_int32, ctypes.c_char_p,
                i64, i64, i64, i64, i64, ctypes.c_void_p, i64,
            ]
            lib.store_decode_chunk.restype = ctypes.c_int
        except AttributeError:
            # A stale binary predating the decode-to-slab entry: the
            # store's codec layer detects this (has_store_decode) and
            # degrades LOUDLY to the Python path (store.codec.fallback).
            pass
        _lib = lib
        return _lib


def pack_dosages(g: np.ndarray) -> np.ndarray | None:
    """Native 2-bit pack; None when the library is unavailable (caller
    falls back to NumPy). Raises on out-of-domain values, matching the
    NumPy path's loud rejection."""
    lib = load()
    if lib is None or g.dtype != np.int8:
        return None  # other dtypes would wrap under the int8 view;
        # the NumPy fallback validates the wide domain itself
    g = np.ascontiguousarray(g)
    n, v = g.shape
    out = np.empty((n, -(-v // 4)), np.uint8)
    if lib.pack_dosages_i8(g, n, v, out):
        raise ValueError(
            "dosage values out of 2-bit range [-1, 2] "
            "(pack_dosages is for genotype dosages only)"
        )
    return out


def unpack_dosages(packed: np.ndarray) -> np.ndarray | None:
    """Native host-side 2-bit unpack; None when unavailable."""
    lib = load()
    if lib is None:
        return None
    packed = np.ascontiguousarray(packed, np.uint8)
    n, w = packed.shape
    out = np.empty((n, 4 * w), np.int8)
    lib.unpack_dosages_u8(packed, n, w, out)
    return out


def vcf_parse_gt(line: bytes, gt_index: int, n_samples: int,
                 out: np.ndarray) -> bool:
    """Parse one VCF record's sample GT columns into ``out`` (int8,
    n_samples). Returns False when the library is unavailable or the
    record is short (caller falls back to the Python parser)."""
    lib = load()
    if lib is None:
        return False
    got = lib.vcf_parse_gt(line, len(line), 9, gt_index, out, n_samples)
    return got == n_samples


def has_store_decode() -> bool:
    """Whether the loaded library exports the store's decode-to-slab
    entry (False also when the library itself is unavailable). A stale
    cached binary can lack it — the store layer then selects the
    Python fallback and counts ``store.codec.fallback``."""
    lib = load()
    return lib is not None and hasattr(lib, "store_decode_chunk")


def store_decode_chunk(stored: np.ndarray, codec_id: int,
                       zdict: bytes | None, n: int, w_bytes: int,
                       v0: int, v1: int, out: np.ndarray,
                       col_off: int = 0) -> int | None:
    """Decode variants [v0, v1) of one stored chunk into
    ``out[:, col_off : col_off + (v1 - v0)]`` in ONE GIL-released call
    (inflate when compressed + 2-bit unpack, no intermediate buffers).

    ``stored`` is any C-contiguous uint8 buffer of the chunk file's
    bytes (typically the verified mmap); ``out`` must be C-contiguous
    int8 with at least ``col_off + (v1 - v0)`` columns. Returns the C
    return code (0 = ok; nonzero = undecodable bytes, the caller's
    corruption path), or None when the library or the symbol is
    unavailable (caller falls back to the Python decode)."""
    lib = load()
    if lib is None or not hasattr(lib, "store_decode_chunk"):
        return None
    stored = np.ascontiguousarray(stored, np.uint8)
    if (out.dtype != np.int8 or out.ndim != 2
            or not out.flags["C_CONTIGUOUS"]
            or not out.flags["WRITEABLE"]
            or not 0 <= col_off <= out.shape[1] - (v1 - v0)
            or out.shape[0] < n):
        raise ValueError(
            "store_decode_chunk needs a writable C-contiguous int8 "
            f"(>= {n}, >= {col_off + (v1 - v0)}) output, got "
            f"{out.dtype} {out.shape} col_off={col_off}"
        )
    return lib.store_decode_chunk(
        ctypes.c_void_p(stored.ctypes.data), stored.size,
        int(codec_id), zdict or None, len(zdict) if zdict else 0,
        n, w_bytes, v0, v1,
        ctypes.c_void_p(out.ctypes.data + col_off), out.strides[0],
    )


def vcf_parse_block(buf: bytes, n_samples: int):
    """Parse every VCF data line in ``buf`` in one GIL-released call.

    Returns ``(dosages (r, n_samples) int8, positions (r,) int64,
    contigs list[str], n_short)`` for the ``r`` accepted records, in
    file order — skip semantics identical to the Python record parser
    (ingest/vcf.py parse_record_lines). Returns None when the library
    is unavailable OR the batch hit input the C parser punts on (a
    non-integer POS field): the caller must fall back to the Python
    parser, which raises the same error a serial parse would.
    """
    lib = load()
    if lib is None:
        return None
    # Output bound: an ACCEPTED record occupies at least n_samples + 9
    # bytes of buf (its tab separators alone), so sizing by newline
    # count alone is capped by that — a garbled shard of millions of
    # short junk lines must not translate into a multi-GB allocation
    # per worker (the C side punts the batch if the bound ever proves
    # too small, so the cap can never silently drop records).
    max_records = min(
        buf.count(b"\n") + 1,
        len(buf) // max(1, n_samples + 9) + 1,
    )
    out = np.empty((max_records, n_samples), np.int8)
    pos = np.empty(max_records, np.int64)
    coff = np.empty(max_records, np.int64)
    clen = np.empty(max_records, np.int64)
    n_short = ctypes.c_int64(0)
    n_reject = ctypes.c_int64(0)
    r = lib.vcf_parse_block(
        buf, len(buf), n_samples, max_records, out, pos, coff, clen,
        ctypes.byref(n_short), ctypes.byref(n_reject),
    )
    if n_reject.value:
        return None
    contigs = [
        buf[o:o + w].decode()
        for o, w in zip(coff[:r].tolist(), clen[:r].tolist())
    ]
    return out[:r], pos[:r], contigs, int(n_short.value)

"""Device-mesh bootstrap: the execution substrate of the framework.

The reference's execution substrate was Apache Spark (SURVEY.md §1 L1): a
driver JVM scheduling RDD partitions onto executors, with netty shuffle as
the communication backend. Here the substrate is a
:class:`jax.sharding.Mesh` over a TPU slice; communication is the XLA
collectives (``psum`` / ``all_gather`` / ``reduce_scatter`` /
``ppermute``) that ``jit``/``shard_map`` emit over ICI, with
``jax.distributed`` for multi-host (DCN) coordination (SURVEY.md §2.2
"Distributed communication backend").

Mesh axes
---------
``("i", "j")`` — a 2-D mesh over which the N x N similarity / Gram
accumulator is tiled (rows over ``i``, columns over ``j``). The 40M-long
*variant* axis — the reference's only parallel axis (RDD partitions by
genomic range) — is streamed in blocks and, in the variant-parallel mode,
sharded over the flattened ``(i, j)`` device list with a final ``psum``
(the TPU-native replacement of Spark's ``reduceByKey`` shuffle).
"""

from __future__ import annotations

import math
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_I = "i"  # sample-row axis of the N x N accumulator
AXIS_J = "j"  # sample-column axis of the N x N accumulator


def shard_map(body, *, mesh: Mesh, in_specs, out_specs,
              check_vma: bool = True):
    """``jax.shard_map`` across JAX versions: the public entry point
    (with its ``check_vma`` kwarg) when the installed JAX has one, else
    the 0.4.x ``jax.experimental.shard_map`` fallback, whose equivalent
    kwarg is the pre-rename ``check_rep``. Every shard_map in the
    package routes through here so a JAX upgrade/downgrade is a one-line
    compat problem, not a scattered one."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(body, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(body, mesh=mesh, in_specs=in_specs,
               out_specs=out_specs, check_rep=check_vma)


_distributed_initialized = False


def maybe_init_distributed() -> None:
    """Initialise ``jax.distributed`` when launched multi-host.

    Single-host runs (this environment) skip it; multi-host launchers set
    ``JAX_COORDINATOR_ADDRESS`` plus — outside of auto-detected cluster
    environments (Slurm/OMPI/GKE, which JAX sniffs itself) —
    ``JAX_NUM_PROCESSES`` and ``JAX_PROCESS_ID``, so a plain
    two-terminal/ssh launch works without a cluster manager (exercised by
    ``tests/test_distributed.py`` with two localhost processes over the
    DCN-analogue gRPC coordinator). Must run before any JAX backend is
    touched — so this deliberately avoids querying
    ``jax.process_count()``/``jax.devices()`` first. Mirrors the role of
    the reference's SparkContext connect (SURVEY.md §3.1) minus the
    driver/executor split.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # Multi-process on the host (CPU) platform needs a cross-process
        # collectives backend: without one, the first process-spanning
        # jit dies with "Multiprocess computations aren't implemented on
        # the CPU backend". Select gloo when this jaxlib carries the
        # knob (real TPU meshes ignore it — it only shapes CPU client
        # creation), and tolerate its absence: JAX versions that dropped
        # the option wire CPU collectives through the distributed
        # client on their own.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
        kw = {}
        if os.environ.get("JAX_NUM_PROCESSES"):
            kw["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        if os.environ.get("JAX_PROCESS_ID"):
            kw["process_id"] = int(os.environ["JAX_PROCESS_ID"])
        jax.distributed.initialize(**kw)
    _distributed_initialized = True


def _factor_2d(n: int) -> tuple[int, int]:
    """Near-square factorization of a device count into (i, j)."""
    best = (1, n)
    for i in range(1, int(math.isqrt(n)) + 1):
        if n % i == 0:
            best = (i, n // i)
    return best


def make_mesh(
    devices: Sequence[jax.Device] | None = None,
    shape: tuple[int, int] | None = None,
) -> Mesh:
    """Build the framework's 2-D ``(i, j)`` mesh.

    ``shape`` defaults to a near-square factorization of the device count,
    e.g. 8 devices -> (2, 4). A single device yields a (1, 1) mesh so all
    sharded code paths also run unmodified on one chip.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = _factor_2d(n)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, (AXIS_I, AXIS_J))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def tile2d(mesh: Mesh) -> NamedSharding:
    """Sharding for the N x N accumulator: rows over i, cols over j."""
    return NamedSharding(mesh, P(AXIS_I, AXIS_J))


def rows_i(mesh: Mesh) -> NamedSharding:
    """Sharding for an (N, V) genotype block: sample rows over i."""
    return NamedSharding(mesh, P(AXIS_I, None))


def rows_j(mesh: Mesh) -> NamedSharding:
    """Sharding for an (N, V) genotype block: sample rows over j."""
    return NamedSharding(mesh, P(AXIS_J, None))


def rows_flat(mesh: Mesh) -> NamedSharding:
    """Sharding for an (N, r) skinny solver block with the SAMPLE axis
    split over the whole mesh — the sketch solve's layout
    (solvers/solve.py): every r x r contraction is a local product plus
    one psum over the flattened (i, j) device list."""
    return NamedSharding(mesh, P((AXIS_I, AXIS_J), None))


def variants_flat(mesh: Mesh) -> NamedSharding:
    """Sharding for an (N, V) block with the variant axis split over the
    whole mesh — the data-parallel axis (reference: RDD partitions by
    genomic range, SURVEY.md §2.2)."""
    return NamedSharding(mesh, P(None, (AXIS_I, AXIS_J)))


def ring_perm(mesh: Mesh) -> tuple[tuple[int, int], ...]:
    """``ppermute`` source→destination pairs rotating one hop around the
    flattened ``(i, j)`` device ring: the shard on device ``s`` moves to
    device ``s - 1`` (mod D), so after ``D - 1`` hops every device has
    held every shard exactly once — the schedule of the tile2d ring
    transport (parallel/gram_sharded), where each hop rides ICI *behind*
    the current shard's tile contraction instead of serializing in front
    of it the way the bulk ``all_gather`` does."""
    n = mesh.devices.size
    return tuple((s, (s - 1) % n) for s in range(n))

"""Partial-Gram checkpoint / resume.

The reference had nothing here: a failed PCA job reran from scratch,
recovery being Spark lineage recompute (SURVEY.md §5 "Checkpoint /
resume", "Failure detection"). The TPU-native design does better because
the Gram accumulation is associative: persisting (accumulators, variant
cursor) every K blocks makes recovery "resume from the last checkpointed
partial sum", and the same mechanism powers the streaming/incremental
config (BASELINE.md config 5).

Format: a directory with one ``.npy`` per accumulator leaf plus a JSON
manifest (cursor, metric, block size, sample ids hash). Writes are
atomic (tmp dir + rename) so a crash mid-write never corrupts the latest
good checkpoint.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

import jax
import numpy as np


def _sample_hash(sample_ids: list[str]) -> str:
    h = hashlib.sha256("\n".join(sample_ids).encode()).hexdigest()
    return h[:16]


def save(
    path: str,
    acc: dict,
    next_variant: int,
    metric: str,
    block_variants: int,
    sample_ids: list[str],
    stream_stats: dict | None = None,
) -> None:
    """Atomically persist accumulators + resume cursor.

    ``stream_stats``: the runner's producer-side stream statistics
    (currently ``max_value``) — persisted so a resumed dot/euclidean
    job's int32-exactness guard still sees the largest value of the
    *whole* stream, not just the post-resume tail.
    """
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for k, v in acc.items():
        np.save(os.path.join(tmp, f"{k}.npy"), np.asarray(v))
    manifest = {
        "next_variant": int(next_variant),
        "metric": metric,
        "block_variants": int(block_variants),
        "sample_hash": _sample_hash(sample_ids),
        "n_samples": len(sample_ids),
        "leaves": sorted(acc.keys()),
        "stream_stats": dict(stream_stats or {}),
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    # Never a window with zero good checkpoints: move the old one aside,
    # land the new one, then delete the old. A crash mid-sequence leaves
    # either `path` or `path.old` intact (load() checks both).
    old = path + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.replace(path, old)
    os.replace(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def load(path: str, metric: str, sample_ids: list[str],
         block_variants: int | None = None):
    """Load (acc, next_variant, stream_stats) or None when absent.

    Incompatible checkpoints (different metric, cohort, or block grid)
    are rejected rather than silently mixed into the accumulation: a
    resume with a different ``block_variants`` would misalign the cursor
    against the block grid and double-count or skip variants.
    """
    manifest_path = os.path.join(path, "manifest.json")
    if not os.path.exists(manifest_path):
        # Crash window fallback: the previous good checkpoint was moved
        # aside but the new one never landed.
        old = path + ".old"
        if os.path.exists(os.path.join(old, "manifest.json")):
            path, manifest_path = old, os.path.join(old, "manifest.json")
        else:
            return None
    with open(manifest_path) as f:
        manifest = json.load(f)
    if block_variants is not None and manifest["block_variants"] != block_variants:
        raise ValueError(
            f"checkpoint at {path} was written with --block-variants "
            f"{manifest['block_variants']}, job wants {block_variants}; "
            "resume must keep the same block grid"
        )
    if manifest["metric"] != metric:
        raise ValueError(
            f"checkpoint at {path} is for metric {manifest['metric']!r}, "
            f"job wants {metric!r}"
        )
    if manifest["sample_hash"] != _sample_hash(sample_ids):
        raise ValueError(
            f"checkpoint at {path} was built for a different cohort "
            f"({manifest['n_samples']} samples)"
        )
    from spark_examples_tpu.ops import gram

    expected = sorted(
        ("zz", "nvar") if metric == "grm" else gram.PIECES_FOR_METRIC[metric]
    )
    if manifest["leaves"] != expected:
        raise ValueError(
            f"checkpoint at {path} holds accumulator leaves "
            f"{manifest['leaves']} but this version expects {expected} "
            f"for metric {metric!r} (stale accumulator schema — delete "
            "the checkpoint to restart)"
        )
    acc = {
        k: jax.device_put(np.load(os.path.join(path, f"{k}.npy")))
        for k in manifest["leaves"]
    }
    return acc, int(manifest["next_variant"]), manifest.get("stream_stats", {})

"""Partial-Gram checkpoint / resume — shard-aware.

The reference had nothing here: a failed PCA job reran from scratch,
recovery being Spark lineage recompute (SURVEY.md §5 "Checkpoint /
resume", "Failure detection"). The TPU-native design does better because
the Gram accumulation is associative: persisting (accumulators, variant
cursor) every K blocks makes recovery "resume from the last checkpointed
partial sum", and the same mechanism powers the streaming/incremental
config (BASELINE.md config 5).

Layout discipline matters at the tile2d regime (BASELINE.md config 4): a
76k^2 f32 leaf is ~23 GB, and the whole point of the tiling is that no
single host or device ever materializes it. So tiled leaves are saved
**one file per addressable tile** (``{leaf}.t{row0}_{col0}.npy``, the
filename keyed by the tile's global offsets) and restored through
``jax.make_array_from_callback`` under the plan's sharding — each device
reads back exactly its own tile, host peak stays O(tile), and in
multi-host runs each process touches only its own tiles. Replicated
leaves (variant mode, scalars) keep the simple one-``.npy``-per-leaf
format. A manifest records the tile grid; resuming under a different
mesh/mode is rejected rather than silently re-laid-out (re-tiling a
partial sum is possible in principle but never what an interrupted
production job wants to discover it did implicitly).

Writes are atomic (tmp dir + rename; multi-host writers barrier before
process 0 rotates the directory) so a crash mid-write never corrupts the
latest good checkpoint.

Integrity: the manifest records a sha256 per data file (each process
checksums its own tiles; process 0 merges per-process sidecars on the
shared FS), and ``load()`` verifies every file before placing a single
byte on a device. A truncated or bit-flipped file is a **checksum
error**, not garbage silently added into a 40M-variant accumulation —
and because rotation now RETAINS the previous checkpoint as ``.old``
(one generation of history, costing one extra checkpoint of disk), a
corrupt latest falls back to the previous good state instead of
restarting the job from zero — and the fallback is promoted back into
the latest slot on load (corrupt latest set aside as ``.corrupt``), so
the next rotation never destroys the only good generation. Only when
both generations fail verification does load raise
:class:`CheckpointCorruptError`.
"""

from __future__ import annotations

import json
import os
import shutil
import warnings

import jax
import numpy as np

from spark_examples_tpu.core import faults, telemetry

# Shared digest vocabulary (core/hashing.py) — the store's content
# addressing and this module's file integrity use the SAME encodings.
from spark_examples_tpu.core.hashing import (
    TeeHashWriter as _TeeHashWriter,
    sample_hash as _sample_hash,
    sha256_file as _sha256_file,
)


class CheckpointCorruptError(RuntimeError):
    """Every on-disk generation failed checksum verification. Raised
    (not silently ignored): restarting from zero discards work the
    operator may be able to recover; delete the checkpoint directory to
    restart deliberately."""


def _is_replicated(v) -> bool:
    """True when every addressable shard holds the full leaf value."""
    if not isinstance(v, jax.Array):
        return True
    shards = v.addressable_shards
    return all(s.data.shape == v.shape for s in shards)


def _tile_name(leaf: str, index) -> str:
    offs = [(sl.start or 0) if isinstance(sl, slice) else int(sl)
            for sl in index]
    return f"{leaf}.t" + "_".join(str(o) for o in offs) + ".npy"


def _vote_all_ok(local_ok: bool, make_peer_error) -> None:
    """THE abort protocol for every fallible cross-process step in this
    module: allgather per-process ok flags (the gather doubles as the
    synchronization point) and, when any process failed, raise
    ``make_peer_error(bad_indices)`` on the processes whose local step
    succeeded. Callers re-raise their own local exception afterwards.
    Raising BESIDE a collective instead of voting through it would park
    the surviving processes in it until the distributed timeout — the
    hang class this layer exists to eliminate. Single-host: no-op."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    oks = np.asarray(multihost_utils.process_allgather(
        np.int32(bool(local_ok))
    ))
    if not oks.all() and local_ok:
        raise make_peer_error([int(i) for i in np.flatnonzero(oks == 0)])


@telemetry.traced("checkpoint.save", cat="checkpoint")
def save(
    path: str,
    acc: dict,
    next_variant: int,
    metric: str,
    block_variants: int,
    sample_ids: list[str],
    stream_stats: dict | None = None,
    plan=None,
    extra: dict | None = None,
) -> None:
    """Atomically persist accumulators + resume cursor.

    Tiled leaves (tile2d plans) are written one file per addressable
    shard — no full N x N leaf ever materializes on the host (the
    VERDICT r3 weak-#1 defect). ``plan`` records the tile grid in the
    manifest; without it (legacy callers, host-built accumulators) every
    leaf is treated as replicated and saved whole.

    ``stream_stats``: the runner's producer-side stream statistics
    (currently ``max_value``) — persisted so a resumed dot/euclidean
    job's int32-exactness guard still sees the largest value of the
    *whole* stream, not just the post-resume tail.

    Multi-host: a SHARED filesystem is required — every process writes
    its own tiles into the shared directory, process 0 writes the
    manifest and performs the atomic rotation after a cross-process
    barrier (without a shared FS, non-primary tmp dirs would never be
    rotated and load() would find no manifest there). ``next_variant``
    is this process's LOCAL cursor into its own ingest partition,
    recorded per process.

    ``extra``: caller-defined JSON-serializable compatibility record
    (the sketch solver stores its rung/rank/seed/pass here); ``load``
    rejects a checkpoint whose extra does not equal the job's — resuming
    a sketch accumulation under a different probe seed or rank would
    silently mix two different random subspaces.
    """
    proc = jax.process_index() if jax.process_count() > 1 else 0
    is_primary = proc == 0
    tmp = path + ".tmp"
    mkdir_error: Exception | None = None
    if is_primary:
        try:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp, exist_ok=True)
        except OSError as e:
            mkdir_error = e
    _vote_all_ok(mkdir_error is None, lambda bad: RuntimeError(
        "checkpoint save: could not (re)create the tmp directory on "
        "the primary process — see its log"
    ))
    if mkdir_error is not None:
        raise mkdir_error

    # filename -> sha256 of THIS process's writes; checksummed before the
    # injection site fires so an injected truncation corrupts the file
    # relative to its recorded digest (exactly what a real torn write
    # looks like to load()).
    checksums: dict[str, str] = {}

    def _write(fname: str, host: np.ndarray) -> None:
        fpath = os.path.join(tmp, fname)
        with telemetry.span("checkpoint.write", cat="checkpoint",
                            file=fname):
            with open(fpath, "wb") as f:
                tee = _TeeHashWriter(f)
                np.save(tee, host)
        telemetry.count("checkpoint.bytes_written", float(host.nbytes))
        checksums[fname] = tee.sha256.hexdigest()
        faults.fire("checkpoint.tile_write", path=fpath)

    layout: dict[str, str] = {}
    write_error: Exception | None = None
    try:
        os.makedirs(tmp, exist_ok=True)  # idempotent on the shared FS
        for k, v in acc.items():
            if _is_replicated(v):
                layout[k] = "full"
                if is_primary:
                    if isinstance(v, jax.Array) and not v.is_fully_addressable:
                        host = np.asarray(v.addressable_data(0))
                    else:
                        host = np.asarray(v)
                    _write(f"{k}.npy", host)
            else:
                layout[k] = "tiles"
                for sh in v.addressable_shards:
                    _write(_tile_name(k, sh.index), np.asarray(sh.data))
        # Non-primary processes publish their tile checksums as sidecars
        # on the shared FS; process 0 merges them into the manifest
        # after the synchronization below (gathering variable-length
        # dicts through the control plane would be needless ceremony
        # when a shared FS is already required).
        if jax.process_count() > 1 and not is_primary:
            with open(os.path.join(tmp, f"checksums.{proc}.json"), "w") as f:
                json.dump(checksums, f)
    except Exception as e:
        write_error = e
    _vote_all_ok(write_error is None, lambda bad: RuntimeError(
        f"checkpoint save: tile/sidecar write failed on process(es) "
        f"{bad} (see their logs); the previous checkpoint generations "
        "are untouched"
    ))
    if write_error is not None:
        raise write_error

    # Per-process cursors: each process resumes its own partition.
    cursors = {str(proc): int(next_variant)}
    if jax.process_count() > 1:
        from spark_examples_tpu.parallel import multihost as mh

        gathered = mh.allgather(np.int64(next_variant))
        cursors = {str(i): int(c) for i, c in enumerate(gathered)}

    manifest = {
        "next_variant": cursors.get("0", int(next_variant)),  # legacy field
        "cursors": cursors,
        "metric": metric,
        "block_variants": int(block_variants),
        "sample_hash": _sample_hash(sample_ids),
        "n_samples": len(sample_ids),
        "leaves": sorted(acc.keys()),
        "layout": layout,
        "mesh_shape": (list(plan.mesh.devices.shape) if plan is not None
                       else None),
        "mode": plan.mode if plan is not None else None,
        "process_count": jax.process_count(),
        "stream_stats": dict(stream_stats or {}),
        "extra": dict(extra) if extra else None,
    }
    primary_error: Exception | None = None
    if is_primary:
        try:
            # Every non-primary process wrote exactly one sidecar before
            # the barrier, so enumerate them BY PROCESS INDEX and fail
            # loudly on a missing one — discovering them via listdir()
            # would let a stale NFS directory cache silently drop a
            # process's checksums from the manifest, quietly disabling
            # verification for exactly those tiles.
            for peer in range(1, jax.process_count()):
                fpath = os.path.join(tmp, f"checksums.{peer}.json")
                try:
                    with open(fpath) as f:
                        checksums.update(json.load(f))
                except OSError as e:
                    raise RuntimeError(
                        f"checkpoint save: checksum sidecar from process "
                        f"{peer} is missing/unreadable after the write "
                        f"barrier ({e}) — the checkpoint directory is not "
                        "consistently visible across processes (multi-host "
                        "--checkpoint-dir must be a shared filesystem)"
                    )
                os.remove(fpath)
            manifest["sha256"] = checksums
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
            # Never a window with zero good checkpoints: move the old one
            # aside, land the new one, and KEEP the old as `.old` — one
            # generation of history (one extra checkpoint of disk), so a
            # latest checkpoint that later fails checksum verification
            # falls back to the previous good state instead of restarting
            # the job from zero. A crash mid-sequence still leaves either
            # `path` or `path.old` intact (load() checks both).
            with telemetry.span("checkpoint.rotate", cat="checkpoint"):
                old = path + ".old"
                if os.path.exists(old):
                    shutil.rmtree(old)
                if os.path.exists(path):
                    os.replace(path, old)
                os.replace(tmp, path)
        except Exception as e:
            primary_error = e
    _vote_all_ok(primary_error is None, lambda bad: RuntimeError(
        "checkpoint save: sidecar merge or rotation failed on the "
        "primary process (see its log for the cause); the checkpoint "
        "directory was left on the previous good generation"
    ))
    if primary_error is not None:
        raise primary_error


def _load_leaf(path: str, k: str, layout: str, manifest: dict, plan):
    """One accumulator leaf back onto the devices it belongs on."""
    if layout == "full":
        host = np.load(os.path.join(path, f"{k}.npy"))
        if plan is None:
            return jax.device_put(host)
        from spark_examples_tpu.parallel.gram_sharded import _acc_shardings

        sh = _acc_shardings(plan, manifest["metric"]).get(k)
        return jax.device_put(host, sh)
    # Tiled leaf: every device reads exactly its own tile file — the
    # callback receives each addressable shard's global index and maps
    # it to the file that shard was saved under. Host peak = one tile.
    if plan is None:
        raise ValueError(
            f"checkpoint at {path} holds tiled leaf {k!r} but no plan "
            "was given to place it — pass the job's GramPlan"
        )
    n = manifest["n_samples"]
    sharding = plan.acc_sharding

    def cb(index):
        return np.load(os.path.join(path, _tile_name(k, index)))

    return jax.make_array_from_callback((n, n), sharding, cb)


def _local_files(manifest: dict, plan, sums: dict) -> list[str]:
    """The subset of checkpoint files THIS process will load: replicated
    leaves plus its own tiles. Verifying peers' tiles too would multiply
    shared-FS read traffic by process_count (~11.6 GB of tiles becomes
    ~93 GB over NFS at 8 processes) for no safety: each process only
    ever places its own shards, and the agreement round already turns
    any process's local verification failure into a global abort."""
    layout = manifest.get("layout") or {}
    if (plan is None or jax.process_count() == 1
            or not any(v == "tiles" for v in layout.values())):
        return sorted(sums)
    n = manifest["n_samples"]
    idx_map = plan.acc_sharding.devices_indices_map((n, n))
    addressable = plan.acc_sharding.addressable_devices
    mine: set[str] = set()
    for k, lay in layout.items():
        if lay == "tiles":
            mine.update(_tile_name(k, idx_map[d]) for d in addressable)
        else:
            mine.add(f"{k}.npy")
    return sorted(f for f in sums if f in mine)


@telemetry.traced("checkpoint.verify", cat="checkpoint")
def _verify_files(path: str, manifest: dict, plan=None) -> str | None:
    """Re-hash this process's data files against the manifest; a reason
    string on the first mismatch/unreadable file, None when all verify.
    Manifests without a ``sha256`` map (pre-integrity checkpoints)
    verify vacuously — rejecting them would orphan every existing
    checkpoint.

    Deliberate tradeoff: a resume reads each local file twice (hash
    here, np.load in _load_leaf). Folding the two into one pass would
    mean either buffering every local tile in host RAM (breaking the
    O(tile) host-peak guarantee the tiled layout exists for) or
    verifying after placement (feeding unverified bytes to devices and
    aborting mid-load). Resume is the rare path; save — which runs
    every K blocks — hashes in one pass via _TeeHashWriter."""
    sums = manifest.get("sha256")
    if not sums:
        return None
    for fname in _local_files(manifest, plan, sums):
        fpath = os.path.join(path, fname)
        try:
            faults.fire("checkpoint.tile_read", path=fpath)
            got = _sha256_file(fpath)
        except OSError as e:
            return f"{fname}: unreadable ({e})"
        if got != sums[fname]:
            return f"{fname}: sha256 mismatch (truncated or corrupt)"
    return None


def _usable_generation(path: str, plan=None):
    """First checkpoint generation (`path`, then `path.old`) whose
    manifest parses and whose files verify -> (dir, manifest), None when
    no generation exists at all, CheckpointCorruptError when generations
    exist but every one fails verification."""
    reasons: list[str] = []
    for gen in (path, path + ".old"):
        manifest_path = os.path.join(gen, "manifest.json")
        if not os.path.exists(manifest_path):
            continue
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            reasons.append(f"{gen}: manifest unreadable ({e})")
            continue
        reason = _verify_files(gen, manifest, plan)
        if reason is not None:
            reasons.append(f"{gen}: {reason}")
            continue
        if reasons:
            warnings.warn(
                f"checkpoint integrity: {'; '.join(reasons)} — falling "
                f"back to the previous good generation at {gen}",
                RuntimeWarning,
                stacklevel=3,
            )
        return gen, manifest
    if reasons:
        raise CheckpointCorruptError(
            "no usable checkpoint generation: " + "; ".join(reasons)
            + " — recover the files or delete the checkpoint "
            "directory to deliberately restart from zero"
        )
    return None


def _agree_generation(path: str, found, local_error=None, plan=None):
    """Multi-host: every process must resume from the SAME generation.

    Verification is per-process (a transient NFS read error or a stale
    attribute cache can make one process reject the latest generation
    while its peers accept it); without agreement each process would
    load its own shards and cursor from a different generation and the
    device-sharded accumulator would silently mix the two. One
    allgather of the chosen generation index settles it: if any process
    fell back, every process adopts the older generation (re-verifying
    it locally); if some processes found no usable generation while
    others did, the shared-filesystem contract is broken and every
    process aborts together in this round.

    ``local_error``: the CheckpointCorruptError this process's own
    verification raised, if any. It MUST be voted through the gather
    rather than raised before it — a process that raised pre-gather
    while its peers entered the allgather would park those peers in the
    collective until the distributed timeout, the exact hang this
    layer's multihost watchdog exists to prevent."""
    if jax.process_count() <= 1:
        if local_error is not None:
            raise local_error
        return found
    from jax.experimental import multihost_utils

    # Ordered worst-to-best: latest=0, .old=1, nothing=2, corrupt=3.
    NONE, CORRUPT = 2, 3
    if local_error is not None:
        mine = CORRUPT
    else:
        mine = NONE if found is None else (0 if found[0] == path else 1)
    votes = np.asarray(multihost_utils.process_allgather(np.int32(mine)))
    if (votes == CORRUPT).any():
        if local_error is not None:
            raise local_error
        raise CheckpointCorruptError(
            f"process(es) "
            f"{[int(i) for i in np.flatnonzero(votes == CORRUPT)]} found "
            f"every checkpoint generation at {path} corrupt — aborting "
            "the resume on every process (recover the files or delete "
            "the checkpoint directory to deliberately restart from zero)"
        )
    if (votes == NONE).any():
        if (votes == NONE).all():
            return found  # genuinely no checkpoint anywhere
        raise CheckpointCorruptError(
            f"process(es) {[int(i) for i in np.flatnonzero(votes == NONE)]} "
            f"found no usable checkpoint generation at {path} while "
            "others did — the checkpoint directory is not consistently "
            "visible across processes (multi-host --checkpoint-dir must "
            "be a filesystem shared by every process)"
        )
    agreed = int(votes.max())
    result, reason = found, None
    if agreed != mine:
        # A peer fell back further than this process: adopt the agreed
        # (older) generation so all processes resume from one state —
        # re-verifying it locally, since this process never checked it
        # (its own newer generation passed).
        gen = path + ".old" if agreed else path
        try:
            with open(os.path.join(gen, "manifest.json")) as f:
                manifest = json.load(f)
            reason = _verify_files(gen, manifest, plan)
        except (OSError, ValueError) as e:
            reason = f"manifest unusable ({e})"
        if reason is None:
            result = gen, manifest
    # Confirmation round: an adopter whose re-verification failed must
    # not raise before its peers leave the agreement protocol — they
    # would proceed into load()'s device placement and park in the next
    # collective (everyone participates, adopters and non-adopters
    # alike).
    _vote_all_ok(reason is None, lambda bad: CheckpointCorruptError(
        f"peers agreed on a checkpoint generation at {path}, but "
        f"process(es) {bad} cannot use it"
    ))
    if reason is not None:
        raise CheckpointCorruptError(
            f"peers agreed on a checkpoint generation at {path}, but "
            f"it is unusable on this process: {reason}"
        )
    if agreed != mine:
        warnings.warn(
            f"checkpoint generation agreement: adopting {result[0]} "
            "because a peer process could not use a newer generation",
            RuntimeWarning,
            stacklevel=3,
        )
    return result


def _promote_fallback(path: str, found):
    """When load resolved to ``.old`` (latest corrupt or missing),
    promote the good generation back to ``path`` — the corrupt latest
    is kept aside as ``path.corrupt`` for recovery. Without this, the
    NEXT save's rotation would rmtree the only good generation and
    demote the corrupt one into ``.old``: a crash in that window leaves
    zero good checkpoints, and even without a crash the one-generation
    fallback would be dead until the save after next."""
    gen, manifest = found
    if gen == path:
        return found
    proc = jax.process_index() if jax.process_count() > 1 else 0
    err: Exception | None = None
    if proc == 0:
        try:
            if os.path.exists(path):
                corrupt = path + ".corrupt"
                if os.path.exists(corrupt):
                    shutil.rmtree(corrupt)
                os.replace(path, corrupt)
                warnings.warn(
                    f"checkpoint: corrupt latest generation set aside "
                    f"as {corrupt}; delete it once recovered",
                    RuntimeWarning,
                    stacklevel=3,
                )
            os.replace(gen, path)
        except OSError as e:
            err = e
    # Peers must not read the generation while process 0 renames it;
    # the vote's gather is the barrier, and it carries the promotion
    # outcome so a process-0 failure aborts every process in the same
    # round instead of parking peers on files that moved.
    _vote_all_ok(err is None, lambda bad: CheckpointCorruptError(
        f"promotion of fallback checkpoint generation {gen} failed on "
        "process 0 — see its log"
    ))
    if err is not None:
        raise CheckpointCorruptError(
            f"cannot promote fallback checkpoint generation {gen} back "
            f"to {path}: {err}"
        )
    # Counted only once the promotion actually succeeded on every
    # process — the single funnel every adopted resume-from-.old passes
    # through; a failed promotion aborts the job and must not inflate
    # the adopted-fallback count in post-mortem metrics.
    telemetry.count("checkpoint.fallback")
    telemetry.event("checkpoint.fallback", cat="checkpoint", generation=gen)
    return path, manifest


def load(path: str, metric: str, sample_ids: list[str],
         block_variants: int | None = None, plan=None,
         leaves: list[str] | None = None, expect_extra: dict | None = None):
    """Load (acc, next_variant, stream_stats) or None when absent.

    ``leaves``: expected accumulator leaf names when the checkpoint is
    NOT a gram accumulation (the sketch solver's state) — without it the
    expectation derives from the metric's gram pieces as before.
    ``expect_extra``: required value of the manifest's ``extra`` record
    (see :func:`save`); a mismatch is rejected like any other
    incompatibility, never silently mixed in.

    Every file is checksum-verified BEFORE any leaf is placed on a
    device; a truncated/corrupt generation falls back to ``.old`` (with
    a warning), and when every generation is corrupt the load raises
    :class:`CheckpointCorruptError` instead of feeding garbage into the
    accumulation.

    Incompatible checkpoints (different metric, cohort, block grid,
    tile grid, or process count) are rejected rather than silently mixed
    into the accumulation: a resume with a different ``block_variants``
    would misalign the cursor against the block grid and double-count or
    skip variants; a resume under a different mesh/mode would need a
    re-tiling no interrupted job should do implicitly.
    """
    # Span inlined rather than @telemetry.traced: the fallback/corruption
    # warnings in this load path use stacklevel=3 tuned to land on
    # load()'s CALLER, and a decorator's wrapper frame would re-attribute
    # every operator-facing warning to telemetry.py (and break
    # module-keyed warning filters).
    with telemetry.span("checkpoint.load", cat="checkpoint"):
        try:
            mine, local_error = _usable_generation(path, plan), None
        except CheckpointCorruptError as e:
            # Don't raise yet in multi-host: peers may already be in the
            # agreement allgather — vote the corruption instead so every
            # process aborts together (_agree_generation re-raises it).
            mine, local_error = None, e
        found = _agree_generation(path, mine, local_error, plan)
        if found is None:
            return None
        path, manifest = _promote_fallback(path, found)
        if block_variants is not None and manifest["block_variants"] != block_variants:
            raise ValueError(
                f"checkpoint at {path} was written with --block-variants "
                f"{manifest['block_variants']}, job wants {block_variants}; "
                "resume must keep the same block grid"
            )
        if manifest["metric"] != metric:
            raise ValueError(
                f"checkpoint at {path} is for metric {manifest['metric']!r}, "
                f"job wants {metric!r}"
            )
        if manifest["sample_hash"] != _sample_hash(sample_ids):
            raise ValueError(
                f"checkpoint at {path} was built for a different cohort "
                f"({manifest['n_samples']} samples)"
            )
        if expect_extra is not None:
            got = manifest.get("extra") or {}
            if got != dict(expect_extra):
                raise ValueError(
                    f"checkpoint at {path} was written under solver/"
                    f"sketch settings {got} but this job runs "
                    f"{dict(expect_extra)} — a resume must keep the same "
                    "probe seed/rank/rung (delete the checkpoint "
                    "directory to deliberately restart)"
                )
        if leaves is not None:
            expected = sorted(leaves)
        else:
            from spark_examples_tpu.ops import gram

            expected = sorted(gram.acc_leaves(metric))
        if manifest["leaves"] != expected:
            raise ValueError(
                f"checkpoint at {path} holds accumulator leaves "
                f"{manifest['leaves']} but this version expects {expected} "
                f"for metric {metric!r} (stale accumulator schema — delete "
                "the checkpoint to restart)"
            )
        layout = manifest.get("layout") or {k: "full" for k in manifest["leaves"]}
        # Cursors are per-process offsets into per-process ingest
        # partitions, so a resume under a DIFFERENT process count would
        # misapply every cursor regardless of leaf layout — reject it
        # outright (re-partitioning a partial sum is never implicit).
        if manifest.get("process_count", 1) != jax.process_count():
            raise ValueError(
                f"checkpoint at {path} was written by "
                f"{manifest.get('process_count', 1)} process(es); this job "
                f"runs {jax.process_count()} — per-process ingest cursors "
                "do not transfer across process counts"
            )
        if any(v == "tiles" for v in layout.values()):
            want_mesh = list(plan.mesh.devices.shape) if plan is not None else None
            if (
                plan is None
                or manifest.get("mesh_shape") != want_mesh
                or manifest.get("mode") != plan.mode
            ):
                raise ValueError(
                    f"checkpoint at {path} is tiled for mesh "
                    f"{manifest.get('mesh_shape')} mode "
                    f"{manifest.get('mode')!r}; this job runs mesh "
                    f"{want_mesh} mode {getattr(plan, 'mode', None)!r} — "
                    "resume must keep the tile grid (re-tiling a partial "
                    "sum is never implicit)"
                )
        acc = {
            k: _load_leaf(path, k, layout.get(k, "full"), manifest, plan)
            for k in manifest["leaves"]
        }
        cursors = manifest.get("cursors") or {"0": manifest["next_variant"]}
        proc = jax.process_index() if jax.process_count() > 1 else 0
        cursor = int(cursors.get(str(proc), manifest["next_variant"]))
        return acc, cursor, manifest.get("stream_stats", {})

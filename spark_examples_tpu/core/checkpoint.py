"""Partial-Gram checkpoint / resume — shard-aware.

The reference had nothing here: a failed PCA job reran from scratch,
recovery being Spark lineage recompute (SURVEY.md §5 "Checkpoint /
resume", "Failure detection"). The TPU-native design does better because
the Gram accumulation is associative: persisting (accumulators, variant
cursor) every K blocks makes recovery "resume from the last checkpointed
partial sum", and the same mechanism powers the streaming/incremental
config (BASELINE.md config 5).

Layout discipline matters at the tile2d regime (BASELINE.md config 4): a
76k^2 f32 leaf is ~23 GB, and the whole point of the tiling is that no
single host or device ever materializes it. So tiled leaves are saved
**one file per addressable tile** (``{leaf}.t{row0}_{col0}.npy``, the
filename keyed by the tile's global offsets) and restored through
``jax.make_array_from_callback`` under the plan's sharding — each device
reads back exactly its own tile, host peak stays O(tile), and in
multi-host runs each process touches only its own tiles. Replicated
leaves (variant mode, scalars) keep the simple one-``.npy``-per-leaf
format. A manifest records the tile grid; resuming under a different
mesh/mode is rejected rather than silently re-laid-out (re-tiling a
partial sum is possible in principle but never what an interrupted
production job wants to discover it did implicitly).

Writes are atomic (tmp dir + rename; multi-host writers barrier before
process 0 rotates the directory) so a crash mid-write never corrupts the
latest good checkpoint.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

import jax
import numpy as np


def _sample_hash(sample_ids: list[str]) -> str:
    h = hashlib.sha256("\n".join(sample_ids).encode()).hexdigest()
    return h[:16]


def _is_replicated(v) -> bool:
    """True when every addressable shard holds the full leaf value."""
    if not isinstance(v, jax.Array):
        return True
    shards = v.addressable_shards
    return all(s.data.shape == v.shape for s in shards)


def _tile_name(leaf: str, index) -> str:
    offs = [(sl.start or 0) if isinstance(sl, slice) else int(sl)
            for sl in index]
    return f"{leaf}.t" + "_".join(str(o) for o in offs) + ".npy"


def _barrier(name: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def save(
    path: str,
    acc: dict,
    next_variant: int,
    metric: str,
    block_variants: int,
    sample_ids: list[str],
    stream_stats: dict | None = None,
    plan=None,
) -> None:
    """Atomically persist accumulators + resume cursor.

    Tiled leaves (tile2d plans) are written one file per addressable
    shard — no full N x N leaf ever materializes on the host (the
    VERDICT r3 weak-#1 defect). ``plan`` records the tile grid in the
    manifest; without it (legacy callers, host-built accumulators) every
    leaf is treated as replicated and saved whole.

    ``stream_stats``: the runner's producer-side stream statistics
    (currently ``max_value``) — persisted so a resumed dot/euclidean
    job's int32-exactness guard still sees the largest value of the
    *whole* stream, not just the post-resume tail.

    Multi-host: a SHARED filesystem is required — every process writes
    its own tiles into the shared directory, process 0 writes the
    manifest and performs the atomic rotation after a cross-process
    barrier (without a shared FS, non-primary tmp dirs would never be
    rotated and load() would find no manifest there). ``next_variant``
    is this process's LOCAL cursor into its own ingest partition,
    recorded per process.
    """
    proc = jax.process_index() if jax.process_count() > 1 else 0
    is_primary = proc == 0
    tmp = path + ".tmp"
    if is_primary:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
    _barrier("ckpt-mkdir")
    os.makedirs(tmp, exist_ok=True)  # idempotent on the shared FS

    layout: dict[str, str] = {}
    for k, v in acc.items():
        if _is_replicated(v):
            layout[k] = "full"
            if is_primary:
                if isinstance(v, jax.Array) and not v.is_fully_addressable:
                    host = np.asarray(v.addressable_data(0))
                else:
                    host = np.asarray(v)
                np.save(os.path.join(tmp, f"{k}.npy"), host)
        else:
            layout[k] = "tiles"
            for sh in v.addressable_shards:
                np.save(
                    os.path.join(tmp, _tile_name(k, sh.index)),
                    np.asarray(sh.data),
                )

    # Per-process cursors: each process resumes its own partition.
    cursors = {str(proc): int(next_variant)}
    if jax.process_count() > 1:
        from spark_examples_tpu.parallel import multihost as mh

        gathered = mh.allgather(np.int64(next_variant))
        cursors = {str(i): int(c) for i, c in enumerate(gathered)}

    manifest = {
        "next_variant": cursors.get("0", int(next_variant)),  # legacy field
        "cursors": cursors,
        "metric": metric,
        "block_variants": int(block_variants),
        "sample_hash": _sample_hash(sample_ids),
        "n_samples": len(sample_ids),
        "leaves": sorted(acc.keys()),
        "layout": layout,
        "mesh_shape": (list(plan.mesh.devices.shape) if plan is not None
                       else None),
        "mode": plan.mode if plan is not None else None,
        "process_count": jax.process_count(),
        "stream_stats": dict(stream_stats or {}),
    }
    _barrier("ckpt-tiles-written")
    if is_primary:
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        # Never a window with zero good checkpoints: move the old one
        # aside, land the new one, then delete the old. A crash
        # mid-sequence leaves either `path` or `path.old` intact
        # (load() checks both).
        old = path + ".old"
        if os.path.exists(old):
            shutil.rmtree(old)
        if os.path.exists(path):
            os.replace(path, old)
        os.replace(tmp, path)
        if os.path.exists(old):
            shutil.rmtree(old)
    _barrier("ckpt-rotated")


def _load_leaf(path: str, k: str, layout: str, manifest: dict, plan):
    """One accumulator leaf back onto the devices it belongs on."""
    if layout == "full":
        host = np.load(os.path.join(path, f"{k}.npy"))
        if plan is None:
            return jax.device_put(host)
        from spark_examples_tpu.parallel.gram_sharded import _acc_shardings

        sh = _acc_shardings(plan, manifest["metric"]).get(k)
        return jax.device_put(host, sh)
    # Tiled leaf: every device reads exactly its own tile file — the
    # callback receives each addressable shard's global index and maps
    # it to the file that shard was saved under. Host peak = one tile.
    if plan is None:
        raise ValueError(
            f"checkpoint at {path} holds tiled leaf {k!r} but no plan "
            "was given to place it — pass the job's GramPlan"
        )
    n = manifest["n_samples"]
    sharding = plan.acc_sharding

    def cb(index):
        return np.load(os.path.join(path, _tile_name(k, index)))

    return jax.make_array_from_callback((n, n), sharding, cb)


def load(path: str, metric: str, sample_ids: list[str],
         block_variants: int | None = None, plan=None):
    """Load (acc, next_variant, stream_stats) or None when absent.

    Incompatible checkpoints (different metric, cohort, block grid,
    tile grid, or process count) are rejected rather than silently mixed
    into the accumulation: a resume with a different ``block_variants``
    would misalign the cursor against the block grid and double-count or
    skip variants; a resume under a different mesh/mode would need a
    re-tiling no interrupted job should do implicitly.
    """
    manifest_path = os.path.join(path, "manifest.json")
    if not os.path.exists(manifest_path):
        # Crash window fallback: the previous good checkpoint was moved
        # aside but the new one never landed.
        old = path + ".old"
        if os.path.exists(os.path.join(old, "manifest.json")):
            path, manifest_path = old, os.path.join(old, "manifest.json")
        else:
            return None
    with open(manifest_path) as f:
        manifest = json.load(f)
    if block_variants is not None and manifest["block_variants"] != block_variants:
        raise ValueError(
            f"checkpoint at {path} was written with --block-variants "
            f"{manifest['block_variants']}, job wants {block_variants}; "
            "resume must keep the same block grid"
        )
    if manifest["metric"] != metric:
        raise ValueError(
            f"checkpoint at {path} is for metric {manifest['metric']!r}, "
            f"job wants {metric!r}"
        )
    if manifest["sample_hash"] != _sample_hash(sample_ids):
        raise ValueError(
            f"checkpoint at {path} was built for a different cohort "
            f"({manifest['n_samples']} samples)"
        )
    from spark_examples_tpu.ops import gram

    expected = sorted(
        ("zz", "nvar") if metric == "grm" else gram.PIECES_FOR_METRIC[metric]
    )
    if manifest["leaves"] != expected:
        raise ValueError(
            f"checkpoint at {path} holds accumulator leaves "
            f"{manifest['leaves']} but this version expects {expected} "
            f"for metric {metric!r} (stale accumulator schema — delete "
            "the checkpoint to restart)"
        )
    layout = manifest.get("layout") or {k: "full" for k in manifest["leaves"]}
    # Cursors are per-process offsets into per-process ingest
    # partitions, so a resume under a DIFFERENT process count would
    # misapply every cursor regardless of leaf layout — reject it
    # outright (re-partitioning a partial sum is never implicit).
    if manifest.get("process_count", 1) != jax.process_count():
        raise ValueError(
            f"checkpoint at {path} was written by "
            f"{manifest.get('process_count', 1)} process(es); this job "
            f"runs {jax.process_count()} — per-process ingest cursors "
            "do not transfer across process counts"
        )
    if any(v == "tiles" for v in layout.values()):
        want_mesh = list(plan.mesh.devices.shape) if plan is not None else None
        if (
            plan is None
            or manifest.get("mesh_shape") != want_mesh
            or manifest.get("mode") != plan.mode
        ):
            raise ValueError(
                f"checkpoint at {path} is tiled for mesh "
                f"{manifest.get('mesh_shape')} mode "
                f"{manifest.get('mode')!r}; this job runs mesh "
                f"{want_mesh} mode {getattr(plan, 'mode', None)!r} — "
                "resume must keep the tile grid (re-tiling a partial "
                "sum is never implicit)"
            )
    acc = {
        k: _load_leaf(path, k, layout.get(k, "full"), manifest, plan)
        for k in manifest["leaves"]
    }
    cursors = manifest.get("cursors") or {"0": manifest["next_variant"]}
    proc = jax.process_index() if jax.process_count() > 1 else 0
    cursor = int(cursors.get(str(proc), manifest["next_variant"]))
    return acc, cursor, manifest.get("stream_stats", {})

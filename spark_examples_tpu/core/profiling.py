"""Per-phase timing and profiling.

The reference had no in-repo tracing; it leaned on the Spark web UI and
stage/task metrics (SURVEY.md §5 "Tracing / profiling"). The TPU-native
replacement is (a) a phase-timer that blocks on device results so
wall-clock numbers are honest, emitting the structured per-phase metrics
the baseline asks for (ingest MB/s, Gram GFLOPS, eigh GFLOPS/chip —
BASELINE.md), and (b) optional ``jax.profiler`` trace capture viewable in
TensorBoard.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field

import jax
import numpy as np


def hard_sync(tree):
    """A *real* completion barrier.

    On the experimental axon PJRT platform ``jax.block_until_ready``
    returns before device execution finishes (verified empirically:
    a 3.4-TFLOP program "completed" in 0.1 ms but its first host fetch
    took seconds). Fetching one element to host forces the dependency
    chain — but indexing the *global* array forces only the shard(s)
    holding element (0, …, 0), so sharded leaves fetch one element from
    every locally-addressable shard instead: each device's chain is
    forced, and wall-clock timings stay honest on a mesh. Returns its
    argument.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for sh in shards:
                # One element per shard (no ravel — that would
                # materialise a flattened copy, resharding tiled
                # layouts); sh.data is that device's local tile.
                np.asarray(sh.data[(0,) * sh.data.ndim])
        else:
            np.asarray(leaf[(0,) * leaf.ndim])
    return tree


@dataclass
class PhaseTimer:
    """Accumulates named phase durations; durations are wall-clock with
    ``block_until_ready`` applied to whatever the phase returns."""

    phases: dict[str, float] = field(default_factory=dict)
    counters: dict[str, float] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def timed(self, name: str, fn, *args, **kwargs):
        with self.phase(name):
            out = fn(*args, **kwargs)
            out = hard_sync(out)
        return out

    def add(self, counter: str, amount: float) -> None:
        self.counters[counter] = self.counters.get(counter, 0.0) + amount

    def report(self) -> dict:
        rep: dict[str, float] = dict(self.phases)
        # Derived throughput metrics where the raw counters exist. The
        # streaming-PCoA refresh hook runs *inside* the gram loop, so
        # its wall-clock (tracked as "stream_refresh") is subtracted
        # before dividing — otherwise config-5 runs would report
        # deflated Gram GFLOPS / ingest MB/s and hide exactly the
        # overhead the phase exists to expose.
        refresh_t = self.phases.get("stream_refresh", 0.0)
        gram_t = max(self.phases.get("gram", 0.0) - refresh_t, 0.0)
        if "gram_flops" in self.counters and gram_t:
            rep["gram_gflops_per_s"] = (
                self.counters["gram_flops"] / gram_t / 1e9
            )
        # Ingest bytes are counted wherever streaming happens — a
        # dedicated "ingest" phase if one exists, else the gram loop
        # (whose wall-clock includes the overlapped host reads).
        stream_t = self.phases.get("ingest") or gram_t
        if "ingest_bytes" in self.counters and stream_t:
            rep["ingest_mb_per_s"] = (
                self.counters["ingest_bytes"] / stream_t / 1e6
            )
        if "eigh_flops" in self.counters and self.phases.get("eigh"):
            rep["eigh_gflops_per_s"] = (
                self.counters["eigh_flops"] / self.phases["eigh"] / 1e9
            )
        return rep

    def dump(self) -> str:
        return json.dumps(self.report(), sort_keys=True)


@contextlib.contextmanager
def trace(logdir: str | None):
    """Capture a ``jax.profiler`` trace into ``logdir`` when set."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

"""Per-phase timing and profiling.

The reference had no in-repo tracing; it leaned on the Spark web UI and
stage/task metrics (SURVEY.md §5 "Tracing / profiling"). The TPU-native
replacement is (a) a phase-timer that blocks on device results so
wall-clock numbers are honest, emitting the structured per-phase metrics
the baseline asks for (ingest MB/s, Gram GFLOPS, eigh GFLOPS/chip —
BASELINE.md), and (b) optional ``jax.profiler`` trace capture viewable in
TensorBoard.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
from dataclasses import dataclass, field

import jax
import numpy as np

from spark_examples_tpu.core import telemetry


def _leaf_sum_program(leaf):
    """One scalar depending on every element of the leaf (the full-read
    sum means sharded leaves force every shard via the cross-device
    reduction — no device's chain can be skipped)."""
    import jax.numpy as jnp

    return jnp.sum(leaf.astype(jnp.float32))


# Jitted PER LEAF, not per tree: the compile cache keys on the leaf's
# shape/dtype/sharding, which recur across call sites (the same (N, N)
# accumulator shape appears in gram, finalize, and checkpoint trees), so
# the one-time trace+compile charge amortizes across every phase instead
# of re-paying per distinct tree signature.
_leaf_sum = jax.jit(_leaf_sum_program)


def _expand_dataclasses(leaf):
    """Recurse into unregistered dataclass instances (PCoAResult,
    PCAResult, …): jax.tree_util treats them as opaque leaves, so
    without this a ``hard_sync(fit_pcoa(...))`` would silently barrier
    on NOTHING — measured: a dense N=2504 eigh "completed" in 2 ms while
    the real work (371 ms) drained into whichever later phase first
    touched the result. Timing bugs of this shape are exactly what
    hard_sync exists to prevent, so it defends itself. Field values are
    themselves tree-flattened (a dataclass may hold a dict/list of
    arrays — GramRun.acc does) and any nested dataclasses expand
    recursively."""
    if dataclasses.is_dataclass(leaf) and not isinstance(leaf, type):
        for f in dataclasses.fields(leaf):
            for sub in jax.tree_util.tree_leaves(getattr(leaf, f.name)):
                yield from _expand_dataclasses(sub)
    else:
        yield leaf


def hard_sync(tree):
    """A *real* completion barrier.

    On the experimental axon PJRT platform ``jax.block_until_ready``
    returns before device execution finishes (verified empirically:
    a 3.4-TFLOP program "completed" in 0.1 ms but its first host fetch
    took seconds), so the barrier must round-trip data the computation
    produced. Doing that with a per-leaf element fetch costs one host
    link round-trip per leaf — measured ~77 ms *each* through a slow
    dev tunnel, which at 4 accumulator leaves charged ~0.3 s of pure
    RTT to every timed phase. Instead: per-leaf jitted checksums
    combined on device (dispatch is async) and ONE scalar D2H at the
    end. Returns its argument.
    """
    leaves = [
        leaf
        for raw in jax.tree_util.tree_leaves(tree)
        for leaf in _expand_dataclasses(raw)
        if isinstance(leaf, jax.Array)
    ]
    if not leaves:
        return tree
    try:
        total = None
        for leaf in leaves:
            s = _leaf_sum(leaf)
            total = s if total is None else total + s  # eager async add
        np.asarray(total)
    except Exception as e:
        # Mixed-mesh / committed-device trees whose scalars can't be
        # combined in one place: fall back to one element per shard.
        # Warn once per telemetry reset — the fallback pays a host
        # round-trip per shard per leaf, the exact per-phase timing
        # inflation the checksum path exists to remove, and silent
        # degradation would quietly deflate every reported TFLOP/s
        # number. Every occurrence counts into the "hard_sync.fallback"
        # telemetry counter (so a degraded run is visible in metrics
        # long after the one warning scrolled away), and
        # ``telemetry.reset()`` re-arms the warning — testable, unlike
        # the old module-global latch.
        if telemetry.count("hard_sync.fallback") == 1.0:
            import warnings

            warnings.warn(
                f"hard_sync checksum barrier failed ({type(e).__name__}: "
                f"{e}); falling back to per-shard element fetches — "
                "timed phases now include one host RTT per shard per "
                "leaf and reported throughputs will read low",
                RuntimeWarning,
                stacklevel=2,
            )
        for leaf in leaves:
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                for sh in shards:
                    np.asarray(sh.data[(0,) * sh.data.ndim])
            else:
                np.asarray(leaf[(0,) * leaf.ndim])
    return tree


# Registry counter -> report key for resilience incidents surfaced by
# PhaseTimer.report(). The registry is process-wide, so each timer
# snapshots these at construction and reports only the DELTA — a retry
# absorbed by an earlier run in the same process must not show up as a
# phantom incident in every later timer's report.
_INCIDENT_COUNTERS = (
    ("ingest.retries", "ingest_retries"),
    ("ingest.reopens", "ingest_reopens"),
    ("ingest.corrupt_blocks", "ingest_corrupt_blocks"),
)


@dataclass
class PhaseTimer:
    """Accumulates named phase durations; durations are wall-clock with
    ``block_until_ready`` applied to whatever the phase returns.

    Every phase duration and counter is mirrored into the process-wide
    telemetry registry (core/telemetry.py: counter ``phase.<name>`` plus
    a same-named span on the trace timeline), which is what lets the
    exporter derive the identical throughputs this timer reports."""

    phases: dict[str, float] = field(default_factory=dict)
    counters: dict[str, float] = field(default_factory=dict)
    incident_base: dict[str, float] = field(default_factory=dict,
                                            repr=False, compare=False)

    def __post_init__(self):
        if not self.incident_base:
            self.incident_base = {
                name: telemetry.counter_value(name)
                for name, _ in _INCIDENT_COUNTERS
            }

    @contextlib.contextmanager
    def phase(self, name: str):
        sp = telemetry.begin("phase." + name, cat="phase")
        try:
            yield
        finally:
            dt = sp.end()
            self.phases[name] = self.phases.get(name, 0.0) + dt
            telemetry.count("phase." + name, dt)

    def timed(self, name: str, fn, *args, **kwargs):
        with self.phase(name):
            out = fn(*args, **kwargs)
            out = hard_sync(out)
        return out

    def add(self, counter: str, amount: float) -> None:
        self.counters[counter] = self.counters.get(counter, 0.0) + amount
        telemetry.count(counter, amount)

    def report(self) -> dict:
        rep: dict[str, float] = dict(self.phases)
        # Derived throughputs: the one shared formula (telemetry.
        # derive_throughputs) — the exporter's metrics.json and this
        # report can only agree.
        rep.update(telemetry.derive_throughputs(self.phases, self.counters))
        # Resilience incidents (ingest/resilient.py counts them into the
        # process-wide registry — it has no timer handle): a silently
        # retrying run must be distinguishable from a clean one in the
        # same --timings / bench output that reports its throughput.
        # Delta against this timer's construction-time snapshot, so
        # incidents belong to the run that owned the timer.
        for cname, key in _INCIDENT_COUNTERS:
            v = telemetry.counter_value(cname) - self.incident_base.get(
                cname, 0.0)
            if v > 0:
                rep[key] = v
        return rep

    def dump(self) -> str:
        return json.dumps(self.report(), sort_keys=True)


@contextlib.contextmanager
def trace(logdir: str | None):
    """Capture a ``jax.profiler`` trace into ``logdir`` when set."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

"""Per-phase timing and profiling.

The reference had no in-repo tracing; it leaned on the Spark web UI and
stage/task metrics (SURVEY.md §5 "Tracing / profiling"). The TPU-native
replacement is (a) a phase-timer that blocks on device results so
wall-clock numbers are honest, emitting the structured per-phase metrics
the baseline asks for (ingest MB/s, Gram GFLOPS, eigh GFLOPS/chip —
BASELINE.md), and (b) optional ``jax.profiler`` trace capture viewable in
TensorBoard.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from dataclasses import dataclass, field

import jax
import numpy as np


def _leaf_sum_program(leaf):
    """One scalar depending on every element of the leaf (the full-read
    sum means sharded leaves force every shard via the cross-device
    reduction — no device's chain can be skipped)."""
    import jax.numpy as jnp

    return jnp.sum(leaf.astype(jnp.float32))


# Jitted PER LEAF, not per tree: the compile cache keys on the leaf's
# shape/dtype/sharding, which recur across call sites (the same (N, N)
# accumulator shape appears in gram, finalize, and checkpoint trees), so
# the one-time trace+compile charge amortizes across every phase instead
# of re-paying per distinct tree signature.
_leaf_sum = jax.jit(_leaf_sum_program)
_warned_fallback = False


def _expand_dataclasses(leaf):
    """Recurse into unregistered dataclass instances (PCoAResult,
    PCAResult, …): jax.tree_util treats them as opaque leaves, so
    without this a ``hard_sync(fit_pcoa(...))`` would silently barrier
    on NOTHING — measured: a dense N=2504 eigh "completed" in 2 ms while
    the real work (371 ms) drained into whichever later phase first
    touched the result. Timing bugs of this shape are exactly what
    hard_sync exists to prevent, so it defends itself. Field values are
    themselves tree-flattened (a dataclass may hold a dict/list of
    arrays — GramRun.acc does) and any nested dataclasses expand
    recursively."""
    if dataclasses.is_dataclass(leaf) and not isinstance(leaf, type):
        for f in dataclasses.fields(leaf):
            for sub in jax.tree_util.tree_leaves(getattr(leaf, f.name)):
                yield from _expand_dataclasses(sub)
    else:
        yield leaf


def hard_sync(tree):
    """A *real* completion barrier.

    On the experimental axon PJRT platform ``jax.block_until_ready``
    returns before device execution finishes (verified empirically:
    a 3.4-TFLOP program "completed" in 0.1 ms but its first host fetch
    took seconds), so the barrier must round-trip data the computation
    produced. Doing that with a per-leaf element fetch costs one host
    link round-trip per leaf — measured ~77 ms *each* through a slow
    dev tunnel, which at 4 accumulator leaves charged ~0.3 s of pure
    RTT to every timed phase. Instead: per-leaf jitted checksums
    combined on device (dispatch is async) and ONE scalar D2H at the
    end. Returns its argument.
    """
    leaves = [
        leaf
        for raw in jax.tree_util.tree_leaves(tree)
        for leaf in _expand_dataclasses(raw)
        if isinstance(leaf, jax.Array)
    ]
    if not leaves:
        return tree
    try:
        total = None
        for leaf in leaves:
            s = _leaf_sum(leaf)
            total = s if total is None else total + s  # eager async add
        np.asarray(total)
    except Exception as e:
        # Mixed-mesh / committed-device trees whose scalars can't be
        # combined in one place: fall back to one element per shard.
        # Warn ONCE — the fallback pays a host round-trip per shard per
        # leaf, the exact per-phase timing inflation the checksum path
        # exists to remove, and silent degradation would quietly deflate
        # every reported TFLOP/s number.
        global _warned_fallback
        if not _warned_fallback:
            _warned_fallback = True
            import warnings

            warnings.warn(
                f"hard_sync checksum barrier failed ({type(e).__name__}: "
                f"{e}); falling back to per-shard element fetches — "
                "timed phases now include one host RTT per shard per "
                "leaf and reported throughputs will read low",
                RuntimeWarning,
                stacklevel=2,
            )
        for leaf in leaves:
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                for sh in shards:
                    np.asarray(sh.data[(0,) * sh.data.ndim])
            else:
                np.asarray(leaf[(0,) * leaf.ndim])
    return tree


@dataclass
class PhaseTimer:
    """Accumulates named phase durations; durations are wall-clock with
    ``block_until_ready`` applied to whatever the phase returns."""

    phases: dict[str, float] = field(default_factory=dict)
    counters: dict[str, float] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def timed(self, name: str, fn, *args, **kwargs):
        with self.phase(name):
            out = fn(*args, **kwargs)
            out = hard_sync(out)
        return out

    def add(self, counter: str, amount: float) -> None:
        self.counters[counter] = self.counters.get(counter, 0.0) + amount

    def report(self) -> dict:
        rep: dict[str, float] = dict(self.phases)
        # Derived throughput metrics where the raw counters exist. The
        # streaming-PCoA refresh hook runs *inside* the gram loop, so
        # its wall-clock (tracked as "stream_refresh") is subtracted
        # before dividing — otherwise config-5 runs would report
        # deflated Gram GFLOPS / ingest MB/s and hide exactly the
        # overhead the phase exists to expose.
        refresh_t = self.phases.get("stream_refresh", 0.0)
        gram_t = max(self.phases.get("gram", 0.0) - refresh_t, 0.0)
        if "gram_flops" in self.counters and gram_t:
            rep["gram_gflops_per_s"] = (
                self.counters["gram_flops"] / gram_t / 1e9
            )
        # Ingest bytes are counted wherever streaming happens — a
        # dedicated "ingest" phase if one exists, else the gram loop
        # (whose wall-clock includes the overlapped host reads).
        stream_t = self.phases.get("ingest") or gram_t
        if "ingest_bytes" in self.counters and stream_t:
            rep["ingest_mb_per_s"] = (
                self.counters["ingest_bytes"] / stream_t / 1e6
            )
        if "eigh_flops" in self.counters and self.phases.get("eigh"):
            rep["eigh_gflops_per_s"] = (
                self.counters["eigh_flops"] / self.phases["eigh"] / 1e9
            )
        return rep

    def dump(self) -> str:
        return json.dumps(self.report(), sort_keys=True)


@contextlib.contextmanager
def trace(logdir: str | None):
    """Capture a ``jax.profiler`` trace into ``logdir`` when set."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

"""Structured telemetry: spans, metrics registry, per-rank Perfetto export.

The reference leaned on the Spark web UI for stage/task metrics
(core/profiling.py's note); the TPU-native rebuild had only an aggregate
:class:`~spark_examples_tpu.core.profiling.PhaseTimer` — phase totals
and three derived throughputs, no per-block timeline, no visibility into
the retry/checkpoint/consensus machinery, no per-rank view in multihost
runs. This module is the process-wide replacement, three layers:

- **Spans** — nestable named intervals (category, monotonic t0/t1,
  key=value attrs) recorded as Chrome trace-event objects, one JSON
  object per line (``trace.jsonl``). The file loads directly in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing`` — both tokenizers
  accept a sequence of event objects without the array wrapper. Each
  rank is its own ``pid`` track; threads (the prefetch producer, the
  main stream) are ``tid`` tracks within it. Every ended span also
  feeds a same-named duration histogram, so the timeline and the
  percentiles never disagree about what was measured.
- **Metrics registry** — counters (monotonic float sums), gauges
  (last/min/max), and streaming histograms: fixed log-spaced buckets
  (``GROWTH`` per bucket), p50/p95/p99 by bucket walk — **no sample
  retention**, so a 40M-variant stream costs the same memory as a toy
  run. The registry subsumes ``PhaseTimer.counters``: the timer mirrors
  every phase duration (``phase.<name>``) and counter into it, and
  :func:`derive_throughputs` is the single shared formula both
  ``PhaseTimer.report()`` and the exporter use — the two can only agree.
- **Exporter** — ``<dir>/rank<k>/{trace.jsonl,metrics.json}`` per
  process plus a merged human-readable ``summary.txt`` on rank 0
  (best-effort merge of whatever peer ``metrics.json`` files are
  visible on the shared filesystem — no collective at exit).

Metrics are **always on** (a dict update per event — noise against a
block's matmul); span *trace events* buffer only when tracing is enabled
via :func:`configure` (``--telemetry-dir`` / ``--trace-events``), capped
at :data:`MAX_EVENTS` with an overflow counter rather than unbounded
growth.

**The live plane** (this PR's tentpole): telemetry no longer
materializes only at process exit. :func:`live_snapshot` is the
read-side API (full metrics snapshot + a rolling ring of recent trace
events + job identity), and :func:`configure` with ``flush_s > 0``
starts a background :class:`PeriodicFlusher` that atomically
(tmp+rename — a mid-write kill leaves the last-good snapshot readable;
the ``telemetry.flush`` fault site proves it) republishes
``metrics.json`` plus ``live_trace.jsonl`` (the recent-event ring)
every K seconds, so an operator, an autoscaler, or the supervisor's
stall detector can observe a running job without killing it. The HTTP
surfaces over this API live in :mod:`core.live`.

**Job identity for restart stitching**: every exported trace event and
heartbeat carries a stable ``run_id`` plus ``attempt``/``rank``
metadata (:func:`run_id` / :func:`attempt`; the supervisor parent pins
both through the environment, so all attempts of one supervised job
share a run_id and each attempt exports into its own
``attempt<k>/rank<r>/`` directory). ``telemetry stitch`` (core/
stitch.py) merges those per-attempt, per-rank exports into one
Perfetto-loadable session trace annotated with restart markers.

Every name used at an instrumentation site must be declared in
:data:`NAMES` (families like ``phase.*`` cover dynamic suffixes);
``tests/test_telemetry_names.py`` lints call sites against the registry
so a typo'd metric name cannot silently fork a timeline, and unknown
names at runtime warn once and count into ``telemetry.unknown_names``
instead of raising mid-job.
"""

from __future__ import annotations

import contextvars
import functools
import heapq
import json
import math
import os
import threading
import time
import uuid
import warnings
import zlib
from contextlib import contextmanager

# ---------------------------------------------------------------------------
# Canonical name registry (THE source of truth — satellite: names lint).
# kind is documentation + export section; membership is what the lint and
# the runtime check enforce. Entries ending in ".*" declare a family.
# Spans double as duration histograms under the same name (seconds).

NAMES: dict[str, tuple[str, str]] = {
    # -- spans ------------------------------------------------------------
    "phase.*": (
        "span",
        "one PhaseTimer phase (gram/eigh/finalize/...) — wall-clock of the "
        "named pipeline stage; also mirrored as a counter of summed seconds",
    ),
    "gram.block": (
        "span",
        "one block period of the streamed gram loop: producer/queue wait + "
        "host->device transfer + update dispatch + hooks + checkpoint",
    ),
    "multihost.consensus": (
        "span",
        "one control-plane allgather round (step-count / has-data / "
        "terminal agreement) — the wait is the per-rank straggler metric: "
        "a fast rank burns its skew here",
    ),
    "checkpoint.save": ("span", "one checkpoint save (write + vote + rotate)"),
    "checkpoint.write": ("span", "one checkpoint data file (hash-tee + np.save)"),
    "checkpoint.verify": ("span", "sha256 re-hash of this rank's files on load"),
    "checkpoint.rotate": ("span", "atomic generation rotation on the primary"),
    "checkpoint.load": ("span", "one checkpoint load (verify + agree + place)"),
    "serve.assemble": (
        "span",
        "one micro-batch assembly in the projection server: dequeue sweep "
        "(fault site, cancellation, deadline expiry) + stack/pad of the "
        "live queries",
    ),
    "serve.device_step": (
        "span",
        "one padded micro-batch through the device: cross-stat "
        "accumulation against the staged reference blocks + per-row "
        "finalize (the compiled hot path one jit entry serves)",
    ),
    "serve.drain": (
        "span",
        "graceful server drain: admission closed, wall-clock until every "
        "in-flight request resolved and the worker joined",
    ),
    "store.compact": (
        "span",
        "one compaction pass of the content-addressed block store: "
        "source stream -> packed sha256-named chunk files + manifest "
        "(duration vs store.compact_bytes = compaction throughput)",
    ),
    "store.chunk_read": (
        "span",
        "one chunk through the store read path: fault site + mmap + "
        "first-touch digest verify + 2-bit decode (or decode-cache hit)",
    ),
    "store.heal": (
        "span",
        "one in-place chunk repair (store/heal.py): verified copy from "
        "a replica dir, else re-compaction of the chunk's origin span — "
        "both digest-checked against the content address before install",
    ),
    "solver.pass": (
        "span",
        "one streamed pass of the sketch solver (solvers/): the range "
        "sketch Y = B@Q folded block-by-block over the whole cohort — "
        "pass 0 against the random probes, later passes the corrected "
        "rung's subspace-iteration power steps (args: index, rung)",
    ),
    "solver.solve": (
        "span",
        "the sketch solver's terminal solve: Nystrom eigenpairs "
        "(single-pass rung) or Rayleigh Ritz pairs (corrected) from the "
        "(N, rank) sketch state — rank-sized math, never an N x N eigh",
    ),
    "fleet.stage": (
        "span",
        "one reference panel staged (or re-staged after an LRU "
        "eviction) into the fleet serving warm pool through the store "
        "read path (serve/pool.py) — the cold-start cost the pool's "
        "budget trades against panel residency",
    ),
    "live.flush": (
        "span",
        "one periodic live-telemetry flush: the telemetry.flush fault "
        "site + atomic metrics.json rewrite + rolling live_trace.jsonl "
        "ring rewrite (tmp+rename both, so a kill mid-flush leaves the "
        "last-good snapshot readable)",
    ),
    "controller.step": (
        "span",
        "one fleet-controller control round (fleet/controller.py): "
        "watch every replica slot (crash/hang/stale-scrape "
        "classification), run the autoscale rules, publish gauges, and "
        "rewrite the atomic controller.json incident ledger",
    ),
    "controller.spawn": (
        "span",
        "one replica spawned by the fleet controller (bootstrap, "
        "respawn after a loss, scale-up, or preemption respawn) "
        "including its warm-set staging — the time-to-ready cost the "
        "scale-up bench measures (args: slot, reason)",
    ),
    # -- instant events ---------------------------------------------------
    "fault": ("event", "a fault-injection spec fired (args: site, kind)"),
    "stream.snapshot": (
        "event",
        "streaming incremental-PCoA snapshot dispatched (args: n_variants)",
    ),
    "gram.pad_step": (
        "event",
        "multihost consensus step where this rank fed an all-MISSING "
        "padding slab (its partition was exhausted) — deliberately NOT a "
        "gram.block sample, so padding cannot skew the per-rank block "
        "percentiles the straggler comparison reads",
    ),
    "checkpoint.fallback": (
        "counter",
        "loads that resumed from the .old generation (latest corrupt/missing); "
        "also emitted as an instant event with the adopted generation",
    ),
    # -- counters ---------------------------------------------------------
    "gram_flops": ("counter", "FLOPs credited to the gram accumulation"),
    "ingest_bytes": ("counter", "bytes actually shipped host->device"),
    "eigh_flops": ("counter", "FLOPs credited to the eigensolve"),
    "ingest.retries": (
        "counter",
        "transient-IO retries absorbed by RetryingSource (a silently "
        "retrying run is distinguishable from a clean one)",
    ),
    "ingest.reopens": ("counter", "inner-source rebuilds (reopen factory) before retries"),
    "ingest.corrupt_blocks": ("counter", "corrupt blocks failed fast (never retried)"),
    "ingest.exhausted": ("counter", "retry budgets exhausted (job-killing incidents)"),
    "ingest.backoff_s": ("counter", "seconds slept in retry backoff"),
    "ingest.parallel_shards": (
        "counter",
        "shards dispatched to the parallel ingest engine's worker pool "
        "(VCF byte ranges / exact-source block stripes; 0 in a run "
        "means every stream took the serial path)",
    ),
    "store.readahead.scheduled": (
        "counter",
        "chunk warms submitted to the store readahead pool (decode + "
        "first-touch verify ahead of the streaming cursor)",
    ),
    "store.codec.raw_bytes": (
        "counter",
        "packed payload bytes produced by compaction BEFORE chunk "
        "compression (store/codec.py); raw_bytes / stored_bytes is the "
        "store's compression ratio",
    ),
    "store.codec.stored_bytes": (
        "counter",
        "chunk bytes after compression — what compaction actually "
        "hashes, names, and a cold read actually pulls off disk/link",
    ),
    "store.codec.fallback": (
        "counter",
        "the native decode-to-slab entry (store_decode_chunk) was "
        "unavailable and the pure-Python chunk decode was selected "
        "(once per process — a selection flag, not a rate): a stale "
        "native build degrading loudly instead of silently running "
        "the slow path",
    ),
    "store.readahead.hits": (
        "counter",
        "consumer chunk reads served by a completed (or awaited) "
        "background warm instead of an inline cold decode",
    ),
    "store.readahead.errors": (
        "counter",
        "warms that failed in a pool worker — each error is re-raised "
        "in the consumer when its cursor reaches the chunk, through "
        "the ordinary retry/fail-fast boundary, never swallowed",
    ),
    "checkpoint.bytes_written": ("counter", "checkpoint data bytes written by this rank"),
    "faults.fired": ("counter", "fault-injection specs fired (all sites)"),
    "hard_sync.fallback": (
        "counter",
        "hard_sync checksum-barrier failures that fell back to per-shard "
        "element fetches (inflates every timed phase; warns once per reset)",
    ),
    "telemetry.dropped_events": ("counter", "trace events dropped past MAX_EVENTS"),
    "telemetry.unknown_names": ("counter", "instrumentation calls with undeclared names"),
    "serve.requests": (
        "counter",
        "requests admitted into the projection server's bounded queue "
        "(cache hits answered at submit are counted separately)",
    ),
    "serve.shed": (
        "counter",
        "requests rejected with ServerOverloaded at admission — the "
        "bounded queue was full (explicit load-shedding, not latency)",
    ),
    "serve.cache_hits": (
        "counter",
        "requests answered from the LRU result cache by genotype digest "
        "(no queue, no device work)",
    ),
    "serve.cache_misses": ("counter", "requests that missed the result cache"),
    "serve.deadline_expired": (
        "counter",
        "admitted requests dropped at batch assembly because their "
        "deadline had already passed (answered with DeadlineExceeded)",
    ),
    "serve.cancelled": (
        "counter",
        "admitted requests cancelled by the client before batch pickup",
    ),
    "serve.errors": (
        "counter",
        "admitted requests answered with a processing error (including "
        "injected serve.request faults)",
    ),
    "store.compact_bytes": (
        "counter",
        "packed chunk bytes written by the compaction writer (a chunk "
        "deduplicated by content address is counted once, when written)",
    ),
    "store.compact_chunks": (
        "counter",
        "chunks the compaction writer emitted (including deduplicated "
        "ones — the manifest records every chunk, shared files or not)",
    ),
    "store.cache_hits": (
        "counter",
        "store reads answered from the bounded host-RAM decode cache "
        "(no mmap touch, no 2-bit decode)",
    ),
    "store.cache_misses": (
        "counter",
        "store reads that mapped + decoded a chunk from disk (the cold "
        "tier); hits / (hits + misses) is the cache hit rate",
    ),
    "store.chunks_verified": (
        "counter",
        "chunk digests re-hashed against the manifest on first touch "
        "(each chunk verifies once per reader, not once per read)",
    ),
    "store.verify_failures": (
        "counter",
        "chunks whose bytes no longer match their content address — "
        "every one is quarantined and the read fails fast with the "
        "resume cursor named",
    ),
    "store.quarantined": (
        "counter",
        "corrupt chunks recorded in the store's quarantine.json (the "
        "operator-facing recovery list; never silently skipped) — only "
        "after every heal route failed",
    ),
    "store.healed": (
        "counter",
        "corrupt chunks repaired in place (replica copy or origin "
        "re-compaction, digest-verified) instead of failing the run — "
        "healed incidents also count store.verify_failures, so "
        "healed/verify_failures is the self-healing rate",
    ),
    "solver.passes": (
        "counter",
        "streamed sketch-solver passes completed (1 for the sketch "
        "rung, 1 + --sketch-iters for corrected; each is one full "
        "variant pass over the cohort)",
    ),
    "supervisor.restarts": (
        "counter",
        "supervised-child restarts (crash, injected kill, or watchdog "
        "hang/stall kill) — each resumes from the latest verified "
        "checkpoint; a clean supervised run counts 0",
    ),
    "supervisor.stalls": (
        "counter",
        "watchdog interventions: heartbeats stopped arriving or "
        "arrived with frozen progress past the stall budget, and the "
        "child was killed for restart",
    ),
    "supervisor.heartbeats": (
        "counter",
        "heartbeat files written by this supervised child (the "
        "liveness/progress signal core/supervisor.py's watchdog reads)",
    ),
    "serve.worker_restarts": (
        "counter",
        "projection-server batching-worker recoveries: an unexpected "
        "worker-loop failure or thread death was caught and the worker "
        "restarted WITHOUT dropping admitted requests (health degrades "
        "for the cooloff window)",
    ),
    "serve.breaker_open": (
        "counter",
        "store-read circuit-breaker trips in the serve panel path: "
        "repeated staging failures opened the breaker and the server "
        "entered cached-panel-only mode (still serving, degraded)",
    ),
    "fleet.restage_total": (
        "counter",
        "panel stages of a route that had been staged before and was "
        "LRU-evicted from the warm pool — each is a cold start paid to "
        "the HBM budget (a climbing rate under steady traffic means "
        "the budget is too small for the working set)",
    ),
    "fleet.evictions": (
        "counter",
        "panels LRU-evicted from the fleet warm pool to fit a newly "
        "staged route under the configured budget (the panel re-stages "
        "on demand through the store — nothing is lost, only warmth)",
    ),
    "fleet.shard_stages": (
        "counter",
        "shards staged while serving a panel that exceeds the pool "
        "budget (serve/router.py _sharded_blocks): each is one "
        "budget-sized slice of the panel streamed from the store, "
        "charged transiently against the pool, and dropped after its "
        "blocks are consumed — the request count times the shard "
        "count, since over-budget panels cannot be kept warm",
    ),
    "fleet.cache_namespace_evictions": (
        "counter",
        "result-cache entries reclaimed because their route was "
        "unloaded (the cache is namespaced by model fingerprint; an "
        "unloaded route's namespace is evicted whole, so cache bytes "
        "stay flat across load/unload cycles)",
    ),
    "fleet.hedge_launched": (
        "counter",
        "hedge requests the loadgen client sent to a second replica "
        "after the p95-derived hedge delay passed without a primary "
        "answer (serve/loadgen.py run_hedged_loadgen)",
    ),
    "fleet.hedge_wins": (
        "counter",
        "hedged requests whose SECOND replica answered first (the "
        "primary was the straggler; the loser future is cancelled) — "
        "hedge_wins / hedge_launched is the tail-latency relief rate",
    ),
    "fleet.failovers": (
        "counter",
        "hedged-client re-admissions after a replica loss: a request "
        "refused or failed with ServerClosed (kill, preemption, drain) "
        "is re-sent to the hedge partner instead of erroring — the "
        "zero-lost-admitted-requests contract exercised (latency paid, "
        "answer kept)",
    ),
    "serve.drain_abandoned": (
        "counter",
        "admitted requests still queued when the SIGTERM drain budget "
        "(--drain-timeout-s) expired — failed loudly with ServerClosed, "
        "never dropped; read from the final telemetry flush by a "
        "supervising parent to judge whether a drain was clean",
    ),
    "controller.scrapes": (
        "counter",
        "successful replica /metrics (or in-process stats) scrapes by "
        "the fleet controller — the denominator against "
        "controller.scrape_stale for scrape-path health",
    ),
    "controller.scrape_stale": (
        "counter",
        "controller scrape attempts that failed (blackholed endpoint, "
        "parse error, injected controller.scrape fault): the slot "
        "keeps acting on its last-good snapshot marked stale until "
        "stale_scrapes consecutive failures declare the replica lost",
    ),
    "controller.respawns": (
        "counter",
        "replicas respawned by the controller after a loss (crash/"
        "hang/stale) or preemption — each lands after the slot's "
        "bounded exponential backoff, and too many inside the flap "
        "window park the slot instead",
    ),
    "controller.scale_ups": (
        "counter",
        "replicas added by the autoscale rule: sustained interactive "
        "queue depth per ready replica (or worst-route p99) over "
        "pressure_rounds consecutive control rounds",
    ),
    "controller.retires": (
        "counter",
        "replicas retired by the autoscale rule after idle_rounds "
        "consecutive all-idle rounds — SIGTERM drain within "
        "--drain-timeout-s, hedging covers the window",
    ),
    "controller.preemptions": (
        "counter",
        "graceful preemptions handled (preempt(): drain within budget "
        "+ immediate respawn, no backoff — the platform's fault, not "
        "the replica's)",
    ),
    "controller.incidents": (
        "counter",
        "incidents appended to the controller's atomic controller.json "
        "ledger (crash/hang/stale losses, spawn failures, flap-breaker "
        "trips, dirty drains, placement overflow)",
    ),
    "controller.ledger_rotations": (
        "counter",
        "full-ledger generations rotated to controller.json.old "
        "(atomic tmp+rename) before the bounded incident/decision "
        "deques started dropping their oldest entries — history is "
        "archived, never silently discarded",
    ),
    "serve.priority.preemptions": (
        "counter",
        "dequeues where an interactive request jumped ahead of an "
        "older batch-class request waiting in admission — the priority "
        "contract (interactive before batch) actually exercised",
    ),
    "serve.priority.shed_interactive": (
        "counter",
        "interactive-class requests shed at admission (the "
        "--queue-interactive threshold; nonzero means even the "
        "protected class is past capacity — scale out)",
    ),
    "serve.priority.shed_batch": (
        "counter",
        "batch-class requests shed at admission (the --queue-batch "
        "threshold) — expected first under overload, while the "
        "interactive class keeps admitting",
    ),
    "live.flushes": (
        "counter",
        "periodic live-telemetry snapshots published by the background "
        "flusher (atomic metrics.json + rolling live_trace.jsonl every "
        "flush_s seconds — the mid-run observability the exit-time "
        "export cannot provide)",
    ),
    "live.flush_errors": (
        "counter",
        "periodic flushes that failed (unwritable dir, full disk, "
        "injected telemetry.flush fault) — warned once and absorbed; "
        "the flusher, like the heartbeat, must never be able to kill "
        "the job it reports on",
    ),
    "live.requests": (
        "counter",
        "live-introspection HTTP requests answered by this process "
        "(/metrics, /debug/telemetry, /healthz on the --live-port "
        "sidecar or the serve front)",
    ),
    "live.proxy_requests": (
        "counter",
        "scrapes answered by a supervisor parent's live proxy on "
        "behalf of its supervised child (the endpoint that stays up "
        "across child restarts)",
    ),
    "live.proxy_stale": (
        "counter",
        "proxy answers served from the last-good cached child "
        "snapshot because the child was down (mid-restart) or "
        "unreachable — the scrape succeeds, marked stale, instead of "
        "erroring during the exact window an operator most wants data",
    ),
    "trend.metrics_checked": (
        "counter",
        "headline metrics the noise-aware trend checker (tools/"
        "trend.py) evaluated against the BENCH_HISTORY.jsonl "
        "median/MAD band in this process",
    ),
    "trend.regressions": (
        "counter",
        "headline metrics the trend checker flagged as regressed "
        "(worse than the direction-aware noise band) — bench --trend "
        "exits nonzero when this is nonzero",
    ),
    # -- gauges -----------------------------------------------------------
    "prefetch.queue_depth": (
        "gauge",
        "prefetch queue occupancy sampled at each consumer get (max == "
        "configured depth means the producer is ahead; 0 means the chip "
        "is starved)",
    ),
    "serve.in_flight": (
        "gauge",
        "admitted-but-unanswered requests in the projection server "
        "(queued + in the current batch); max is the realized backlog",
    ),
    "serve.health": (
        "gauge",
        "the serving health state machine as a number (0 healthy, "
        "1 degraded, 2 draining) — published on every transition so "
        "the exported timeline shows when and how long the server was "
        "degraded; /healthz reports the same state as a string",
    ),
    "fleet.routes": (
        "gauge",
        "routes currently loaded in the fleet server (each = one "
        "(model, panel) pair addressable by name)",
    ),
    "fleet.pool_bytes": (
        "gauge",
        "staged panel bytes resident in the fleet warm pool (dense "
        "device-resident blocks); bounded by the configured "
        "--fleet-budget-mb via LRU eviction",
    ),
    "fleet.pool_pressure": (
        "gauge",
        "resident / budget of the fleet warm pool (1.0 = at budget; "
        "sustained ~1.0 with climbing fleet.restage_total means the "
        "working set does not fit and cold starts are being paid)",
    ),
    "fleet.panel_over_budget_x": (
        "gauge",
        "panel bytes / pool budget of the last shard-staged route "
        "served (>1.0 by construction): how many budgets' worth of "
        "panel each request streams through — ceil of it is the shard "
        "count per request; raise --fleet-budget-mb above it to serve "
        "the route warm instead",
    ),
    "fleet.route.*": (
        "gauge",
        "per-route autoscale signals, one gauge per "
        "fleet.route.<name>.<signal>: queue_depth (admitted waiting), "
        "p99_s (served latency), shed_rate (shed / offered), staged "
        "(1 = panel warm in the pool) — the series an autoscaler "
        "scales replica counts on (GET /metrics)",
    ),
    "serve.priority.depth_interactive": (
        "gauge",
        "interactive-class admission queue depth (published at every "
        "put/take; pinned at the --queue-interactive bound means the "
        "protected class itself is saturated)",
    ),
    "serve.priority.depth_batch": (
        "gauge",
        "batch-class admission queue depth — deep-and-draining is the "
        "designed steady state under mixed load (backfill absorbs the "
        "slack the interactive class leaves)",
    ),
    "controller.replicas": (
        "gauge",
        "replica slots currently up under the fleet controller "
        "(spawned and not lost/retired/parked) — the autoscale loop's "
        "actuated value, between min_replicas and max_replicas",
    ),
    "controller.ready": (
        "gauge",
        "up replicas whose latest fresh scrape reported ready (worker "
        "alive, not draining, warm set staged — the /readyz rule); "
        "ready < replicas marks a warmup or degradation window",
    ),
    "controller.flap_breaker_open": (
        "gauge",
        "replica slots parked by the flap breaker (more than "
        "flap_max_respawns respawns inside flap_window_s): a crash-"
        "looping slot stops burning spawns until an operator "
        "reset_flap_breaker() — nonzero demands attention",
    ),
    "store.cache_bytes": (
        "gauge",
        "decoded dense bytes resident in the store's host-RAM decode "
        "cache (bounded by --store-cache-mb; max == the bound means "
        "the working set does not fit and evictions are live)",
    ),
    "store.readahead.depth": (
        "gauge",
        "the readahead pool's live scheduling depth: cadence-adaptive "
        "between --readahead-chunks (floor) and --readahead-chunks-max "
        "(ceiling) — deepened one per retire while the consumer blocks "
        "on unfinished warms, settled toward the EWMA of per-chunk "
        "consumer cadence vs decode latency otherwise; pinned at the "
        "ceiling means the feed is decode/disk-bound; at the floor, "
        "compute-bound",
    ),
    "store.readahead.in_flight": (
        "gauge",
        "chunk warms pending in the readahead pool; pinned at 0 means "
        "the consumer outruns the warms (raise --readahead-chunks), "
        "pinned at depth means readahead is fully ahead (healthy)",
    ),
    "prefetch.transfers_in_flight": (
        "gauge",
        "host->device transfers dispatched ahead of the yielded block "
        "in the K-deep feed (bounded by the transfer ring depth)",
    ),
    "solver.rung": (
        "gauge",
        "the accuracy-ladder rung this job's eigensolve ran "
        "(0 sketch, 1 corrected, 2 exact) — the provenance the model "
        "artifact records as a string",
    ),
    "solver.rank": (
        "gauge",
        "sketch probe columns actually used (--sketch-rank clamped to "
        "N) — the r of the (N, r) solver state",
    ),
    "solver.state_bytes": (
        "gauge",
        "peak sketch-solver state residency (the y + q f32 leaves) — "
        "THE solver memory number; compare solver.nxn_bytes_avoided "
        "for what the dense route would have held",
    ),
    "solver.nxn_bytes_avoided": (
        "gauge",
        "bytes of N x N accumulator the dense route would have "
        "allocated for this cohort/metric — the allocation the sketch "
        "path exists to never make",
    ),
    "solver.dual": (
        "gauge",
        "1 when this sketch job streamed a ratio metric's dual "
        "(numerator + pair-count denominator) sketches, 0 for the "
        "single-factor construction — which operator family the "
        "ladder's relerr claims apply to",
    ),
    "solver.dual_den_defect": (
        "gauge",
        "measured rank-1 residual of the ratio denominator "
        "(||DEN Q - a(a^T Q)||_F / ||DEN Q||_F from the pass-0 dual "
        "sketches) — 0 means the scaled operator is exact; larger "
        "means the dual rungs embed a denominator approximation the "
        "exact rung does not",
    ),
    # -- histograms -------------------------------------------------------
    "prefetch.put_wait_s": (
        "histogram",
        "producer-thread wait per block for queue space (large => consumer/"
        "device is the bottleneck)",
    ),
    "prefetch.get_wait_s": (
        "histogram",
        "consumer wait per block for the producer (large => ingest is the "
        "bottleneck; sum/gram time = the stall fraction)",
    ),
    "prefetch.stage_wait_s": (
        "histogram",
        "producer wait per block for a free host staging slab (large => "
        "the transfer/compute side of the ring is the bottleneck and "
        "every slab is in flight)",
    ),
    "prefetch.transfer_wait_s": (
        "histogram",
        "residual wait at block retire time for its host->device "
        "transfer to complete before the staging slab rotates back — "
        "~0 when the K-deep pipeline hides the transfer entirely",
    ),
    "ingest.reassembly_wait_s": (
        "histogram",
        "per in-order result: consumer wait at the parallel ingest "
        "engine's ordered reassembly buffer (large => one straggler "
        "shard gates the stream; ~0 => workers run ahead of the "
        "consumer)",
    ),
    "store.readahead.wait_s": (
        "histogram",
        "consumer wait for an in-flight background warm of the chunk "
        "its cursor just reached (the readahead analogue of "
        "prefetch.get_wait_s; large => raise --readahead-chunks)",
    ),
    "serve.enqueue_wait_s": (
        "histogram",
        "per admitted request: wall-clock from admission to batch pickup "
        "(large => the device step or linger window is the bottleneck)",
    ),
    "serve.latency_s": (
        "histogram",
        "per served request: submit to completed result, cache hits "
        "included — the client-visible latency whose p50/p99 the loadgen "
        "reports",
    ),
    "serve.batch_rows": (
        "histogram",
        "live (non-padding) queries per executed micro-batch: mean near "
        "max_batch means coalescing is working; 1 means linger is too "
        "short for the offered load",
    ),
    # -- multi-chip execution (tile2d transports + shard-aware feed) ------
    "gram.lowering": (
        "gauge",
        "count-family contraction lowering the gram job resolved to: 1 = "
        "the fused packed Pallas kernel (decode + mask + contract in one "
        "VMEM pass), 0 = the reference unpack-then-matmul XLA path — the "
        "auto choice made observable (--gram-lowering)",
    ),
    "gram.fused_blocks": (
        "counter",
        "block updates dispatched through the fused packed Pallas "
        "lowering — nonzero proves the fused kernel, not the reference "
        "XLA path, is the one contracting (pairs with gram.lowering)",
    ),
    "gram.ring_steps": (
        "counter",
        "tile2d ring-transport shard rotations dispatched (n_devices per "
        "block update) — nonzero proves the overlapped schedule, not the "
        "bulk gather, is the one running",
    ),
    "gram.gather_wait_s": (
        "histogram",
        "measured wall-clock of the tile2d gather transport's bulk block "
        "all_gather alone, at the job's block cadence (bench --multichip "
        "times gram_sharded.make_gather_probe) — the serial collective "
        "cost the ring transport hides behind the MXU",
    ),
    "gram.overlap_frac": (
        "gauge",
        "1 - gather_wait / block compute for the measured multi-chip gram "
        "(bench --multichip): the fraction of the block period the ring "
        "schedule keeps the chips computing instead of waiting on the "
        "block collective",
    ),
    "multihost.shard_feed_bytes": (
        "counter",
        "bytes THIS process fed into the mesh as its own variant-shard "
        "slabs (padding steps feed none) — summed across hosts, the "
        "aggregate-ingest number that scales with host count under the "
        "shard-aware feed",
    ),
    # -- request tracing / fleet timeline / SLO (the flight recorder) -----
    "trace.request": (
        "span",
        "one sampled request's admission-to-response wall at the HTTP "
        "front (args: trace_id, route, class, status, cache_hit) — the "
        "root of the per-request waterfall the stitcher renders",
    ),
    "trace.queue": (
        "span",
        "one sampled request's admission-to-batch-pickup wait inside "
        "the router (args: trace_id, route, class) — the per-request "
        "leg of serve.enqueue_wait_s, placed on the waterfall",
    ),
    "trace.compute": (
        "span",
        "the device-step wall attributed to one sampled request of the "
        "executed micro-batch (args: trace_id, rows, cold_start, "
        "stage_s — stage_s > 0 is the cold-start cost this request "
        "paid waiting on a panel re-stage)",
    ),
    "trace.hedge": (
        "event",
        "hedge resolution for a traced request: both legs share one "
        "trace_id with distinct span ids; args record the winning leg "
        "(primary/hedge) and whether the loser was cancelled",
    ),
    "trace.sampled": (
        "counter",
        "requests granted detailed per-request tracing by the "
        "--trace-sample rate (deterministic on trace_id, so every "
        "process and both hedge legs agree on the same decision)",
    ),
    "trace.export_errors": (
        "counter",
        "slowest-request exemplar (requests.json) writes that failed "
        "(unwritable dir, injected trace.export fault) — absorbed; the "
        "last-good exemplar file stays readable (tmp+rename)",
    ),
    "trace.exemplars": (
        "gauge",
        "occupancy of the slowest-K request exemplar ring keyed by "
        "trace_id (GET /debug/requests serves it; bounded at "
        "TRACE_EXEMPLARS)",
    ),
    "timeline.rounds": (
        "counter",
        "control rounds persisted into the fleet timeline ring "
        "(fleet/timeline.py timeline.jsonl — one line per scrape round "
        "with every slot's ReplicaSnapshot folded in)",
    ),
    "timeline.markers": (
        "counter",
        "replica lifecycle incidents (crash/respawn/preempt/park/"
        "scale) aligned onto the fleet timeline as markers",
    ),
    "timeline.compactions": (
        "counter",
        "timeline ring compactions: the append-only timeline.jsonl hit "
        "its size bound and was atomically rewritten (tmp+rename) with "
        "only the newest rounds kept",
    ),
    "timeline.write_errors": (
        "counter",
        "timeline appends/compactions that failed (full disk, injected "
        "trace.export fault) — absorbed, the controller keeps stepping "
        "and the last-good timeline stays readable",
    ),
    "timeline.bytes": (
        "gauge",
        "current byte size of the fleet timeline ring file (bounded by "
        "max_bytes via compaction)",
    ),
    "timeline.fleet_p99_s": (
        "gauge",
        "fleet-wide served p99 folded across every fresh replica "
        "snapshot this round (Histogram.merge over per-slot series; "
        "served as fleet_timeline_fleet_p99_s on GET /fleet/metrics)",
    ),
    "timeline.fleet_queue_depth": (
        "gauge",
        "fleet-wide interactive+batch admission queue depth summed "
        "across every fresh replica snapshot this round",
    ),
    "timeline.fleet_shed_rate": (
        "gauge",
        "worst per-replica shed rate across the fleet this round (the "
        "load-shedding hot spot, not the average)",
    ),
    "timeline.route.*": (
        "gauge",
        "cross-replica folded per-route series, one gauge per "
        "timeline.route.<name>.<signal>: p99_s (max across replicas), "
        "queue_depth (sum), staged (replicas holding the panel warm) — "
        "the fleet-wide view GET /fleet/metrics serves",
    ),
    "slo.breaches": (
        "counter",
        "SLO burn-rate breaches recorded by the controller's evaluator "
        "(fast AND slow windows both burning): each lands as a ledger "
        "incident and registers scale-up pressure in the same round",
    ),
    "slo.ok": (
        "gauge",
        "1 while no declared SLO is breaching, 0 while any objective's "
        "fast+slow burn windows are both over budget",
    ),
    "slo.*": (
        "gauge",
        "per-objective burn-rate gauges, one per "
        "slo.<route>.<class>.<window>: fast_burn / slow_burn (observed "
        "violation fraction over the window divided by the objective's "
        "error budget; >= 1.0 means the budget is burning at alert "
        "rate) and breach (1 while both windows burn)",
    ),
    "neighbors.candidate_pairs": (
        "counter",
        "candidate pairs emitted by LSH banding (after per-band "
        "bucket caps and i<j dedup) — the pairs that pay exact kernel "
        "evaluation instead of the full N(N-1)/2",
    ),
    "neighbors.filter_frac": (
        "gauge",
        "fraction of all N(N-1)/2 pairs the LSH filter AVOIDED "
        "evaluating exactly (1 - candidates/all); higher is better — "
        "the whole point of the MinHash screen",
    ),
    "neighbors.bucket_overflows": (
        "counter",
        "samples dropped from over-cap LSH band buckets "
        "(--minhash-bucket-cap): a crowded bucket (monomorphic band, "
        "degenerate signature) is truncated deterministically, never "
        "allowed to regenerate the quadratic pair set",
    ),
    "neighbors.evaluated_pairs": (
        "counter",
        "candidate pairs whose exact per-pair kernel statistics were "
        "accumulated through the streamed candidate-evaluation pass "
        "(equals neighbors.candidate_pairs on a clean run)",
    ),
    "neighbors.requests": (
        "counter",
        "top-k neighbor requests answered by the serving layer (the "
        "/neighbors endpoint and the in-process fleet.topk path)",
    ),
}

_FAMILIES = tuple(n[:-1] for n in NAMES if n.endswith(".*"))  # "phase."

KINDS = ("span", "event", "counter", "gauge", "histogram")

MAX_EVENTS = 500_000

# Histogram geometry: log buckets growing by GROWTH per step from LO.
# 2**(1/8) per bucket => a quantile read off the geometric bucket
# midpoint is within ~4.5% of the true sample quantile — tight enough
# for p50/p95/p99 attribution with zero sample retention.
HIST_LO = 1e-9
HIST_GROWTH = 2.0 ** 0.125
_HIST_BUCKETS = 1 + 8 * 47 + 1  # underflow + 47 octaves (1e-9..~1.4e5 s) + overflow
_LOG_G = math.log(HIST_GROWTH)


def is_declared(name: str) -> bool:
    """True when ``name`` is in the registry (exact or family match)."""
    return name in NAMES or name.startswith(_FAMILIES)


class Histogram:
    """Fixed log-bucket streaming histogram — no sample retention.

    Exact count/sum/min/max ride along, and quantiles clamp into
    [min, max], so a single-sample (or constant) histogram reports its
    quantiles exactly.
    """

    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self):
        self.buckets = [0] * _HIST_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, value: float) -> None:
        v = float(value)
        if v <= HIST_LO:
            i = 0
        else:
            i = min(1 + int(math.log(v / HIST_LO) / _LOG_G), _HIST_BUCKETS - 1)
        self.buckets[i] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into this histogram (identical bucket grid by
        construction). The aggregation primitive client-side latency
        tracking uses (serve/loadgen.py) — one implementation, so a
        bucket-layout change can never skew a caller's own fold."""
        if other.count:
            for i, n in enumerate(other.buckets):
                self.buckets[i] += n
            self.count += other.count
            self.sum += other.sum
            if other.min < self.min:
                self.min = other.min
            if other.max > self.max:
                self.max = other.max
        return self

    @staticmethod
    def _bounds(i: int) -> tuple[float, float]:
        if i == 0:
            return 0.0, HIST_LO
        return HIST_LO * HIST_GROWTH ** (i - 1), HIST_LO * HIST_GROWTH ** i

    def quantile(self, q: float) -> float:
        """The q-quantile (q in [0, 1]) read off the bucket grid."""
        if self.count == 0:
            return 0.0
        target = max(q * self.count, 1e-12)
        seen = 0
        for i, n in enumerate(self.buckets):
            if not n:
                continue
            seen += n
            if seen >= target:
                lo, hi = self._bounds(i)
                mid = math.sqrt(lo * hi) if lo > 0 else hi / 2.0
                return min(max(mid, self.min), self.max)
        return self.max

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.sum / self.count,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


# ---------------------------------------------------------------------------
# Process-wide state. One lock guards everything: per-event cost is a
# dict update — noise against the block compute the events describe —
# and sites fire from both the main thread and the prefetch producer.
# REENTRANT on purpose: the SIGTERM crash-flush handler runs export()
# on the main thread at an arbitrary bytecode boundary — including
# inside a `with _lock:` of a hot-path count()/observe() — and a plain
# Lock would deadlock the dying process there. Re-entry can observe a
# half-recorded histogram (count bumped, sum not yet); for a final
# best-effort flush that is noise, for a hang it would be fatal.

_lock = threading.RLock()
_T0 = time.perf_counter()  # trace timestamp epoch (per process)
_START_UNIX = time.time()  # wall-clock process start (summary staleness)

_counters: dict[str, float] = {}
_gauges: dict[str, dict] = {}
_hists: dict[str, Histogram] = {}
_events: list[dict] = []

_dir: str | None = None
_trace = False
_warned_names: set[str] = set()
_flusher: "PeriodicFlusher | None" = None

# Job identity for restart/rank stitching (core/stitch.py): a stable
# run_id shared by every attempt of one logical job, and the attempt
# ordinal. The supervisor parent pins both through the environment so
# a restarted child keeps the run_id and bumps the attempt; an
# unsupervised run mints its own run_id (attempt 0).
ENV_RUN_ID = "SPARK_EXAMPLES_TPU_RUN_ID"
ENV_ATTEMPT = "SPARK_EXAMPLES_TPU_ATTEMPT"
_run_id: str | None = None


def run_id() -> str:
    """The stable job id stamped into every exported trace event,
    metrics meta, and heartbeat: the env value when supervised (the
    parent mints one per supervised lifetime), else a fresh token.
    Minted under the module lock — the flusher thread and a sidecar
    scrape can race the first call, and two minted tokens would make
    one job stitch as two."""
    global _run_id
    with _lock:
        if _run_id is None:
            _run_id = (os.environ.get(ENV_RUN_ID, "").strip()
                       or uuid.uuid4().hex[:12])
        return _run_id


def attempt() -> int:
    """This process's attempt ordinal (0 unsupervised / first child)."""
    try:
        return int(os.environ.get(ENV_ATTEMPT, "0") or 0)
    except ValueError:
        return 0


def identity() -> dict:
    """{run_id, attempt, rank} — the stitch keys, in one place."""
    rank, _ = _rank()
    return {"run_id": run_id(), "attempt": attempt(), "rank": rank}


def _check_name(name: str) -> None:
    if is_declared(name):
        return
    with _lock:
        _counters["telemetry.unknown_names"] = (
            _counters.get("telemetry.unknown_names", 0.0) + 1.0
        )
        if name in _warned_names:
            return
        _warned_names.add(name)
    warnings.warn(
        f"telemetry name {name!r} is not declared in telemetry.NAMES — "
        "declare it (the canonical registry is what keeps timelines from "
        "silently forking on typos)",
        RuntimeWarning,
        stacklevel=3,
    )


def configure(dir: str | None = None, trace_events: bool = True,
              flush_s: float = 0.0,
              trace_sample: float | None = None) -> None:
    """Enable export (and optionally span trace events) process-wide.

    Metrics are always collected; this sets where :func:`export` writes
    and whether spans buffer Chrome trace events (``trace_events=False``
    keeps ``metrics.json`` but writes an events-free ``trace.jsonl``).

    ``flush_s > 0`` additionally starts the :class:`PeriodicFlusher`:
    every ``flush_s`` seconds the current metrics snapshot and a
    rolling ring of recent trace events are atomically republished
    under the export directory, so the job is observable *while it
    runs* (``live_snapshot()`` is the in-process read side; the
    ``--live-port`` sidecar and the serve front's ``/metrics`` read
    the registry directly and work with or without the flusher).

    Configuring a directory also installs the crash flush (once per
    process): an ``atexit`` hook and a SIGTERM handler that export
    whatever has been collected, so a run that dies mid-flight — an
    unhandled exception, an orchestrator's TERM — still leaves its
    trace and metrics behind. (SIGKILL / ``os._exit`` cannot be caught;
    the periodic flusher's last-good snapshot and the supervised-run
    checkpoints cover those.)
    """
    global _dir, _trace
    with _lock:
        _dir = dir
        _trace = bool(trace_events) and dir is not None
    if trace_sample is not None:
        set_trace_sample(trace_sample)
        # Children (ProcessReplica, supervised ranks) inherit the rate
        # through the environment, so one --trace-sample governs the
        # whole process tree and sampling decisions stay consistent.
        os.environ[ENV_TRACE_SAMPLE] = repr(_trace_sample)
    if dir is not None:
        _install_crash_flush()
    if flush_s and flush_s > 0 and dir is not None:
        start_periodic_flush(flush_s, dir=dir)
    else:
        stop_periodic_flush()


_atexit_installed = False
_sigterm_installed = False


def _crash_flush() -> None:
    """Best-effort export for abnormal exits: never raises, never
    prints — a telemetry flush must not be able to mask the real
    failure or fail an exiting process twice."""
    try:
        export()
    except BaseException:
        pass


def _install_crash_flush() -> None:
    # Two independent latches: a configure() first called from a
    # worker thread can only install the atexit half (signal handlers
    # are main-thread-only) — the SIGTERM half must stay retryable so
    # a LATER main-thread configure() still installs it, instead of a
    # single latch silently disabling the flush this satellite exists
    # to provide.
    global _atexit_installed, _sigterm_installed
    if not _atexit_installed:
        _atexit_installed = True
        import atexit

        atexit.register(_crash_flush)
    if _sigterm_installed:
        return
    try:
        import signal

        if threading.current_thread() is not threading.main_thread():
            return  # retry from the next main-thread configure()
        _sigterm_installed = True
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            _crash_flush()
            # The handler only ever installs over the DEFAULT
            # disposition (gate below), so after flushing, restore it
            # and re-deliver: the exit status still says "terminated
            # by SIGTERM".
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        # Only take the slot while the disposition is the default —
        # a handler installed first (or later: serve's drain handler
        # replaces this one, and its KeyboardInterrupt path unwinds
        # through the CLI's export callback anyway) keeps its semantics.
        if prev in (signal.SIG_DFL, None):
            signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # embedded interpreter / exotic platform: atexit still set


def reset() -> None:
    """Zero every counter/gauge/histogram and drop buffered trace events
    (configuration survives). Also re-arms every warn-once keyed on a
    counter (e.g. the hard_sync fallback warning)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _events.clear()
        _warned_names.clear()
        _exemplars.clear()


# ---------------------------------------------------------------------------
# Recording API.


def count(name: str, n: float = 1.0) -> float:
    """Add ``n`` to counter ``name``; returns the new total (so call
    sites can key warn-once behavior on the first increment)."""
    _check_name(name)
    with _lock:
        total = _counters.get(name, 0.0) + n
        _counters[name] = total
    return total


def counter_value(name: str) -> float:
    with _lock:
        return _counters.get(name, 0.0)


def histogram_sum(name: str) -> float:
    """Sum of every value observed into histogram ``name`` (0.0 when
    never observed) — the read bench.py's stall-fraction deltas use."""
    with _lock:
        h = _hists.get(name)
        return h.sum if h is not None else 0.0


def gauge_set(name: str, value: float) -> None:
    _check_name(name)
    v = float(value)
    with _lock:
        g = _gauges.get(name)
        if g is None:
            _gauges[name] = {"last": v, "min": v, "max": v, "n": 1}
        else:
            g["last"] = v
            g["n"] += 1
            if v < g["min"]:
                g["min"] = v
            if v > g["max"]:
                g["max"] = v


def observe(name: str, value: float) -> None:
    """Record ``value`` into histogram ``name``."""
    _check_name(name)
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram()
        h.record(value)


def _append_event(ev: dict) -> None:
    with _lock:
        if len(_events) >= MAX_EVENTS:
            _counters["telemetry.dropped_events"] = (
                _counters.get("telemetry.dropped_events", 0.0) + 1.0
            )
            return
        _events.append(ev)


def event(name: str, cat: str = "misc", **attrs) -> None:
    """Instant event on the trace timeline (thread-scoped 'i' phase).
    An ambient request trace context (:func:`trace_scope`) stamps its
    ids into the args unless the caller passed its own."""
    _check_name(name)
    if not _trace:
        return
    ctx = _TRACE_CTX.get()
    if ctx is not None:
        attrs = {**ctx, **attrs}
    _append_event({
        "name": name,
        "cat": cat,
        "ph": "i",
        "s": "t",
        "ts": (time.perf_counter() - _T0) * 1e6,
        "tid": threading.get_ident(),
        "args": attrs,
    })


# ---------------------------------------------------------------------------
# Request-scoped trace context (the flight-recorder tentpole).
#
# A trace_id is minted at HTTP admission (or accepted from X-Trace-Id)
# and identifies one logical request across threads, hedge legs, and
# process boundaries; span_ids are per-leg. The context rides a
# contextvar so spans/events opened on the admitting thread pick the
# ids up automatically, and explicit ``trace_id=`` attrs carry them
# where work hops threads (the router's batch worker). Sampling is
# DETERMINISTIC on the trace_id (crc32 threshold), so both hedge legs
# and every replica subprocess make the same keep/drop decision for a
# given request without coordination.

_TRACE_CTX: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "spark_examples_tpu_trace_ctx", default=None)

ENV_TRACE_SAMPLE = "SPARK_EXAMPLES_TPU_TRACE_SAMPLE"

TRACE_EXEMPLARS = 32  # slowest-K request exemplar ring size

_trace_sample = 1.0
_exemplars: list[tuple[float, int, dict]] = []  # min-heap (total_s, seq, rec)
_exemplar_seq = 0


def _env_trace_sample() -> float:
    try:
        v = float(os.environ.get(ENV_TRACE_SAMPLE, "") or 1.0)
    except ValueError:
        return 1.0
    return min(max(v, 0.0), 1.0)


_trace_sample = _env_trace_sample()


def new_trace_id() -> str:
    """A fresh 16-hex request id (one per logical request; hedge legs
    share it)."""
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    """A fresh 8-hex span id (one per leg/hop of a traced request)."""
    return uuid.uuid4().hex[:8]


def current_trace() -> dict | None:
    """The ambient {trace_id, span_id} of this task, or None."""
    return _TRACE_CTX.get()


@contextmanager
def trace_scope(trace_id: str | None = None, span_id: str | None = None):
    """Bind a request trace context for the duration of the block —
    spans begun and events emitted inside automatically carry the ids.
    Yields the context dict (handy for X-Trace-Id echo)."""
    ctx = {"trace_id": trace_id or new_trace_id(),
           "span_id": span_id or new_span_id()}
    token = _TRACE_CTX.set(ctx)
    try:
        yield ctx
    finally:
        _TRACE_CTX.reset(token)


def set_trace_sample(rate: float) -> None:
    """Set the process-wide detailed-tracing sample rate in [0, 1]
    (the --trace-sample knob; also seeds ENV_TRACE_SAMPLE defaults in
    replica children via the environment)."""
    global _trace_sample
    _trace_sample = min(max(float(rate), 0.0), 1.0)


def trace_sample() -> float:
    return _trace_sample


def should_sample(trace_id: str) -> bool:
    """Deterministic per-request sampling decision: crc32(trace_id)
    against the configured rate — stable across threads, hedge legs,
    and replica processes, so a sampled request is sampled everywhere
    its trace_id travels."""
    rate = _trace_sample
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) < rate * 2**32


def span_at(name: str, t0: float, dur: float, cat: str = "trace",
            tid: int | None = None, **attrs) -> None:
    """Record an already-measured interval as a completed span
    (histogram + ph:'X' trace event with explicit start/duration).
    The retroactive form per-request waterfall legs need: the router's
    batch worker knows a request's queue wait only at pickup time and
    its compute share only after the device step returns."""
    _check_name(name)
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram()
        h.record(dur)
    if _trace:
        _append_event({
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (t0 - _T0) * 1e6,
            "dur": dur * 1e6,
            "tid": threading.get_ident() if tid is None else tid,
            "args": attrs,
        })


def record_request_exemplar(trace_id: str, total_s: float,
                            phases: dict, **attrs) -> None:
    """Keep this request in the slowest-K exemplar ring if it is slow
    enough (min-heap on total latency). Keyed by trace_id; served by
    GET /debug/requests and exported as requests.json."""
    global _exemplar_seq
    rec = {"trace_id": trace_id, "total_s": total_s,
           "phases": dict(phases), "t_unix": time.time(), **attrs}
    with _lock:
        _exemplar_seq += 1
        if len(_exemplars) < TRACE_EXEMPLARS:
            heapq.heappush(_exemplars, (total_s, _exemplar_seq, rec))
        elif total_s > _exemplars[0][0]:
            heapq.heapreplace(_exemplars, (total_s, _exemplar_seq, rec))
        else:
            return
        n = len(_exemplars)
    gauge_set("trace.exemplars", float(n))


def request_exemplars() -> list[dict]:
    """The exemplar ring, slowest first."""
    with _lock:
        items = sorted(_exemplars, key=lambda e: (-e[0], e[1]))
    return [dict(rec) for _t, _s, rec in items]


class SpanHandle:
    """An open span: :meth:`end` records it (histogram + trace event),
    :meth:`cancel` drops it. Explicit handles let loop bodies time the
    full block *period* (producer wait included) without contorting the
    iteration into a context manager."""

    __slots__ = ("name", "cat", "t0", "tid", "trace", "_open")

    def __init__(self, name: str, cat: str):
        self.name = name
        self.cat = cat
        self.t0 = time.perf_counter()
        self.tid = threading.get_ident()
        # Captured at open: the span may END on another thread (or
        # after the request scope unwound) and must keep its ids.
        self.trace = _TRACE_CTX.get()
        self._open = True

    def end(self, **attrs) -> float:
        if not self._open:
            return 0.0
        self._open = False
        t1 = time.perf_counter()
        dur = t1 - self.t0
        with _lock:
            h = _hists.get(self.name)
            if h is None:
                h = _hists[self.name] = Histogram()
            h.record(dur)
        if _trace:
            if self.trace is not None:
                attrs = {**self.trace, **attrs}
            _append_event({
                "name": self.name,
                "cat": self.cat,
                "ph": "X",
                "ts": (self.t0 - _T0) * 1e6,
                "dur": dur * 1e6,
                "tid": self.tid,
                "args": attrs,
            })
        return dur

    def cancel(self) -> None:
        self._open = False


def begin(name: str, cat: str = "misc") -> SpanHandle:
    _check_name(name)
    return SpanHandle(name, cat)


@contextmanager
def span(name: str, cat: str = "misc", **attrs):
    """``with telemetry.span("checkpoint.save", cat="checkpoint"):`` —
    nestable (strict LIFO per thread, so the trace's time-containment
    nesting is guaranteed by construction)."""
    sp = begin(name, cat)
    try:
        yield sp
    finally:
        sp.end(**attrs)


def traced(name: str, cat: str = "misc"):
    """Decorator form of :func:`span` for whole-function spans."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name, cat=cat):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------------
# Derived metrics — THE shared formula (PhaseTimer.report() calls this
# too, which is what makes the exported throughputs agree with the
# timer's by construction).


def effective_gram_time(phases: dict) -> float:
    """THE shared gram denominator: gram wall-clock minus the
    streaming-PCoA refresh dispatch that runs *inside* the gram loop
    ("stream_refresh") — otherwise config-5 runs would report deflated
    Gram GFLOPS / ingest MB/s / inflated stall fractions and hide
    exactly the overhead the phase exists to expose. Every consumer
    (derive_throughputs, digest, the rank-0 summary) goes through here
    so they cannot fork."""
    return max(phases.get("gram", 0.0) - phases.get("stream_refresh", 0.0),
               0.0)


def stall_fraction(phases: dict, get_wait_sum: float) -> float:
    """Fraction of the (effective) gram phase the consumer spent waiting
    on the prefetch producer — the 'was the chip starved by ingest'
    number."""
    gram_t = effective_gram_time(phases)
    return get_wait_sum / gram_t if gram_t else 0.0


def derive_throughputs(phases: dict, counters: dict) -> dict:
    """Derived throughput metrics where the raw counters exist."""
    rep: dict[str, float] = {}
    gram_t = effective_gram_time(phases)
    if "gram_flops" in counters and gram_t:
        rep["gram_gflops_per_s"] = counters["gram_flops"] / gram_t / 1e9
    # Ingest bytes are counted wherever streaming happens — a dedicated
    # "ingest" phase if one exists, else the gram loop (whose wall-clock
    # includes the overlapped host reads).
    stream_t = phases.get("ingest") or gram_t
    if "ingest_bytes" in counters and stream_t:
        rep["ingest_mb_per_s"] = counters["ingest_bytes"] / stream_t / 1e6
    if "eigh_flops" in counters and phases.get("eigh"):
        rep["eigh_gflops_per_s"] = counters["eigh_flops"] / phases["eigh"] / 1e9
    return rep


def _split_counters() -> tuple[dict, dict]:
    """(phases, plain counters) from the mirrored registry state."""
    with _lock:
        counters = dict(_counters)
    phases = {k[len("phase."):]: v for k, v in counters.items()
              if k.startswith("phase.")}
    plain = {k: v for k, v in counters.items() if not k.startswith("phase.")}
    return phases, plain


def digest() -> dict:
    """The compact headline digest (bench.py): block-time p50/p95,
    prefetch stall fraction, retries, consensus-wait p95."""
    phases, counters = _split_counters()
    with _lock:
        block = _hists.get("gram.block")
        stall = _hists.get("prefetch.get_wait_s")
        consensus = _hists.get("multihost.consensus")
        block = block.summary() if block else {"count": 0}
        stall_sum = stall.sum if stall else 0.0
        consensus_p95 = consensus.quantile(0.95) if consensus else 0.0
    return {
        "block_p50_s": round(block.get("p50", 0.0), 6),
        "block_p95_s": round(block.get("p95", 0.0), 6),
        "blocks": block.get("count", 0),
        "prefetch_stall_frac": round(stall_fraction(phases, stall_sum), 4),
        "ingest_retries": int(counters.get("ingest.retries", 0.0)),
        "consensus_wait_p95_s": round(consensus_p95, 6),
    }


# ---------------------------------------------------------------------------
# Export.


_rank_cache: tuple[int, int] | None = None


def _rank() -> tuple[int, int]:
    """(process_index, process_count) — lazily, so importing this module
    never initializes a jax backend (test bootstrap order matters).

    A process that has not imported jax at all is rank 0 of 1 and must
    stay that way WITHOUT importing it: the periodic flusher and the
    live HTTP surfaces call this from background threads, and paying
    a full backend/plugin discovery (~hundreds of ms on CPU, seconds
    on a TPU host) inside the first flush would delay the first
    published snapshot past the lifetime of a short or quickly-killed
    process — exactly the process whose last snapshot matters most.
    Once jax is imported the resolved rank is cached (post-init
    process_index is cheap, but the first call may initialize the
    backend; pay that once)."""
    global _rank_cache
    if _rank_cache is not None:
        return _rank_cache
    import sys as _sys

    if "jax" not in _sys.modules:
        # Not cached: jax (and a real multihost rank) may arrive later.
        return 0, 1
    try:
        import jax

        _rank_cache = (jax.process_index(), jax.process_count())
        return _rank_cache
    except Exception:
        return 0, 1


def metrics_snapshot() -> dict:
    """The metrics.json payload (also handy for in-process assertions)."""
    phases, counters = _split_counters()
    with _lock:
        gauges = {k: dict(v) for k, v in _gauges.items()}
        hists = {k: h.summary() for k, h in _hists.items()}
    return {
        "counters": counters,
        "phases": phases,
        "gauges": gauges,
        "histograms": hists,
        "derived": derive_throughputs(phases, counters),
    }


# ---------------------------------------------------------------------------
# Live plane: read-side snapshot API + the periodic publisher.

RECENT_EVENTS = 256  # rolling ring size the live surfaces expose


def recent_events(n: int = RECENT_EVENTS) -> list[dict]:
    """The newest ``n`` buffered trace events (empty when tracing is
    off) — the rolling ring the live surfaces expose; the full buffer
    still lands in ``trace.jsonl`` at export.

    The flusher's own ``live.flush`` spans are excluded: during a quiet
    or stalled stretch they would otherwise displace the job events the
    ring exists to preserve — the killed-attempt stitch fallback needs
    what the JOB was doing when it died, not the flusher's heartbeat.
    (They still land in the full export, where they belong.)"""
    if n <= 0:
        return []
    out: list[dict] = []
    with _lock:
        for ev in reversed(_events):
            if ev.get("name") == "live.flush":
                continue
            out.append(dict(ev))
            if len(out) >= n:
                break
    out.reverse()
    return out


def live_snapshot(recent: int = RECENT_EVENTS) -> dict:
    """The in-flight introspection payload: the full metrics snapshot,
    a rolling ring of recent trace events, and the job identity /
    uptime an operator needs to interpret them. This is what
    ``/debug/telemetry`` (core/live.py) serves, and what in-process
    callers (the supervisor's heartbeat, tests) read without waiting
    for process exit."""
    snap = metrics_snapshot()
    snap["recent_events"] = recent_events(recent)
    snap["meta"] = _meta(len(snap["recent_events"]))
    return snap


def _meta(events_n: int) -> dict:
    rank, n_proc = _rank()
    now_unix, now_perf = time.time(), time.perf_counter()
    return {
        "rank": rank,
        "process_count": n_proc,
        "run_id": run_id(),
        "attempt": attempt(),
        "trace_events": events_n,
        "wrote_unix_s": now_unix,
        # Wall-clock at trace ts=0 — what lets the stitcher place this
        # attempt's perf_counter-relative events on one global timeline
        # next to every other attempt's.
        "epoch_unix_s": now_unix - (now_perf - _T0),
        "uptime_s": now_perf - _T0,
    }


def _atomic_write(path: str, text: str) -> None:
    """tmp + fsync-free rename: a reader (or a kill) mid-write sees
    either the previous complete file or the new complete file, never
    a torn one — the property the telemetry.flush chaos site checks."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def _atomic_write_lines(path: str, lines) -> None:
    """Same atomicity, streaming: lines are written to the tmp file as
    they are produced, so a full-buffer trace export (hundreds of MB at
    MAX_EVENTS) never holds a second joined copy in memory — the
    crash-flush moment is exactly when the process can least afford a
    transient 2x spike."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        for line in lines:
            f.write(line)
            f.write("\n")
    os.replace(tmp, path)


def _rank_dir(base: str) -> str:
    """Where this process exports: ``<base>/rank<r>`` normally,
    ``<base>/attempt<a>/rank<r>`` under supervision — each restart
    keeps its predecessor's trace instead of overwriting it, which is
    what makes restart stitching possible at all."""
    rank, _ = _rank()
    if os.environ.get(ENV_ATTEMPT, "").strip():
        return os.path.join(base, f"attempt{attempt()}", f"rank{rank}")
    return os.path.join(base, f"rank{rank}")


def _export_exemplars(d: str) -> None:
    """``requests.json``: the slowest-K request exemplar ring, written
    atomically next to metrics.json. The ``trace.export`` fault site
    fires here (and at the fleet timeline's writes) so the chaos
    harness can prove a torn exemplar write leaves the last-good file
    readable; failures are absorbed into ``trace.export_errors`` — a
    trace artifact must never fail the process it describes."""
    from spark_examples_tpu.core import faults  # circular at module load

    ex = request_exemplars()
    if not ex:
        return
    path = os.path.join(d, "requests.json")
    try:
        faults.fire("trace.export", path=path)
        _atomic_write(path, json.dumps(
            {"exemplars": ex, "trace_sample": _trace_sample,
             "meta": _meta(0)}, indent=1, sort_keys=True, default=str))
    except OSError:
        count("trace.export_errors")


class PeriodicFlusher:
    """Daemon thread atomically republishing ``metrics.json`` plus a
    rolling ``live_trace.jsonl`` ring every ``interval_s`` — the
    in-process snapshot publisher. A failed flush warns once and keeps
    going (``live.flush_errors``); the ``telemetry.flush`` fault site
    fires inside each flush so the chaos harness can fail, stall, or
    kill it deterministically (a mid-write kill must leave the
    last-good snapshot readable — guaranteed by tmp+rename)."""

    def __init__(self, base: str, interval_s: float):
        self.base = base
        self.interval_s = max(0.01, float(interval_s))
        self._stop = threading.Event()
        self._warned = False
        self._thread: threading.Thread | None = None

    def start(self) -> "PeriodicFlusher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="telemetry-flusher", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()
        self.flush()  # final publish so stop() never loses the tail

    def flush(self) -> None:
        from spark_examples_tpu.core import faults  # circular at module load

        d = _rank_dir(self.base)
        try:
            with span("live.flush", cat="live"):
                os.makedirs(d, exist_ok=True)
                metrics_path = os.path.join(d, "metrics.json")
                faults.fire("telemetry.flush", path=metrics_path)
                snap = metrics_snapshot()
                snap["meta"] = _meta(len(_events))
                _atomic_write(metrics_path,
                              json.dumps(snap, indent=1, sort_keys=True,
                                         default=str))
                rank = snap["meta"]["rank"]
                _atomic_write_lines(
                    os.path.join(d, "live_trace.jsonl"),
                    (json.dumps({**ev, "pid": rank}, default=str)
                     for ev in recent_events()))
                _export_exemplars(d)
            count("live.flushes")
        except BaseException as e:
            count("live.flush_errors")
            if not self._warned:
                self._warned = True
                warnings.warn(
                    f"periodic telemetry flush to {d!r} failed ({e!r}) — "
                    "the job continues; live snapshots may be stale "
                    "until writes recover",
                    RuntimeWarning, stacklevel=2,
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def start_periodic_flush(interval_s: float,
                         dir: str | None = None) -> PeriodicFlusher | None:
    """Start (or retarget) the module's periodic flusher. Returns it,
    or None when no directory is configured."""
    global _flusher
    base = dir or _dir
    if not base:
        return None
    stop_periodic_flush()
    _flusher = PeriodicFlusher(base, interval_s).start()
    return _flusher


def stop_periodic_flush() -> None:
    """Stop the periodic flusher (one final flush included)."""
    global _flusher
    f = _flusher
    _flusher = None
    if f is not None:
        f.stop()


def export(dir: str | None = None) -> str | None:
    """Write ``rank<k>/{trace.jsonl,metrics.json}`` under ``dir`` (or the
    configured directory), plus the merged ``summary.txt`` on rank 0.
    Returns this rank's directory, or None when nothing is configured.

    The summary merge is best-effort from whatever peer metrics.json
    files are already visible (no collective at exit: telemetry must
    never be able to hang a job that otherwise finished). An unwritable
    directory or full disk warns and returns None instead of raising —
    telemetry must never be able to FAIL a job (or discard a bench
    run's results) that otherwise finished either."""
    base = dir or _dir
    if not base:
        return None
    try:
        return _export(base)
    except OSError as e:
        warnings.warn(f"telemetry export to {base} failed: {e}",
                      RuntimeWarning, stacklevel=2)
        return None


def _export(base: str) -> str:
    rank, n_proc = _rank()
    d = _rank_dir(base)
    os.makedirs(d, exist_ok=True)
    rid, att = run_id(), attempt()

    with _lock:
        events = sorted(_events, key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    track = f"rank {rank}" if not att else f"attempt {att} rank {rank}"

    def _trace_lines():
        yield json.dumps({"name": "process_name", "ph": "M", "pid": rank,
                          "tid": 0, "ts": 0, "args": {"name": track}})
        for ev in events:
            # default=str: a site passing e.g. a numpy scalar attr must
            # degrade to a stringified arg, not kill the export. Every
            # event carries the stitch identity (run_id/attempt; pid is
            # the rank track) so a merged multi-attempt trace stays
            # attributable event-by-event.
            yield json.dumps(
                {**ev, "pid": rank,
                 "args": {**ev.get("args", {}), "run_id": rid,
                          "attempt": att}},
                default=str)

    # Atomic (tmp+rename) like the periodic flusher's writes: the
    # exit-time export and a racing periodic flush must each leave a
    # complete file, whoever lands last — streamed, so the full trace
    # is never duplicated in memory.
    _atomic_write_lines(os.path.join(d, "trace.jsonl"), _trace_lines())

    snap = metrics_snapshot()
    snap["meta"] = _meta(len(events))
    _atomic_write(os.path.join(d, "metrics.json"),
                  json.dumps(snap, indent=1, sort_keys=True, default=str))
    _export_exemplars(d)

    if rank == 0:
        try:
            # Under supervision the rank dirs live in the attempt dir;
            # the summary belongs next to them either way.
            _write_summary(os.path.dirname(d), n_proc)
        except OSError as e:  # summary is a convenience, never a failure
            warnings.warn(f"telemetry summary not written: {e}",
                          RuntimeWarning, stacklevel=2)
    return d


def _write_summary(base: str, n_proc: int) -> None:
    """Human-readable per-rank table + consensus skew at ``base``.

    Ranks are enumerated by index (0..n_proc-1), NOT by listdir, and a
    peer file whose ``meta.wrote_unix_s`` predates this process's start
    is treated as not-yet-exported: both guard the same failure — a
    stale rank file left by a previous run in a reused directory
    (rank 0 exports without a collective, so a slower peer's file from
    the LAST run may still be sitting at the same path) would fabricate
    exactly the straggler skew the summary exists to surface. The 5 s
    slack absorbs wall-clock skew between hosts sharing the FS."""
    rows = []
    stale = 0
    for rank in range(n_proc):
        try:
            with open(os.path.join(base, f"rank{rank}",
                                   "metrics.json")) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        if (rank != 0 and m.get("meta", {}).get("wrote_unix_s", 0.0)
                < _START_UNIX - 5.0):
            stale += 1
            continue
        hists = m.get("histograms", {})
        block = hists.get("gram.block", {})
        wait = hists.get("multihost.consensus", {})
        stall = hists.get("prefetch.get_wait_s", {})
        derived = m.get("derived", {})
        phases = m.get("phases", {})
        rows.append({
            "rank": rank,
            "gram_gflops": derived.get("gram_gflops_per_s", 0.0),
            "ingest_mb_s": derived.get("ingest_mb_per_s", 0.0),
            "block_p50_ms": block.get("p50", 0.0) * 1e3,
            "block_p95_ms": block.get("p95", 0.0) * 1e3,
            "stall_frac": stall_fraction(phases, stall.get("sum", 0.0)),
            "retries": int(m.get("counters", {}).get("ingest.retries", 0)),
            "wait_mean_ms": (wait.get("mean", 0.0)) * 1e3,
            "wait_p95_ms": wait.get("p95", 0.0) * 1e3,
        })
    cols = ("rank", "gram_gflops", "ingest_mb_s", "block_p50_ms",
            "block_p95_ms", "stall_frac", "retries", "wait_mean_ms",
            "wait_p95_ms")
    lines = ["\t".join(cols)]
    for r in rows:
        lines.append("\t".join(
            str(r["rank"]) if c == "rank"
            else str(r["retries"]) if c == "retries"
            else f"{r[c]:.3f}" if c == "stall_frac"
            else f"{r[c]:.2f}"
            for c in cols
        ))
    waits = [r["wait_mean_ms"] for r in rows]
    if len(waits) > 1:
        lines.append(
            f"consensus wait skew (max-min of per-rank mean): "
            f"{max(waits) - min(waits):.2f} ms"
        )
    if len(rows) < n_proc:
        note = (f"note: {n_proc - len(rows)} rank(s) had not exported "
                "when rank 0 wrote this summary")
        if stale:
            note += (f" ({stale} stale file(s) from a previous run in "
                     "this directory were ignored)")
        lines.append(note)
    with open(os.path.join(base, "summary.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")

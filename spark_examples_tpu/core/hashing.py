"""Shared content-hashing helpers — ONE sha256 vocabulary for the repo.

Three subsystems independently grew digest code: the checkpoint layer
(per-file integrity + a tee writer that hashes bytes as np.save emits
them), the serving result cache (genotype-block content keys), and now
the content-addressed dataset store (chunk addresses ARE digests). The
helpers live here so the encodings can't drift: a digest computed at
write time by one subsystem must verify at read time in another.
"""

from __future__ import annotations

import hashlib

import numpy as np


def sha256_bytes(data) -> str:
    """Hex sha256 of a bytes-like object (bytes/memoryview/buffer).

    The store's chunk content address: the digest of the packed chunk
    bytes exactly as they land on disk, so filename == content and a
    re-read can be verified against the name alone.
    """
    return hashlib.sha256(data).hexdigest()


def sha256_file(path: str, chunk_bytes: int = 1 << 20) -> str:
    """Hex sha256 of a file, streamed (never the whole file in RAM)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_bytes), b""):
            h.update(chunk)
    return h.hexdigest()


class TeeHashWriter:
    """File wrapper hashing every byte as it is written — save paths
    must not re-read what they just wrote only to checksum it (that
    would double every checkpoint/compaction's IO over a shared
    filesystem)."""

    def __init__(self, f):
        self._f = f
        self.sha256 = hashlib.sha256()

    def write(self, data):
        self.sha256.update(data)
        return self._f.write(data)

    def __getattr__(self, name):
        return getattr(self._f, name)


def array_digest(arr: np.ndarray, namespace: str = "") -> str:
    """Content digest of one array, dtype and shape folded in so two
    buffers with the same bytes but different views cannot collide;
    ``namespace`` prefixes a caller-chosen scope (e.g. the serving
    cache's model fingerprint)."""
    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(f"{namespace}|{a.dtype.str}|{a.shape}|".encode())
    h.update(a.tobytes())
    return h.hexdigest()


def sample_hash(sample_ids: list[str]) -> str:
    """Short (16-hex) cohort fingerprint over the ordered sample ids —
    the compatibility check checkpoint and store manifests both carry."""
    h = hashlib.sha256("\n".join(sample_ids).encode()).hexdigest()
    return h[:16]

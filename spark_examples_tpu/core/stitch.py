"""Restart- and rank-stitched traces: one Perfetto session per job.

A supervised job (core/supervisor.py) shatters into per-attempt,
per-rank telemetry exports — ``<dir>/attempt<a>/rank<r>/trace.jsonl``
(unsupervised runs write ``<dir>/rank<r>/``). Each file is loadable on
its own, but the thing an operator debugs is the *logical job*: attempt
0 streamed six blocks, got killed, attempt 1 resumed from the
checkpoint — on one timeline, with the restart visible.

:func:`stitch` merges every attempt/rank export under a telemetry
directory into one Chrome-trace JSONL:

- **One global timeline.** Every export's ``metrics.json`` meta records
  ``epoch_unix_s`` — the wall-clock instant at that process's trace
  ``ts=0`` — so each attempt's perf-counter-relative events shift onto
  a shared wall-clock axis (earliest attempt = t0). No clock collective
  is needed; sub-second host clock skew is noise at restart timescales.
- **One track per (attempt, rank).** Events keep their thread tracks
  within a remapped pid (``attempt*10000 + rank``), named
  ``attempt <a> rank <r>`` and sorted in attempt order.
- **Restart-incident markers.** The supervisor parent's incident ledger
  (``supervisor.json``, written next to the attempt dirs) becomes
  global instant events on a dedicated ``supervisor`` track — the crash
  / hang / stall verdict and detail sit exactly where the timeline
  breaks.
- **Identity checked.** Every export's ``run_id`` must agree (the
  supervisor pins one run_id across attempts); mixed run_ids are
  reported, not silently merged — two unrelated jobs in one directory
  is a layout mistake, not a session.

Exposed as the ``telemetry stitch`` CLI verb (cli/main.py).
"""

from __future__ import annotations

import json
import os
import re

from spark_examples_tpu.core import telemetry

SUPERVISOR_LEDGER = "supervisor.json"
CONTROLLER_LEDGER = "controller.json"

# pid remap: attempts land far apart so rank tracks can't collide
# (rank counts are bounded by pod size, nowhere near 10k).
_ATTEMPT_STRIDE = 10_000
# Fleet mode: one pid block per replica slot, far above any
# slot-internal attempt*stride+rank remap.
_SLOT_STRIDE = 1_000_000
_SUPERVISOR_PID = 999_999_999
_CONTROLLER_PID = 999_999_998

_RANK_RE = re.compile(r"^rank(\d+)$")
_ATTEMPT_RE = re.compile(r"^attempt(\d+)$")


class StitchError(RuntimeError):
    """Nothing stitchable under the directory (wrong path, or a job
    that never exported)."""


def _iter_exports(base: str):
    """Yield (attempt, rank, rank_dir) for every export under base.
    ``attempt`` is None for the flat unsupervised layout (resolved from
    the export's own meta later, defaulting to 0)."""
    try:
        entries = sorted(os.listdir(base))
    except OSError as e:
        raise StitchError(f"cannot read telemetry dir {base!r}: {e}") from e
    for entry in entries:
        full = os.path.join(base, entry)
        if not os.path.isdir(full):
            continue
        m = _RANK_RE.match(entry)
        if m:
            yield None, int(m.group(1)), full
            continue
        m = _ATTEMPT_RE.match(entry)
        if m:
            att = int(m.group(1))
            for sub in sorted(os.listdir(full)):
                rm = _RANK_RE.match(sub)
                if rm and os.path.isdir(os.path.join(full, sub)):
                    yield att, int(rm.group(1)), os.path.join(full, sub)


def _load_export(rank_dir: str) -> tuple[dict, list[dict]]:
    """(meta, events) for one rank export; missing/torn files degrade
    to empty rather than failing the whole stitch — a crashed attempt
    may have a trace but no metrics (or vice versa), and partial
    visibility beats none.

    A killed attempt never reached its exit-time export, so its
    ``trace.jsonl`` is absent — but the periodic flusher's last
    ``live_trace.jsonl`` ring survives the kill (tmp+rename), and
    those recent events are exactly the "what was it doing when it
    died" evidence; fall back to them."""
    meta: dict = {}
    try:
        with open(os.path.join(rank_dir, "metrics.json")) as f:
            meta = json.load(f).get("meta", {}) or {}
    except (OSError, ValueError):
        pass
    events: list[dict] = []
    for name in ("trace.jsonl", "live_trace.jsonl"):
        try:
            with open(os.path.join(rank_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a killed writer
                    if ev.get("ph") != "M":  # re-emit our own metadata
                        events.append(ev)
        except OSError:
            continue
        if events:
            break  # the full trace supersedes the ring
    return meta, events


def stitch(base: str, output: str | None = None) -> dict:
    """Merge every attempt/rank export under ``base`` into one
    Perfetto-loadable trace; returns the stitch report (attempts,
    ranks, event/marker counts, run ids, output path)."""
    exports = []
    for att, rank, rank_dir in _iter_exports(base):
        meta, events = _load_export(rank_dir)
        if att is None:
            att = int(meta.get("attempt", 0) or 0)
        exports.append((att, rank, meta, events))
    if not exports:
        raise StitchError(
            f"no rank<k>/ or attempt<a>/rank<k>/ exports under {base!r} "
            "— is this a --telemetry-dir?")
    exports.sort(key=lambda e: (e[0], e[1]))

    run_ids = sorted({m.get("run_id") for _a, _r, m, _e in exports
                      if m.get("run_id")})
    epochs = [m.get("epoch_unix_s") for _a, _r, m, _e in exports
              if isinstance(m.get("epoch_unix_s"), (int, float))]
    # Fallback for exports with no meta at all: align their ts=0 to the
    # earliest known epoch (best-effort; they still land on the track).
    epoch0 = min(epochs) if epochs else 0.0

    markers = _ledger_markers(base, epoch0)
    counted = [0]

    # Serialized lines stream straight into the atomic tmp file — a
    # near-MAX_EVENTS multi-attempt stitch never holds the whole merged
    # trace a second time as a list-of-strings plus a joined blob.
    def _lines():
        for att, rank, meta, events in exports:
            pid = att * _ATTEMPT_STRIDE + rank
            shift_us = (float(meta.get("epoch_unix_s", epoch0))
                        - epoch0) * 1e6
            yield json.dumps({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "ts": 0, "args": {"name": f"attempt {att} rank {rank}"}})
            yield json.dumps({
                "name": "process_sort_index", "ph": "M", "pid": pid,
                "tid": 0, "ts": 0, "args": {"sort_index": pid}})
            for ev in events:
                ev = dict(ev)
                ev["pid"] = pid
                ev["ts"] = float(ev.get("ts", 0.0)) + shift_us
                counted[0] += 1
                yield json.dumps(ev, default=str)
        if markers:
            yield json.dumps({
                "name": "process_name", "ph": "M", "pid": _SUPERVISOR_PID,
                "tid": 0, "ts": 0, "args": {"name": "supervisor"}})
            for m in markers:
                yield json.dumps(m, default=str)

    out_path = output or os.path.join(base, "stitched_trace.jsonl")
    telemetry._atomic_write_lines(out_path, _lines())
    total_events = counted[0]
    return {
        "output": out_path,
        "attempts": sorted({a for a, _r, _m, _e in exports}),
        "ranks": sorted({r for _a, r, _m, _e in exports}),
        "events": total_events,
        "restart_markers": len(markers),
        "run_ids": run_ids,
        "mixed_run_ids": len(run_ids) > 1,
    }


def stitch_fleet(base: str, output: str | None = None) -> dict:
    """Fleet mode: merge EVERY replica slot's attempt/rank exports
    under ``base/<slot>/`` onto one Perfetto timeline — one pid block
    per slot — with the controller ledger's incidents (current
    generation plus the rotated ``controller.json.old``) as global
    markers on a dedicated ``controller`` track.

    This is the whole-fleet waterfall view: a request hedged across
    two replicas shows both legs (same ``trace_id`` in the span args,
    distinct span ids), and a kill/respawn incident marker sits at the
    wall-clock instant the surviving leg's spans route around it."""
    slots = []
    try:
        entries = sorted(os.listdir(base))
    except OSError as e:
        raise StitchError(f"cannot read fleet dir {base!r}: {e}") from e
    for entry in entries:
        full = os.path.join(base, entry)
        if not os.path.isdir(full):
            continue
        exports = []
        try:
            for att, rank, rank_dir in _iter_exports(full):
                meta, events = _load_export(rank_dir)
                if att is None:
                    att = int(meta.get("attempt", 0) or 0)
                exports.append((att, rank, meta, events))
        except StitchError:
            continue
        if exports:
            exports.sort(key=lambda e: (e[0], e[1]))
            slots.append((entry, exports))
    if not slots:
        raise StitchError(
            f"no <slot>/rank<k> or <slot>/attempt<a>/rank<k> exports "
            f"under {base!r} — is this a fleet workdir?")

    run_ids = sorted({m.get("run_id")
                      for _slot, exports in slots
                      for _a, _r, m, _e in exports if m.get("run_id")})
    epochs = [m.get("epoch_unix_s")
              for _slot, exports in slots
              for _a, _r, m, _e in exports
              if isinstance(m.get("epoch_unix_s"), (int, float))]
    epoch0 = min(epochs) if epochs else 0.0

    markers = _controller_markers(base, epoch0)
    counted = [0]

    def _lines():
        for slot_idx, (slot, exports) in enumerate(slots):
            for att, rank, meta, events in exports:
                pid = (slot_idx * _SLOT_STRIDE
                       + att * _ATTEMPT_STRIDE + rank)
                shift_us = (float(meta.get("epoch_unix_s", epoch0))
                            - epoch0) * 1e6
                yield json.dumps({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "ts": 0,
                    "args": {"name":
                             f"{slot} attempt {att} rank {rank}"}})
                yield json.dumps({
                    "name": "process_sort_index", "ph": "M", "pid": pid,
                    "tid": 0, "ts": 0, "args": {"sort_index": pid}})
                for ev in events:
                    ev = dict(ev)
                    ev["pid"] = pid
                    ev["ts"] = float(ev.get("ts", 0.0)) + shift_us
                    counted[0] += 1
                    yield json.dumps(ev, default=str)
        if markers:
            yield json.dumps({
                "name": "process_name", "ph": "M",
                "pid": _CONTROLLER_PID, "tid": 0, "ts": 0,
                "args": {"name": "controller"}})
            for m in markers:
                yield json.dumps(m, default=str)

    out_path = output or os.path.join(base, "stitched_fleet_trace.jsonl")
    telemetry._atomic_write_lines(out_path, _lines())
    return {
        "output": out_path,
        "slots": [slot for slot, _e in slots],
        "events": counted[0],
        "incident_markers": len(markers),
        "run_ids": run_ids,
        "mixed_run_ids": len(run_ids) > 1,
    }


def _controller_markers(base: str, epoch0: float) -> list[dict]:
    """Controller ledger incidents (current + rotated ``.old``
    generation, deduplicated) -> global markers on the controller
    track."""
    incidents: list[dict] = []
    seen: set[tuple] = set()
    for name in (CONTROLLER_LEDGER + ".old", CONTROLLER_LEDGER):
        try:
            with open(os.path.join(base, name)) as f:
                ledger = json.load(f)
        except (OSError, ValueError):
            continue
        for inc in ledger.get("incidents", []):
            key = (inc.get("t_unix"), inc.get("who"), inc.get("kind"),
                   inc.get("detail"))
            if key in seen:
                continue
            seen.add(key)
            incidents.append(inc)
    markers = []
    for inc in incidents:
        ts = max(0.0, (float(inc.get("t_unix", epoch0)) - epoch0) * 1e6)
        kind = inc.get("kind", "incident")
        markers.append({
            "name": f"incident: {kind}",
            "cat": "controller",
            "ph": "i",
            "s": "g",
            "ts": ts,
            "pid": _CONTROLLER_PID,
            "tid": 0,
            "args": {k: inc.get(k)
                     for k in ("round", "who", "kind", "detail")},
        })
    markers.sort(key=lambda m: m["ts"])
    return markers


def _ledger_markers(base: str, epoch0: float) -> list[dict]:
    """Supervisor incidents -> global instant events on their own track."""
    try:
        with open(os.path.join(base, SUPERVISOR_LEDGER)) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        return []
    markers = []
    for inc in ledger.get("incidents", []):
        ts = max(0.0, (float(inc.get("t_unix", epoch0)) - epoch0) * 1e6)
        kind = inc.get("kind", "incident")
        markers.append({
            "name": f"restart: {kind}",
            "cat": "supervisor",
            "ph": "i",
            "s": "g",  # global scope: the full-height timeline marker
            "ts": ts,
            "pid": _SUPERVISOR_PID,
            "tid": 0,
            "args": {k: inc.get(k) for k in
                     ("attempt", "kind", "detail", "returncode")},
        })
    return markers

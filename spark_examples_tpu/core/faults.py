"""Deterministic, seeded fault injection — the chaos harness.

The reference inherited fault tolerance from Spark lineage recompute and
never had to prove it (SURVEY.md §5 "Failure detection"); the TPU-native
successor carries its own retry/checkpoint/consensus machinery
(ingest/resilient.py, core/checkpoint.py, parallel/multihost.py), and an
untested recovery path is indistinguishable from a missing one. This
module is the proving ground: named **sites** in the production code call
:func:`fire`, and tests / the bench ``--chaos`` mode arm **specs**
against those sites to raise transient IOErrors, delay blocks
(stragglers), truncate just-written files, or kill the process outright.

Design constraints:

- **Deterministic.** A seeded ``random.Random`` plus per-site hit
  counters decide every fire, so an injected run is exactly repeatable —
  the crash-recovery tests assert *bit-identical* results against clean
  runs, which only means something if the faults land in the same places
  every time.
- **Free when disarmed.** ``fire()`` is called in per-block hot paths;
  with nothing armed it is one global check and a return.
- **Cross-process.** Multi-process tests (tests/test_distributed.py) and
  the CLI arm via the ``SPARK_EXAMPLES_TPU_FAULTS`` environment variable
  (parsed lazily on first ``fire``), in-process tests via the
  :func:`armed` context manager.

Sites instrumented in production code:

==========================  ====================================================
``ingest.block_read``       per block, inside the retry boundary of
                            :class:`~spark_examples_tpu.ingest.resilient.RetryingSource`
``checkpoint.tile_write``   per checkpoint file, AFTER its sha256 was
                            recorded (so truncation corrupts against the
                            manifest — core/checkpoint.py)
``checkpoint.tile_read``    per file during checkpoint verification
``multihost.consensus``     per control-plane allgather round
                            (parallel/multihost.py)
``device.put``              per host->device block transfer
                            (ingest/prefetch.py)
``serve.request``           per admitted request, in the projection
                            server's batch-assembly sweep (serve/
                            server.py) — ``io_error`` fails exactly that
                            request, ``delay`` stalls the worker so the
                            bounded admission queue must shed, ``kill``
                            simulates a serving-process preemption
``store.read``              per chunk read in the content-addressed
                            block store (store/reader.py), fired with
                            the chunk file path BEFORE the bytes are
                            mapped — ``io_error`` exercises the
                            RetryingSource boundary, ``truncate``
                            corrupts the chunk against its recorded
                            digest (heal-or-quarantine must catch it)
``store.readahead.decode``  per background chunk warm, inside the
                            readahead pool worker (store/readahead.py)
                            — a worker-thread failure must be held and
                            re-raised at the consumer's cursor, never
                            swallowed or thread-fatal
``prefetch.transfer_wait``  per staging-slab retire in the K-deep
                            device feed (ingest/prefetch.py), fired
                            before the transfer-completion wait —
                            ``delay`` is a stalled host->device link at
                            retire time, ``io_error`` a failed transfer
                            completion (job resumes from checkpoint)
``supervisor.heartbeat``    per heartbeat write in a supervised child
                            (core/supervisor.py) — ``delay`` freezes
                            the heartbeat so the watchdog must detect
                            the hang and restart; ``io_error`` fails
                            one write (tolerated, warned, never fatal
                            to the job thread)
``fleet.stage``             per panel stage into the fleet serving
                            warm pool (serve/pool.py), fired before the
                            panel's source streams — ``io_error`` fails
                            exactly the requests waiting on that stage
                            (and feeds the route's circuit breaker),
                            ``delay`` is a slow cold tier at re-stage
                            time, ``kill`` a preemption mid-stage
``telemetry.flush``         per periodic live-telemetry flush
                            (core/telemetry.py PeriodicFlusher), fired
                            with the metrics.json path before the
                            atomic write — ``io_error`` fails one
                            flush (tolerated, warned, counted),
                            ``kill`` mid-flush must leave the
                            last-good snapshot readable (tmp+rename),
                            ``truncate`` corrupts the current file
                            until the flush's own rename restores it
``controller.scrape``       per replica scrape in the fleet
                            controller's watch loop (fleet/
                            controller.py) — ``io_error`` blackholes
                            the /metrics endpoint: the slot must act
                            on its last-good snapshot marked stale,
                            then declare the replica lost only after
                            stale_scrapes consecutive failures
``controller.spawn``        per replica spawn (bootstrap, respawn,
                            scale-up) in the fleet controller —
                            ``io_error`` is a spawn-failure cascade:
                            the slot must back off exponentially and
                            the flap breaker must park it rather than
                            spawn-loop
``neighbors.candidates``    per candidate-evaluation attempt in the
                            neighbor engine's exact pass (neighbors/
                            engine.py), fired inside the per-block
                            retry boundary BEFORE the pair statistics
                            accumulate — ``io_error`` must recover
                            bit-identically (the block's contribution
                            is recomputed from scratch on retry),
                            ``delay`` is a slow gather of candidate
                            rows, ``kill`` a preemption mid-evaluation
``trace.export``            per flight-recorder artifact write: the
                            slowest-request exemplar file (core/
                            telemetry.py requests.json) and each fleet
                            timeline append/compaction (fleet/
                            timeline.py) — ``io_error`` fails one
                            write (absorbed into trace.export_errors /
                            timeline.write_errors, never fatal),
                            ``truncate`` tears the timeline's tail
                            (readers must skip the torn line and keep
                            the last-good rounds)
==========================  ====================================================

Env grammar (``;``-separated specs, ``:``-separated fields)::

    SPARK_EXAMPLES_TPU_FAULTS="ingest.block_read:io_error:max=2;multihost.consensus:delay:delay=0.1"
    SPARK_EXAMPLES_TPU_FAULT_SEED=7

Fields after ``site:kind`` are ``key=value``: ``p`` (probability,
default 1), ``after`` (hits passed through before firing starts,
default 0), ``max`` (fires before the spec exhausts, default 1;
0 = unlimited), ``delay`` (seconds, ``delay`` kind), ``keep`` (bytes
kept, ``truncate`` kind).
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from spark_examples_tpu.core import telemetry

ENV_SPECS = "SPARK_EXAMPLES_TPU_FAULTS"
ENV_SEED = "SPARK_EXAMPLES_TPU_FAULT_SEED"

KINDS = ("io_error", "delay", "truncate", "kill")

SITES = (
    "ingest.block_read",
    "checkpoint.tile_write",
    "checkpoint.tile_read",
    "multihost.consensus",
    "device.put",
    "serve.request",
    "fleet.stage",
    "store.read",
    "store.readahead.decode",
    "prefetch.transfer_wait",
    "supervisor.heartbeat",
    "telemetry.flush",
    "controller.scrape",
    "controller.spawn",
    "trace.export",
    "neighbors.candidates",
)

# Distinctive exit code for the "kill" kind so tests can tell an injected
# kill from an ordinary crash.
KILL_EXIT_CODE = 113


class InjectedFault(IOError):
    """The transient error the io_error kind raises — an IOError subclass
    on purpose: the retry machinery must treat it exactly like a real
    flaky filesystem/network read."""


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: WHERE (site), WHAT (kind), WHEN (after/max/p)."""

    site: str
    kind: str = "io_error"
    probability: float = 1.0
    after: int = 0  # hits passed through before firing begins
    max_fires: int = 1  # 0 = unlimited
    delay_s: float = 0.05  # "delay" kind
    keep_bytes: int = 8  # "truncate" kind: bytes kept

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; instrumented sites: "
                f"{', '.join(SITES)}"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; valid: "
                f"{', '.join(KINDS)}"
            )

    @classmethod
    def parse(cls, spec: str) -> "FaultSpec":
        """``site:kind[:key=value...]`` -> FaultSpec (the env grammar)."""
        parts = [p for p in spec.strip().split(":") if p]
        if len(parts) < 2:
            raise ValueError(
                f"bad fault spec {spec!r}: expected site:kind[:key=value...]"
            )
        kw: dict = {"site": parts[0], "kind": parts[1]}
        keys = {"p": ("probability", float), "after": ("after", int),
                "max": ("max_fires", int), "delay": ("delay_s", float),
                "keep": ("keep_bytes", int)}
        for field in parts[2:]:
            key, _, val = field.partition("=")
            if key not in keys:
                raise ValueError(
                    f"bad fault spec field {field!r} in {spec!r}; valid "
                    f"keys: {', '.join(keys)}"
                )
            name, cast = keys[key]
            kw[name] = cast(val)
        return cls(**kw)


class Injector:
    """Seeded registry of armed specs with per-site hit/fire counters."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self._rng = random.Random(seed)
        self._hits: dict[str, int] = {}
        self._fires: dict[str, int] = {}
        self._lock = threading.Lock()  # sites fire from producer threads

    def fire(self, site: str, path: str | None = None) -> None:
        with self._lock:
            hit = self._hits.get(site, 0)
            self._hits[site] = hit + 1
            spec = None
            for s in self.specs:
                if s.site != site or hit < s.after:
                    continue
                if s.max_fires and self._fires.get(id(s), 0) >= s.max_fires:
                    continue
                if s.probability < 1.0 and self._rng.random() >= s.probability:
                    continue
                spec = s
                self._fires[id(s)] = self._fires.get(id(s), 0) + 1
                self._fires[site] = self._fires.get(site, 0) + 1
                break
        if spec is None:
            return
        # Observable firings: the counter makes a chaos run's injected-
        # fault count part of its metrics, and the instant event pins
        # each firing to the trace timeline next to whatever it broke.
        telemetry.count("faults.fired")
        telemetry.event("fault", cat="faults", site=site, kind=spec.kind)
        self._execute(spec, site, path)

    @staticmethod
    def _execute(spec: FaultSpec, site: str, path: str | None) -> None:
        if spec.kind == "delay":
            time.sleep(spec.delay_s)
            return
        if spec.kind == "io_error":
            raise InjectedFault(
                f"injected transient IO error at {site}"
                + (f" ({path})" if path else "")
            )
        if spec.kind == "truncate":
            if path is None:
                raise ValueError(
                    f"truncate fault armed at {site}, but the site passed "
                    "no file path to corrupt"
                )
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(min(spec.keep_bytes, size))
            return
        # kill: simulate preemption — no cleanup, no atexit, no flush.
        os._exit(KILL_EXIT_CODE)

    def fire_count(self, site: str) -> int:
        with self._lock:
            return self._fires.get(site, 0)

    def hit_count(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)


_active: Injector | None = None
_env_checked = False
# Guards lazy env-arming in fire(): the first fires can race in from the
# prefetch producer thread and the main thread, and an unlocked
# check-then-arm could double-arm (resetting hit counters) or drop a
# hit — nondeterministic injection in the one module whose design
# constraint is exact repeatability.
_arm_lock = threading.Lock()


def arm(specs, seed: int = 0) -> Injector:
    """Install an injector (replacing any armed one) and return it."""
    global _active, _env_checked
    _env_checked = True  # explicit arming overrides the env
    _active = Injector([s if isinstance(s, FaultSpec) else FaultSpec.parse(s)
                        for s in specs], seed=seed)
    return _active


def disarm() -> None:
    global _active
    _active = None


@contextmanager
def armed(specs, seed: int = 0):
    """``with faults.armed([...]) as inj:`` — scoped arming for tests."""
    inj = arm(specs, seed=seed)
    try:
        yield inj
    finally:
        disarm()


def from_env() -> Injector | None:
    """Arm from ``SPARK_EXAMPLES_TPU_FAULTS`` (subprocess tests, CLI
    chaos runs). Returns the injector, or None when the variable is
    absent/empty."""
    raw = os.environ.get(ENV_SPECS, "").strip()
    if not raw:
        return None
    seed = int(os.environ.get(ENV_SEED, "0"))
    return arm([s for s in raw.split(";") if s.strip()], seed=seed)


def fire(site: str, path: str | None = None) -> None:
    """The production-code hook: a no-op unless armed (one global check
    when disarmed — safe in per-block hot paths)."""
    global _env_checked
    inj = _active
    if inj is None:
        # Unlocked fast path: once the env has been checked and nothing
        # is armed, every fire is one read + return (the documented
        # disarmed cost). The lock only guards the FIRST check, where
        # concurrent fires from the prefetch producer and main threads
        # could otherwise double-arm or drop a hit.
        if _env_checked:
            return
        with _arm_lock:
            inj = _active
            if inj is None:
                if _env_checked:
                    return
                _env_checked = True
                inj = from_env()
                if inj is None:
                    return
    inj.fire(site, path=path)


def fire_count(site: str) -> int:
    """Fires recorded at ``site`` by the armed injector (0 if disarmed)."""
    return _active.fire_count(site) if _active is not None else 0

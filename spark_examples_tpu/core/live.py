"""Live introspection surface: HTTP views over the telemetry registry.

`core/telemetry.py` owns collection and the periodic snapshot flusher;
this module is the *read* plane an operator / autoscaler / supervisor
scrapes while the job runs:

- :func:`prometheus_text` — the registry rendered as Prometheus
  exposition text (counters as ``<name>_total``, gauges as-is,
  histograms as summaries with p50/p95/p99 quantiles plus
  ``_sum``/``_count``) — one renderer shared by the serve front's
  ``/metrics`` and the batch sidecar, so the two can never disagree
  about a series name.
- :class:`LiveTelemetryServer` — the ``--live-port`` stdlib HTTP
  sidecar for *batch* jobs (gram, sketch, ingest/compact): binds
  ``/metrics``, ``/debug/telemetry`` (the full
  :func:`telemetry.live_snapshot` JSON) and ``/healthz`` in a daemon
  thread, costs nothing until scraped. Port 0 binds ephemerally; the
  bound port is written to :data:`ENV_PORT_FILE` / :data:`ENV_ANNOUNCE`
  paths when set, which is how the supervisor parent (and tests) learn
  where an ephemeral child landed.
- :class:`SupervisorLiveProxy` — the supervisor parent's public
  endpoint: it proxies scrapes to the current child's sidecar and keeps
  answering *across restarts* — while the child is down the last-good
  snapshot is served, marked stale, with the parent's own
  ``supervisor_*`` series (attempt, restarts, child_up) appended so the
  scrape that lands mid-restart is the most informative one, not a
  connection error.

Env arming (the supervisor sets these on its children; any process can
set them by hand)::

    SPARK_EXAMPLES_TPU_LIVE_PORT=0          # start sidecar, ephemeral port
    SPARK_EXAMPLES_TPU_LIVE_PORT_FILE=/p    # write the bound port here
    SPARK_EXAMPLES_TPU_LIVE_ANNOUNCE=/a     # write "host:port" here
"""

from __future__ import annotations

import json
import os
import re
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from spark_examples_tpu.core import telemetry

ENV_PORT = "SPARK_EXAMPLES_TPU_LIVE_PORT"
ENV_PORT_FILE = "SPARK_EXAMPLES_TPU_LIVE_PORT_FILE"
ENV_ANNOUNCE = "SPARK_EXAMPLES_TPU_LIVE_ANNOUNCE"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

# Prometheus quantile labels rendered for each histogram (matching the
# p50/p95/p99 the registry's summaries already compute).
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def _prom_name(name: str) -> str:
    """``serve.latency_s`` -> ``serve_latency_s`` (Prometheus charset)."""
    return _NAME_RE.sub("_", name)


def _esc(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(snap: dict | None = None) -> str:
    """Render a metrics snapshot as Prometheus exposition text.

    ``snap`` defaults to a fresh :func:`telemetry.metrics_snapshot`.
    Deterministic ordering (sorted within each section) so diffs of two
    scrapes are meaningful.
    """
    if snap is None:
        snap = telemetry.metrics_snapshot()
    meta = snap.get("meta") or telemetry._meta(0)
    out: list[str] = []
    out.append("# HELP telemetry_info job identity (labels carry the "
               "stitch keys)")
    out.append("# TYPE telemetry_info gauge")
    out.append(
        'telemetry_info{run_id="%s",attempt="%s",rank="%s"} 1'
        % (_esc(meta["run_id"]), meta["attempt"], meta["rank"]))
    out.append("# TYPE telemetry_uptime_seconds gauge")
    out.append(f"telemetry_uptime_seconds {meta.get('uptime_s', 0.0):.3f}")
    for name, v in sorted(snap.get("counters", {}).items()):
        n = _prom_name(name) + "_total"
        out.append(f"# TYPE {n} counter")
        out.append(f"{n} {v}")
    phases = sorted(snap.get("phases", {}).items())
    if phases:
        out.append("# TYPE phase_seconds_total counter")
    for phase, v in phases:
        out.append('phase_seconds_total{phase="%s"} %s' % (_esc(phase), v))
    for name, g in sorted(snap.get("gauges", {}).items()):
        n = _prom_name(name)
        out.append(f"# TYPE {n} gauge")
        out.append(f"{n} {g.get('last', 0.0)}")
        out.append(f"{n}_min {g.get('min', 0.0)}")
        out.append(f"{n}_max {g.get('max', 0.0)}")
    for name, h in sorted(snap.get("histograms", {}).items()):
        n = _prom_name(name)
        out.append(f"# TYPE {n} summary")
        for label, key in _QUANTILES:
            out.append('%s{quantile="%s"} %s' % (n, label, h.get(key, 0.0)))
        out.append(f"{n}_sum {h.get('sum', 0.0)}")
        out.append(f"{n}_count {h.get('count', 0)}")
    return "\n".join(out) + "\n"


def _reply(handler: BaseHTTPRequestHandler, code: int, body: bytes,
           content_type: str) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def reply_metrics(handler: BaseHTTPRequestHandler) -> None:
    """Serve ``/metrics`` from the live registry (shared by the batch
    sidecar and the serve front)."""
    telemetry.count("live.requests")
    snap = telemetry.metrics_snapshot()
    snap["meta"] = telemetry._meta(0)
    _reply(handler, 200, prometheus_text(snap).encode(),
           "text/plain; version=0.0.4; charset=utf-8")


def reply_debug_telemetry(handler: BaseHTTPRequestHandler) -> None:
    """Serve ``/debug/telemetry`` — the full live snapshot as JSON."""
    telemetry.count("live.requests")
    body = json.dumps(telemetry.live_snapshot(), default=str,
                      sort_keys=True).encode()
    _reply(handler, 200, body, "application/json")


class _SidecarHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # telemetry IS the access log
        pass

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/metrics":
            reply_metrics(self)
        elif self.path == "/debug/telemetry":
            reply_debug_telemetry(self)
        elif self.path == "/healthz":
            telemetry.count("live.requests")
            body = json.dumps({"ok": True, **telemetry.identity(),
                               "pid": os.getpid()}).encode()
            _reply(self, 200, body, "application/json")
        else:
            _reply(self, 404,
                   json.dumps({"error": f"unknown path {self.path!r}"})
                   .encode(), "application/json")


class LiveTelemetryServer:
    """The ``--live-port`` sidecar: bind, serve in a daemon thread,
    publish the bound port, shut down idempotently."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 port_file: str | None = None,
                 announce_path: str | None = None):
        self._httpd = ThreadingHTTPServer((host, port), _SidecarHandler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None
        for path, text in ((port_file, str(self.port)),
                           (announce_path, f"{self.host}:{self.port}")):
            if path:
                telemetry._atomic_write(path, text)

    def serve_in_thread(self) -> "LiveTelemetryServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="live-telemetry-http",
            daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def maybe_start_live(port: int | None = None, host: str = "127.0.0.1",
                     environ=None) -> LiveTelemetryServer | None:
    """Start the sidecar iff asked: an explicit ``port`` (the
    ``--live-port`` flag) or :data:`ENV_PORT` in the environment (the
    supervisor parent arms its children this way, with port 0 + a port
    file so the parent learns where the ephemeral bind landed).
    Returns the running server, or None when nothing asked for one."""
    env = os.environ if environ is None else environ
    if port is None:
        raw = env.get(ENV_PORT, "").strip()
        if not raw:
            return None
        port = int(raw)
    server = LiveTelemetryServer(
        host=host, port=port,
        port_file=env.get(ENV_PORT_FILE, "").strip() or None,
        announce_path=env.get(ENV_ANNOUNCE, "").strip() or None,
    )
    return server.serve_in_thread()


# ---------------------------------------------------------------------------
# Supervisor-side proxy.


class SupervisorLiveProxy:
    """The supervised job's public live endpoint, owned by the parent.

    Scrapes are forwarded to the current child's sidecar (its ephemeral
    port read from ``child_port_file`` on every request — a restarted
    child lands on a new port and the very next scrape follows it). A
    child that is down mid-restart answers with the last-good cached
    body, marked stale, so "is the endpoint up" and "is the child up"
    stay separate questions. Every ``/metrics`` answer appends the
    parent's own ``supervisor_*`` series — the restart visibility no
    child can report about itself.
    """

    def __init__(self, host: str, port: int, child_port_file: str,
                 state_fn, announce_path: str | None = None):
        self.child_port_file = child_port_file
        self.state_fn = state_fn  # () -> dict (attempt/restarts/...)
        self._cache: dict[str, bytes] = {}
        self._cache_type: dict[str, str] = {}
        self._cache_lock = threading.Lock()
        proxy = self

        class _ProxyHandler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802 (stdlib API)
                proxy._handle(self)

        self._httpd = ThreadingHTTPServer((host, port), _ProxyHandler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None
        if announce_path:
            telemetry._atomic_write(announce_path,
                                    f"{self.host}:{self.port}")

    # -- child fetch --------------------------------------------------------

    def _child_port(self) -> int | None:
        try:
            with open(self.child_port_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _fetch_child(self, path: str) -> tuple[bytes, str] | None:
        port = self._child_port()
        if port is None:
            return None
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=2.0) as r:
                body = r.read()
                ctype = r.headers.get("Content-Type", "application/json")
        except Exception:
            return None
        with self._cache_lock:
            self._cache[path] = body
            self._cache_type[path] = ctype
        return body, ctype

    def _cached(self, path: str) -> tuple[bytes, str] | None:
        with self._cache_lock:
            if path in self._cache:
                return self._cache[path], self._cache_type[path]
        return None

    # -- request handling ---------------------------------------------------

    def _supervisor_lines(self, state: dict, child_up: bool,
                          stale: bool) -> str:
        return "\n".join([
            "# TYPE supervisor_restarts counter",
            f"supervisor_restarts {state.get('restarts', 0)}",
            f"supervisor_watchdog_kills {state.get('watchdog_kills', 0)}",
            f"supervisor_attempt {state.get('attempt', 0)}",
            f"supervisor_child_up {int(child_up)}",
            f"supervisor_scrape_stale {int(stale)}",
            'supervisor_info{run_id="%s"} 1' % _esc(state.get("run_id", "")),
        ]) + "\n"

    def _handle(self, handler: BaseHTTPRequestHandler) -> None:
        telemetry.count("live.proxy_requests")
        path = handler.path
        state = self.state_fn()
        if path == "/healthz":
            # The parent answers liveness itself: the proxy being up IS
            # the supervised job being alive (restarting included).
            child_up = self._fetch_child("/healthz") is not None
            _reply(handler, 200,
                   json.dumps({"ok": True, "child_up": child_up,
                               **state}).encode(),
                   "application/json")
            return
        if path not in ("/metrics", "/debug/telemetry"):
            _reply(handler, 404,
                   json.dumps({"error": f"unknown path {path!r}"}).encode(),
                   "application/json")
            return
        got = self._fetch_child(path)
        stale = got is None
        if stale:
            telemetry.count("live.proxy_stale")
            got = self._cached(path)
        if path == "/metrics":
            body = got[0].decode(errors="replace") if got else ""
            body += self._supervisor_lines(state, child_up=not stale,
                                           stale=stale)
            _reply(handler, 200, body.encode(),
                   "text/plain; version=0.0.4; charset=utf-8")
            return
        child_payload = None
        if got is not None:
            try:
                child_payload = json.loads(got[0])
            except ValueError:
                child_payload = None
        _reply(handler, 200, json.dumps({
            "supervisor": state,
            "stale": stale,
            "child": child_payload,
        }, default=str).encode(), "application/json")

    # -- lifecycle ----------------------------------------------------------

    def serve_in_thread(self) -> "SupervisorLiveProxy":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="supervisor-live-proxy",
            daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

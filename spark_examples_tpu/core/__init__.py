from spark_examples_tpu.core import config, dtypes, meshes, profiling  # noqa: F401

"""Core subpackage.

Submodules are resolved lazily (PEP 562): ``core.dtypes`` /
``core.meshes`` / ``core.profiling`` import jax at module level, and an
eager re-export here would put a jax runtime (and on TPU, the chip
lock) into every process that touches ANY core module — including the
supervised CLI parent, config-time validation, and graftlint, which are
all contractually device-free (graftlint: jax-import-purity; the eager
form was found by that rule's first run over the tree)."""

import importlib

_SUBMODULES = ("checkpoint", "config", "dtypes", "faults", "hashing",
               "live", "meshes", "profiling", "sidecar", "stitch",
               "supervisor", "telemetry", "virtual")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES))
